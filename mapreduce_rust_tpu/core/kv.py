"""KVBatch — the struct-of-arrays intermediate record batch.

Replaces the reference's ``KeyValue { key: String, value: String }``
(src/lib.rs:9-23). Strings cannot live in fixed-shape device memory, so the
universal intermediate record on TPU is a padded struct of arrays:

    k1, k2 : uint32[N]  — the 64-bit-equivalent key hash pair
    value  : int32[N]   — app payload (count=1 for word_count, doc_id for
                          inverted_index, ...)
    valid  : bool[N]    — padding/liveness mask

The reference's KeyValue deliberately does *not* derive Serialize
(src/lib.rs:9) — pairs can never cross the RPC plane and move only through
files. The same invariant holds here: KVBatch never crosses the control
plane; it moves between chips only via ICI collectives (parallel/shuffle.py,
planned).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from mapreduce_rust_tpu.core.hashing import SENTINEL


class KVBatch(NamedTuple):
    """Padded batch of (key-hash-pair, value) records. A JAX pytree."""

    k1: jnp.ndarray  # uint32[N]
    k2: jnp.ndarray  # uint32[N]
    value: jnp.ndarray  # int32[N]
    valid: jnp.ndarray  # bool[N]

    @property
    def capacity(self) -> int:
        return self.k1.shape[-1]

    def take_front(self, n: int) -> "KVBatch":
        """First n slots. Reduce outputs are front-packed (ops/groupby.py),
        so this is the compaction primitive for partial/update batches."""
        return KVBatch(self.k1[:n], self.k2[:n], self.value[:n], self.valid[:n])

    @staticmethod
    def empty(capacity: int) -> "KVBatch":
        return KVBatch(
            k1=jnp.full((capacity,), SENTINEL, dtype=jnp.uint32),
            k2=jnp.full((capacity,), SENTINEL, dtype=jnp.uint32),
            value=jnp.zeros((capacity,), dtype=jnp.int32),
            valid=jnp.zeros((capacity,), dtype=bool),
        )

    @staticmethod
    def from_host(keys: np.ndarray, values: np.ndarray, capacity: int | None = None) -> "KVBatch":
        """Build a batch from host arrays: keys uint32[n,2], values int32[n]."""
        n = keys.shape[0]
        cap = capacity or n
        if n > cap:
            raise ValueError(f"{n} records exceed capacity {cap}")
        k1 = np.full((cap,), SENTINEL, dtype=np.uint32)
        k2 = np.full((cap,), SENTINEL, dtype=np.uint32)
        val = np.zeros((cap,), dtype=np.int32)
        ok = np.zeros((cap,), dtype=bool)
        k1[:n] = keys[:, 0]
        k2[:n] = keys[:, 1]
        val[:n] = values
        ok[:n] = True
        return KVBatch(jnp.asarray(k1), jnp.asarray(k2), jnp.asarray(val), jnp.asarray(ok))

    def to_host(self) -> tuple[np.ndarray, np.ndarray]:
        """Return (keys uint32[n,2], values int32[n]) for valid records only.

        One batched device_get for all four fields — four separate
        np.asarray calls would be four device→host round trips, and through
        a tunneled TPU each round trip is ~80 ms.
        """
        import jax

        k1, k2, value, valid = (
            np.asarray(x)
            for x in jax.device_get((self.k1, self.k2, self.value, self.valid))
        )
        keys = np.stack([k1[valid], k2[valid]], axis=1)
        return keys, value[valid]
