"""Host-side unicode normalization for ingest.

The reference word-count app strips ``[^\\w\\s]`` with a Unicode-aware Rust
regex and splits on Unicode whitespace (src/app/wc.rs:6-13 via regex 1.9,
``split_whitespace``). The device kernel (ops/tokenize.py) classifies raw
*bytes* and treats every byte >= 0x80 as a word char, which is correct for
UTF-8 letters but wrong for non-ASCII punctuation ("don’t" must hash as
"dont", an em dash must vanish) and non-ASCII whitespace (U+00A0 must split
words).

This pass runs once on the host before bytes reach the chunker: every
non-ASCII codepoint is classified with Python's unicode-aware ``re`` (the
same UTS#18 word definition Rust's regex crate uses) —

- word chars (``\\w``: letters, digits, marks, underscore) are kept verbatim,
  so their UTF-8 bytes still read as word chars on device;
- whitespace becomes an ASCII space (token boundary);
- everything else is deleted in place, which — exactly like the reference's
  regex strip — does NOT split the surrounding token.

After normalization the byte stream contains non-ASCII bytes only inside
genuine words, so the device byte-class tables are exact.

ASCII bytes are never touched here; the device tables already match the
reference for ASCII (tests/test_tokenize.py).

Known divergence (accepted): Python's ``re`` word class excludes combining
marks (``\\p{M}``) while Rust's regex crate (UTS#18) includes them, so
e.g. U+0338 inside a word is deleted here but kept by the reference —
2 occurrences in the whole 4.11 MB reference corpus. Invalid UTF-8 decodes
to U+FFFD which is non-word and is deleted (the reference's
``read_to_string`` would instead fail the task).
"""

from __future__ import annotations

import functools
import re

import numpy as np

_WORD_RE = re.compile(r"\w", re.UNICODE)


@functools.lru_cache(maxsize=4096)
def _classify(cp: int) -> int | None:
    """Translation entry for one non-ASCII codepoint.

    None   -> keep (word char)
    0x20   -> replace with space (whitespace)
    -1     -> delete (punctuation/symbol), encoded as '' for str.translate
    """
    ch = chr(cp)
    if _WORD_RE.match(ch):
        return None
    if ch.isspace():
        return 0x20
    return -1


def _normalize_text(text: str) -> bytes:
    """Classify-and-translate a decoded string (the full slow path)."""
    cps = np.unique(np.frombuffer(text.encode("utf-32-le"), dtype=np.uint32))
    table: dict[int, int | str | None] = {}
    for cp in cps[cps >= 0x80].tolist():
        cls = _classify(cp)
        if cls is not None:
            table[cp] = "" if cls == -1 else " "
    if not table:
        return text.encode("utf-8")
    return text.translate(table).encode("utf-8")


@functools.lru_cache(maxsize=65536)
def _normalize_run(run: bytes) -> bytes:
    """Normalize one short contiguous non-ASCII byte run. Real text repeats
    a handful of sequences (curly quotes, dashes, accented letters), so the
    cache turns per-run work into a dict hit."""
    return _normalize_text(run.decode("utf-8", errors="replace"))


_RUN_CACHE_MAX_LEN = 64


def normalize_unicode(data: bytes) -> bytes:
    """Normalize a UTF-8 byte string for the device tokenizer.

    Pure-ASCII input is returned unchanged. Otherwise only the contiguous
    non-ASCII byte runs are rewritten (UTF-8 lead AND continuation bytes
    are all >= 0x80, so a run always covers whole sequences); the ASCII
    spans between them — the overwhelming majority of real corpora — are
    passed through by slicing at memcpy speed. Short runs hit an LRU cache;
    pathological long runs (dense non-Latin text) fall back to the full
    decode+translate pass per run.
    """
    if data.isascii():
        return data
    from mapreduce_rust_tpu.native.host import normalize_native

    native = normalize_native(data)
    if native is not None:
        return native
    return _normalize_python(data)


def _normalize_python(data: bytes) -> bytes:
    """The pure-Python normalization pass (fallback + native parity oracle)."""
    arr = np.frombuffer(data, dtype=np.uint8)
    idx = np.flatnonzero(arr >= 0x80)
    # Split the non-ASCII byte positions into contiguous runs.
    breaks = np.flatnonzero(np.diff(idx) > 1) + 1
    starts = np.concatenate([idx[:1], idx[breaks]])
    ends = np.concatenate([idx[breaks - 1] + 1, idx[-1:] + 1])
    parts: list[bytes] = []
    pos = 0
    for s, e in zip(starts.tolist(), ends.tolist()):
        parts.append(data[pos:s])
        run = data[s:e]
        if e - s <= _RUN_CACHE_MAX_LEN:
            parts.append(_normalize_run(run))
        else:
            parts.append(_normalize_text(run.decode("utf-8", errors="replace")))
        pos = e
    parts.append(data[pos:])
    return b"".join(parts)


def reference_word_counts(data: bytes):
    """The golden oracle: word -> count with the reference's exact semantics.

    Mirrors src/app/wc.rs:6-13 — delete ``[^\\w\\s]`` (unicode-aware, no
    token split), then split on unicode whitespace; case-sensitive. Used by
    end-to-end tests; never by the production path.
    """
    from collections import Counter

    text = data.decode("utf-8", errors="replace")
    cleaned = re.sub(r"[^\w\s]", "", text, flags=re.UNICODE)
    return Counter(cleaned.split())
