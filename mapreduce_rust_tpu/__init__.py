"""mapreduce_rust_tpu — a TPU-native MapReduce framework.

A from-scratch rebuild of the capabilities of Freebirdgo/MapReduce_Rust
(coordinator/worker runtime, lease-based fault tolerance, hash-partitioned
shuffle, sort-and-group reduce, pluggable map/reduce apps) designed TPU-first:

- Data plane: JAX/XLA. Tokenize→hash runs on-chip over padded uint8 byte
  arrays (segmented associative-scan polynomial hashing), the shuffle is a
  ``lax.all_to_all`` over ICI inside ``shard_map``, and the group-by reduce is
  ``lax.sort`` + ``segment_sum``. Strings exist only at ingest/egress.
- Control plane: a small asyncio JSON-RPC coordinator preserving the
  reference's scheduler semantics (worker registration barrier, -1/-2/-3
  task sentinels, leases with expiry + re-execution) — see
  ``mapreduce_rust_tpu.coordinator``.

Reference behavior parity is cited per-module against /root/reference
(Freebirdgo/MapReduce_Rust) as ``file:line``.
"""

__version__ = "0.1.0"

from mapreduce_rust_tpu.config import Config  # noqa: F401
