"""Chunk-level data provenance ledger (ISSUE 20) — the mrlineage plane.

The paper's one durable architectural decision is that all data moves
through files while the control plane carries only ids — so every output
partition is, in principle, an exactly attributable function of input
chunks, spill runs and attempts. This module makes that attribution a
recorded fact instead of a principle: an opt-in ledger
(``Config.lineage`` / ``--lineage`` / ``MR_LINEAGE=1``, off by default)
writes one torn-tail-safe jsonl record per ingest chunk — a blake2b
content digest computed in the scan thread where the bytes are already
hot, plus the reduce partitions the chunk's (masked) keys route to — and
per-partition claim records at egress, riding the same ``part_bytes``
bookkeeping the coordinator already ships.

Contracts (the prof.py plane doctrine):
- **Observational only.** Nothing the data plane reads is touched, so
  outputs are bit-identical lineage ON vs OFF; the tax is gated ≤2% by
  bench's ``--lineage-overhead`` interleaved pair.
- **Crash-durable.** Records are flushed line-by-line (the reader pops a
  torn tail, like the coordinator journal's parser) and the flight
  recorder embeds the in-memory tail in every ``*.partial.json``, so a
  SIGKILLed run keeps its provenance and backward queries still resolve.
- **One digest seam.** ``chunk_digest`` (content) and
  ``corpus_fingerprint`` (the (name, size, mtime) metadata digest the
  service's ``scan_corpus`` cache key uses) live HERE; mrlint rule
  ``ad-hoc-corpus-digest`` flags any second digest function over the
  same bytes — two formulas for one corpus is exactly the cache-key
  drift ROADMAP item 4's memo tier cannot survive.

Record schema (``{work}/lineage.jsonl``, one JSON object per line):
  {"t":"start", "schema":1, "corpus_meta_digest", "corpus_bytes",
   "reduce_n", "inputs":[basenames], "pid"}
  {"t":"chunk", "seq", "doc", "bytes", "dg", "parts":[r, ...]}
  {"t":"attempt", "phase":"map", "tid", "attempt", "wid",
   "chunks":[dg, ...], "part_bytes":[...]}        (cluster runs: the
   coordinator appends one per finish REPORT — late duplicates too,
   which is what gives mrcheck's re-execution-equality check teeth)
  {"t":"part", "r", "bytes", "chunks":[dg, ...]}  (claims at egress)
  {"t":"end", "chunks", "bytes", "corpus_digest", "partition_bytes"}

``corpus_digest`` is the ordered fold of the per-chunk content digests —
a pure function of (input bytes, window policy), identical across every
(host_map_workers, fold_shards) combination and across the driver and
worker engines, which is what makes it a memo-tier cache key.

jax-free on purpose: ``analysis/lineage.py`` (the query/diff CLI) and the
service import this module in processes that never initialize a backend.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

#: Content-digest width. 16 bytes of blake2b: collision-safe for any
#: plausible corpus (2^64 chunks to a birthday collision) at half the
#: ledger bytes of the full 32.
DIGEST_SIZE = 16

#: Ledger file name inside a job's work dir — shared by the driver
#: ledger, the coordinator's cluster appends, mrcheck's pass and the CLI.
LEDGER_NAME = "lineage.jsonl"

SCHEMA = 1


def lineage_forced() -> bool:
    """``MR_LINEAGE`` — process-tree opt-in to the provenance ledger
    (the MR_PROFILE enablement pattern): a fleet worker or SIGKILL-test
    subprocess inherits lineage without plumbing a flag through argv."""
    return os.environ.get("MR_LINEAGE", "").strip().lower() in (
        "1", "true", "on", "yes"
    )


#: chunk_digest hashes every byte up to this size (cluster chunks and
#: small windows: exact content identity). Above it — the driver's
#: multi-MB ingest windows — it hashes a deterministic SAMPLE: the size,
#: both 64 KiB edges, and 16 strided 8 KiB interior blocks (~256 KiB a
#: window). The sample is a pure function of the bytes, so digests stay
#: reproducible and comparable; what it trades away is detection of a
#: same-length in-place edit that dodges every sampled byte. That trade
#: is what keeps the ledger inside bench's ≤2% wall contract on a
#: CPU-saturated host (a full blake2b of every window byte costs more
#: than the 2% budget on any box whose scan runs near the hash's own
#: speed) — and the common corpus edits (append, truncate, touch a
#: file's head) all move the size or an edge, so the blast-radius diff
#: sees them exactly. A memo tier wanting hard guarantees pairs this
#: content tier with the header's (size, mtime) corpus_fingerprint.
FULL_DIGEST_MAX = 1 << 20
_SAMPLE_EDGE = 64 << 10
_SAMPLE_BLOCKS = 16
_SAMPLE_BLOCK = 8 << 10


def chunk_digest(data) -> str:
    """blake2b content digest of one chunk/window's RAW bytes — full
    content at or below FULL_DIGEST_MAX, sampled (size + edges + strided
    interior) above. Accepts bytes or any contiguous buffer (a zero-copy
    memmap window view) — called from the scan thread, where the bytes
    are already hot in cache, so the hash rides the scan's memory
    traffic instead of re-faulting the corpus."""
    view = memoryview(data)
    if view.ndim != 1 or view.itemsize != 1:
        view = view.cast("B")
    n = view.nbytes
    if n <= FULL_DIGEST_MAX:
        return hashlib.blake2b(view, digest_size=DIGEST_SIZE).hexdigest()
    h = hashlib.blake2b(digest_size=DIGEST_SIZE)
    h.update(n.to_bytes(8, "little"))
    h.update(view[:_SAMPLE_EDGE])
    h.update(view[n - _SAMPLE_EDGE:])
    stride = (n - 2 * _SAMPLE_EDGE) // _SAMPLE_BLOCKS
    for i in range(_SAMPLE_BLOCKS):
        off = _SAMPLE_EDGE + i * stride
        h.update(view[off:off + _SAMPLE_BLOCK])
    return h.hexdigest()


def fold_digests(digests) -> str:
    """Ordered fold of per-chunk content digests into one corpus content
    digest — the memo-tier cache key. Order-sensitive on purpose: the
    chunk sequence is part of the corpus identity (doc ids are
    positional)."""
    h = hashlib.blake2b(digest_size=DIGEST_SIZE)
    for dg in digests:
        h.update(bytes.fromhex(dg) if isinstance(dg, str) else dg)
    return h.hexdigest()


def corpus_fingerprint(paths) -> "tuple[str, int]":
    """(metadata digest, total bytes) over an ordered path list — the
    (basename, size, mtime_ns) fingerprint the service's ``scan_corpus``
    uses as its result-cache corpus key and the per-job journal header
    uses for resume identity. ONE formula, defined here, imported there:
    the finalize cross-check compares the ledger header's copy against
    the cache key's, and they can only agree because they are the same
    function."""
    sig = hashlib.sha256()
    total = 0
    for p in paths:
        try:
            st = os.stat(p)
            total += st.st_size
            sig.update(
                f"{os.path.basename(p)}:{st.st_size}:{st.st_mtime_ns};".encode()
            )
        except OSError:
            sig.update(f"{os.path.basename(p)}:gone;".encode())
    return sig.hexdigest()[:16], total


def append_record(path: str, rec: dict) -> None:
    """Append one ledger record — the coordinator's (cluster-mode) write
    path: no process-global ledger, just the shared line format. Append
    + flush per record keeps the file torn-tail-safe under SIGKILL; the
    reader distrusts an unterminated last line."""
    with open(path, "a") as f:
        f.write(json.dumps(rec, separators=(",", ":")) + "\n")


class LineageLedger:
    """The driver-side provenance ledger: per-chunk content digests +
    partition routing recorded in chunk order, per-partition claims at
    egress, a running ordered digest fold, and a line-buffered jsonl
    file that survives SIGKILL mid-run.

    Thread contract: digests are COMPUTED on scan threads (pure), but
    records are appended from each engine's single consumer/router
    thread — the lock below is belt-and-braces for embedders, not a
    hot-path serialization point."""

    #: In-memory record cap for flight-recorder partial embeds: the tail
    #: a partial carries stays bounded however long the run (the full
    #: history is on disk in the jsonl).
    TAIL_CAP = 8192

    def __init__(self, path: str, inputs=(), reduce_n: int = 0) -> None:
        self.path = path
        self.reduce_n = int(reduce_n)
        self._lock = threading.Lock()
        self._seq = 0
        self._bytes = 0
        self._fold = hashlib.blake2b(digest_size=DIGEST_SIZE)
        self._chunks: list[dict] = []      # tail (capped) for partials
        self._chunk_parts: list[list] = [] # FULL parts index (ints only)
        self._digests: list[str] = []      # FULL ordered digest list
        self._partition_bytes: dict[int, int] = {}
        self._dropped = 0
        self._closed = False
        meta_dg, corpus_bytes = corpus_fingerprint(inputs)
        self.header = {
            "t": "start", "schema": SCHEMA,
            "corpus_meta_digest": meta_dg,
            "corpus_bytes": corpus_bytes,
            "reduce_n": self.reduce_n,
            "inputs": [os.path.basename(p) for p in inputs],
            "pid": os.getpid(),
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # Truncate: a fresh run in a reused work dir must not append to a
        # previous job's provenance (the coordinator-journal doctrine).
        self._f = open(path, "w")
        self.submit(self.header)

    # ---- recording ----

    def submit(self, rec: dict) -> None:
        """The ledger's emit seam — a sync-mode plane handoff (the
        rule-13/14 doctrine AsyncSpillWriter and _DispatchPlane share):
        the fold/consumer hot scopes hand a frozen record here and this
        plane owns what happens below. It runs inline on purpose —
        write + flush per line is what makes the file torn-tail-safe
        under SIGKILL, and the ledger is an explicit opt-in measurement
        path whose tax bench gates at ≤2% (--lineage-overhead)."""
        self._f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._f.flush()

    def record_chunk(self, doc_id: int, nbytes: int, digest: str,
                     parts=None) -> int:
        """One ingest chunk/window, in stream order (the engines' single
        consumer thread): content digest + the reduce partitions its
        masked keys route to. Returns the chunk's ledger seq."""
        parts = [int(r) for r in parts] if parts is not None else []
        with self._lock:
            seq = self._seq
            self._seq += 1
            self._bytes += int(nbytes)
            self._fold.update(bytes.fromhex(digest))
            self._digests.append(digest)
            self._chunk_parts.append(parts)
            rec = {"t": "chunk", "seq": seq, "doc": int(doc_id),
                   "bytes": int(nbytes), "dg": digest, "parts": parts}
            if len(self._chunks) < self.TAIL_CAP:
                self._chunks.append(rec)
            else:
                self._dropped += 1
            self.submit(rec)
        return seq

    def record_partition(self, r: int, nbytes: int) -> None:
        """One reduce partition's egress claim: its output bytes (the
        part_bytes path's number) plus the digests of every chunk whose
        routed keys contributed — mrcheck's lineage-conservation pass
        checks this claim set ⊆ the scanned set."""
        with self._lock:
            claims = [self._digests[i]
                      for i, ps in enumerate(self._chunk_parts) if r in ps]
            self._partition_bytes[int(r)] = int(nbytes)
            self.submit({"t": "part", "r": int(r), "bytes": int(nbytes),
                         "chunks": claims})

    def close(self) -> None:
        """Write the end summary (folded corpus content digest — the
        memo-tier key) and release the file. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self.submit(self.end_dict())
            self._f.close()

    # ---- views ----

    def corpus_digest(self) -> str:
        return self._fold.copy().hexdigest()

    def end_dict(self) -> dict:
        return {
            "t": "end", "chunks": self._seq, "bytes": self._bytes,
            "corpus_digest": self.corpus_digest(),
            "partition_bytes": [
                self._partition_bytes.get(r, 0)
                for r in range(max(self.reduce_n,
                                   len(self._partition_bytes)))
            ],
        }

    def lineage_dict(self) -> dict:
        """Manifest summary block (``stats.lineage``): counts + digests,
        never the per-chunk records — those live in the jsonl, whose
        path this names for the CLI."""
        with self._lock:
            return {
                "schema": SCHEMA,
                "chunks": self._seq,
                "bytes": self._bytes,
                "corpus_digest": self.corpus_digest(),
                "corpus_meta_digest": self.header["corpus_meta_digest"],
                "reduce_n": self.reduce_n,
                "path": self.path,
            }

    def tail_dict(self) -> dict:
        """Flight-recorder partial embed: header + the capped record
        tail + the running fold, enough for backward queries to resolve
        on a SIGKILLed run even if the jsonl itself is lost."""
        with self._lock:
            return {
                "schema": SCHEMA,
                "header": dict(self.header),
                "chunks": self._seq,
                "bytes": self._bytes,
                "corpus_digest": self.corpus_digest(),
                "records": list(self._chunks),
                "records_dropped": self._dropped,
            }


# ---------------------------------------------------------------------------
# Process-global lifecycle — the prof.py start/stop/active doctrine: one
# slot, compare-and-clear on stop (an in-process co-hosted job may have
# replaced it), build_manifest reads the still-active instance.
# ---------------------------------------------------------------------------

_ledger: "LineageLedger | None" = None
_ledger_lock = threading.Lock()


def start_ledger(path: str, inputs=(), reduce_n: int = 0) -> LineageLedger:
    global _ledger
    led = LineageLedger(path, inputs=inputs, reduce_n=reduce_n)
    with _ledger_lock:
        _ledger = led
    return led


def stop_ledger(expected: "LineageLedger | None" = None) -> None:
    """Close + clear the global slot. Compare-and-clear: only clears if
    the slot still holds ``expected`` (or unconditionally when None)."""
    global _ledger
    with _ledger_lock:
        led = _ledger
        if expected is not None and led is not expected:
            expected.close()
            return
        _ledger = None
    if led is not None:
        led.close()


def active_ledger() -> "LineageLedger | None":
    return _ledger
