"""Host-side ingest: files → fixed-shape, whitespace-aligned byte chunks.

Replaces the reference's ``read_file_to_mem_map`` (src/mr/worker.rs:65-77),
which slurps one whole input file into a single ``String`` — its de-facto
sequence-length ceiling. Here each file is normalized (core/normalize.py)
and streamed as fixed-size uint8 chunks:

- every chunk is exactly ``chunk_bytes`` long (space-padded), so the device
  kernels compile once and are reused for the whole corpus;
- chunks are cut at whitespace boundaries, so no token ever straddles a
  chunk edge and per-chunk counts sum exactly to whole-corpus counts
  (the reference gets the same guarantee trivially: one file = one task,
  src/mr/worker.rs:67). The one exception — a single token longer than
  ``chunk_bytes`` — is force-split and *counted* in ``Chunk.forced_cut``,
  like every other lossy path in this codebase (merge/bucket overflow);
- normalization and chunking run over a bounded sliding window, so peak
  host memory is O(window), not O(file);
- a chunk belongs to exactly one document (doc_id = input file index),
  which is what apps/inverted_index.py (planned) needs.

The pure-device alternative for sharded byte streams (cut anywhere, fix up
boundary tokens with a ppermute halo) lives in parallel/halo.py (planned).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Iterator, Sequence

import numpy as np

from mapreduce_rust_tpu.core.hashing import WHITESPACE_BYTES
from mapreduce_rust_tpu.core.normalize import normalize_unicode

_ASCII_WS = frozenset(WHITESPACE_BYTES)


@dataclasses.dataclass(frozen=True)
class Chunk:
    """One device-ready chunk: uint8[chunk_bytes], space padded."""

    doc_id: int  # input file index (GLOBAL across corpora, ISSUE 15)
    seq: int  # chunk index within the document
    data: np.ndarray  # uint8[chunk_bytes]
    nbytes: int  # real payload length before padding
    forced_cut: bool = False  # True: chunk END was cut mid-token (token > chunk_bytes)
    corpus: int = 0  # which named corpus this chunk's document belongs to
    # (multi-corpus input API): index into Config.corpora(). Redundant
    # with doc_id + the job's corpus bounds — the authoritative mapping
    # apps consume via App.corpus_bounds — but tagged here so ingest-side
    # consumers never re-derive the boundary arithmetic.


def _ws_cut(data: bytes, start: int, end: int) -> tuple[int, bool]:
    """Largest cut <= end with data[cut-1] whitespace; (end, True) if none."""
    cut = end
    while cut > start and data[cut - 1] not in _ASCII_WS:
        cut -= 1
    if cut == start:
        return end, True
    return cut, False


def utf8_safe_cut(data: bytes, cut: int) -> int:
    """Largest cut' <= cut that does not split a UTF-8 sequence: back off
    past trailing continuation bytes and their lead byte (a complete
    trailing sequence also moves whole past the cut). Shared force-cut
    policy of every byte-stream splitter (chunk_stream, the host engine's
    window iterator) so the engines can never diverge on it."""
    while cut > 1 and (data[cut - 1] & 0xC0) == 0x80:
        cut -= 1
    if cut > 1 and data[cut - 1] >= 0xC0:
        cut -= 1
    return cut


def split_points(data: bytes, chunk_bytes: int) -> list[tuple[int, int, bool]]:
    """(start, end, forced) payload spans, each <= chunk_bytes.

    The cut is placed after the last whitespace byte in the window so the
    trailing partial token moves whole into the next chunk; ``forced`` marks
    mid-token cuts (token longer than chunk_bytes — the fragments count as
    separate words and the caller must surface the event).
    """
    spans = []
    n = len(data)
    start = 0
    while start < n:
        end = min(start + chunk_bytes, n)
        forced = False
        if end < n:
            end, forced = _ws_cut(data, start, end)
        spans.append((start, end, forced))
        start = end
    return spans


def _emit(data: bytes, start: int, end: int, forced: bool, doc_id: int, seq: int, chunk_bytes: int) -> Chunk:
    buf = np.full(chunk_bytes, 0x20, dtype=np.uint8)
    buf[: end - start] = np.frombuffer(data[start:end], dtype=np.uint8)
    return Chunk(doc_id=doc_id, seq=seq, data=buf, nbytes=end - start, forced_cut=forced)


def chunk_stream(
    f,
    doc_id: int,
    chunk_bytes: int,
    normalize: bool = True,
    window_bytes: int | None = None,
) -> Iterator[Chunk]:
    """Stream one document from a binary file object, one window at a time.

    Each ~window_bytes read is cut at ASCII whitespace — safe before
    normalization because normalize_unicode never alters ASCII bytes, so an
    ASCII-whitespace cut is a token boundary in both the raw and normalized
    streams. The raw tail past the cut carries into the next read, and the
    trailing partial *chunk* carries likewise, so emitted chunks are
    identical to whole-file processing while peak host memory is O(window)
    — never O(file), contrast src/mr/worker.rs:73-76.

    normalize=False skips unicode normalization (raw byte passthrough for
    ASCII-only or pre-normalized input).
    """
    window = window_bytes or max(chunk_bytes * 8, 1 << 24)
    seq = 0
    pending = b""    # normalized bytes whose chunk cut isn't final yet
    raw_carry = b""  # raw bytes past the window's whitespace cut
    while True:
        piece = f.read(window)
        at_eof = not piece
        buf = raw_carry + piece
        raw_carry = b""
        if not at_eof and buf:
            cut, forced_window = _ws_cut(buf, 0, len(buf))
            if forced_window:
                # No whitespace in the whole window: cut anyway, but at a
                # UTF-8 sequence boundary so per-window normalization
                # matches whole-file normalization byte for byte.
                cut = utf8_safe_cut(buf, cut)
            raw_carry = buf[cut:]
            buf = buf[:cut]
        data = pending + (normalize_unicode(buf) if normalize else buf)
        pending = b""
        spans = split_points(data, chunk_bytes)
        if not at_eof and spans:
            # The last span's cut decision isn't final until the following
            # bytes are known — carry it into the next window.
            *spans, last = spans
            pending = data[last[0] :]
        for start, end, forced in spans:
            yield _emit(data, start, end, forced, doc_id, seq, chunk_bytes)
            seq += 1
        if at_eof:
            return


def chunk_document(
    raw: bytes,
    doc_id: int,
    chunk_bytes: int,
    normalize: bool = True,
    window_bytes: int | None = None,
) -> Iterator[Chunk]:
    """chunk_stream over an in-memory document."""
    import io

    yield from chunk_stream(io.BytesIO(raw), doc_id, chunk_bytes, normalize, window_bytes)


def iter_chunks(
    paths: Sequence[str | os.PathLike], chunk_bytes: int,
    corpus_bounds: Sequence[int] = (),
) -> Iterator[Chunk]:
    """Stream all input files as chunks, doc_id = position in ``paths``.

    Reads and normalizes incrementally — peak host memory is one window,
    not the corpus (contrast src/mr/worker.rs:73-76). With
    ``corpus_bounds`` (resolve_corpora), each chunk is tagged with its
    document's corpus id.
    """
    import bisect

    bounds = list(corpus_bounds or ())
    for doc_id, path in enumerate(paths):
        corpus = bisect.bisect_right(bounds, doc_id) if bounds else 0
        with open(path, "rb") as f:
            for c in chunk_stream(f, doc_id, chunk_bytes):
                yield (dataclasses.replace(c, corpus=corpus)
                       if corpus else c)


def list_inputs(input_dir: str, pattern: str = "*.txt") -> list[str]:
    """Sorted input file list — the doc_id ordering contract."""
    import glob

    return sorted(glob.glob(os.path.join(input_dir, pattern)))


def parse_input_spec(values: Sequence[str]):
    """The CLI's ``--input`` forms → (input_dir, input_dirs):

    - ``--input DIR`` — classic single corpus: (DIR, None). ONE value is
      always this form, '=' in the path included;
    - ``--input a=DIR b=DIR`` — N (>= 2) named corpora, canonically
      sorted by name (the submission-digest and join-side ordering
      contract: ``a=X b=Y`` and ``b=Y a=X`` are the SAME job):
      (first dir, sorted ((name, dir), ...)).

    Mixing the two forms (or repeating a name) is a usage error.
    """
    vals = list(values)
    if len(vals) == 1:
        # ONE value is always the classic directory form — even when the
        # path contains '=' (a legal dir name like data/run=5). A single
        # NAMED corpus would be pointless anyway: names only distinguish
        # sides once there are two.
        return vals[0], None
    pairs = []
    for v in vals:
        name, sep, d = v.partition("=")
        if not sep or not name or not d:
            raise ValueError(
                f"multi-corpus --input wants name=DIR entries, got {v!r} "
                "(single-corpus form takes exactly one bare DIR)"
            )
        pairs.append((name, d))
    names = [n for n, _ in pairs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate corpus names in --input: {names}")
    pairs.sort()
    return pairs[0][1], tuple(pairs)


def resolve_corpora(cfg) -> tuple[list[str], tuple, tuple]:
    """Flatten the job's corpora (Config.corpora()) into the doc_id
    space: (inputs, corpus_bounds, names). ``inputs`` concatenates each
    corpus's sorted listing in corpus order; ``corpus_bounds`` holds the
    cumulative doc counts of corpora[:-1] — the boundaries
    splitter.prepare_app binds onto multi-corpus apps. Single-corpus
    configs come back with bounds == () so every classic caller keeps
    flat-list semantics."""
    corpora = cfg.corpora()
    inputs: list[str] = []
    bounds: list[int] = []
    for name, d in corpora:
        inputs.extend(list_inputs(d, cfg.input_pattern))
        bounds.append(len(inputs))
    names = tuple(n for n, _ in corpora)
    if len(corpora) == 1:
        return inputs, (), names
    return inputs, tuple(bounds[:-1]), names
