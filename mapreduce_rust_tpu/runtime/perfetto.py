"""Perfetto ``track_event`` protobuf export — ``trace merge --format
perfetto`` (the long-carried PR 4 ROADMAP leftover).

Why: the JSON trace-event format is ideal for small timelines, but a
multi-job coordinator's merged timeline crosses 100 MB and the Perfetto
UI's JSON ingestion path (parse the whole document, then convert) falls
over long before its native protobuf path does. The ``.pftrace`` binary
stream loads incrementally and is ~3-5x smaller.

Why hand-rolled: the container ships no protobuf library and the bake-in
rule forbids adding one. Proto wire format is three primitives — varints,
length-delimited blobs, fixed64 — so the writer below encodes exactly the
message subset Perfetto's TrackEvent model needs, with the field numbers
pinned from perfetto's ``trace_packet.proto``/``track_event.proto``:

- ``Trace.packet = 1``
- ``TracePacket``: ``timestamp = 8`` (ns, varint),
  ``trusted_packet_sequence_id = 10``, ``track_event = 11``,
  ``track_descriptor = 60``
- ``TrackDescriptor``: ``uuid = 1``, ``name = 2``, ``process = 3``,
  ``thread = 4``, ``parent_uuid = 5``, ``counter = 8``
- ``ProcessDescriptor``: ``pid = 1``, ``process_name = 6``
- ``ThreadDescriptor``: ``pid = 1``, ``tid = 2``, ``thread_name = 5``
- ``TrackEvent``: ``type = 9`` (SLICE_BEGIN=1, SLICE_END=2, INSTANT=3,
  COUNTER=4), ``track_uuid = 11``, ``name = 23``, ``counter_value = 30``,
  ``double_counter_value = 44``, ``flow_ids = 47`` /
  ``terminating_flow_ids = 48`` (fixed64)

Input is the MERGED Chrome event list ``trace.merge_traces`` builds (and
validates) — "X" spans become BEGIN/END pairs emitted in correct nesting
order per track, instants and flows become INSTANT events carrying flow
ids, "C" counters become per-key counter tracks, and the "M"
``process_name`` rows become ProcessDescriptors. A minimal wire-format
reader (``iter_packets``) rides along so tests (and humans) can re-parse
the emitted stream without a protobuf dependency.

Pure stdlib, no jax — same rule as every trace/analysis tool.
"""

from __future__ import annotations

import hashlib
import os
import struct

#: One synthetic writer sequence: we emit absolute timestamps (no
#: interning, no incremental state), so a single sequence id is valid.
_SEQ_ID = 1

TYPE_SLICE_BEGIN = 1
TYPE_SLICE_END = 2
TYPE_INSTANT = 3
TYPE_COUNTER = 4


# ---------------------------------------------------------------------------
# Wire-format primitives
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    n &= (1 << 64) - 1  # proto uint64 wraparound for negative ints
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire_type: int) -> bytes:
    return _varint((field << 3) | wire_type)


def _f_varint(field: int, n: int) -> bytes:
    return _key(field, 0) + _varint(int(n))


def _f_bytes(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _f_str(field: int, s: str) -> bytes:
    return _f_bytes(field, s.encode("utf-8", "replace"))


def _f_double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", float(v))


def _f_fixed64(field: int, n: int) -> bytes:
    return _key(field, 1) + struct.pack("<Q", n & ((1 << 64) - 1))


def _flow_id64(fid) -> int:
    """Stable 64-bit id for a Chrome flow id string (Perfetto flow ids
    are integers; ours are ``phase:tid:attempt`` strings)."""
    h = hashlib.sha1(str(fid).encode()).digest()
    return int.from_bytes(h[:8], "little") or 1


def _packet(ts_ns: "int | None" = None, track_event: "bytes | None" = None,
            track_descriptor: "bytes | None" = None) -> bytes:
    parts = []
    if ts_ns is not None:
        parts.append(_f_varint(8, max(int(ts_ns), 0)))
    parts.append(_f_varint(10, _SEQ_ID))
    if track_event is not None:
        parts.append(_f_bytes(11, track_event))
    if track_descriptor is not None:
        parts.append(_f_bytes(60, track_descriptor))
    return _f_bytes(1, b"".join(parts))


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

def _track_event(type_: int, track_uuid: int, name: "str | None" = None,
                 counter_value=None, flow_id: "int | None" = None,
                 terminating: bool = False) -> bytes:
    parts = [_f_varint(9, type_), _f_varint(11, track_uuid)]
    if name:
        parts.append(_f_str(23, name))
    if counter_value is not None:
        if isinstance(counter_value, float) and not counter_value.is_integer():
            parts.append(_f_double(44, counter_value))
        else:
            parts.append(_f_varint(30, int(counter_value)))
    if flow_id is not None:
        parts.append(_f_fixed64(48 if terminating else 47, flow_id))
    return b"".join(parts)


class _Tracks:
    """uuid mint + descriptor packets for process / thread / counter
    tracks, emitted once each, lazily."""

    def __init__(self, out: list) -> None:
        self._out = out
        self._next = 1
        self._proc: dict = {}     # pid → uuid
        self._thread: dict = {}   # (pid, tid) → uuid
        self._counter: dict = {}  # (pid, series) → uuid
        self._proc_names: dict = {}

    def set_process_name(self, pid, name: str) -> None:
        self._proc_names[pid] = name

    def _mint(self) -> int:
        u, self._next = self._next, self._next + 1
        return u

    def _pid_num(self, pid) -> int:
        # Perfetto pids are int32; merged pids are ints by construction
        # but stay defensive for hand-built traces.
        try:
            return int(pid) & 0x7FFFFFFF
        except (TypeError, ValueError):
            return _flow_id64(pid) & 0x7FFFFFFF

    def process(self, pid) -> int:
        u = self._proc.get(pid)
        if u is None:
            u = self._proc[pid] = self._mint()
            name = str(self._proc_names.get(pid, f"pid {pid}"))
            proc = _f_varint(1, self._pid_num(pid)) + _f_str(6, name)
            desc = _f_varint(1, u) + _f_str(2, name) + _f_bytes(3, proc)
            self._out.append(_packet(track_descriptor=desc))
        return u

    def thread(self, pid, tid) -> int:
        u = self._thread.get((pid, tid))
        if u is None:
            self.process(pid)  # parent descriptor first
            u = self._thread[(pid, tid)] = self._mint()
            try:
                tid_num = int(tid) & 0x7FFFFFFF
            except (TypeError, ValueError):
                tid_num = _flow_id64(tid) & 0x7FFFFFFF
            thr = (
                _f_varint(1, self._pid_num(pid)) + _f_varint(2, tid_num)
                + _f_str(5, f"tid {tid}")
            )
            desc = _f_varint(1, u) + _f_bytes(4, thr)
            self._out.append(_packet(track_descriptor=desc))
        return u

    def counter(self, pid, series: str) -> int:
        u = self._counter.get((pid, series))
        if u is None:
            parent = self.process(pid)
            u = self._counter[(pid, series)] = self._mint()
            desc = (
                _f_varint(1, u) + _f_str(2, series) + _f_varint(5, parent)
                + _f_bytes(8, b"")  # empty CounterDescriptor marks the kind
            )
            self._out.append(_packet(track_descriptor=desc))
        return u


def _nested_slice_stream(spans: list) -> list:
    """(ts_us, is_end, name) stream with correct per-track nesting order:
    sort by (start asc, end desc) — parents before children — and emit
    ENDs for every span that closes at-or-before the next start, so equal
    timestamps never interleave a parent's END under its child's."""
    spans = sorted(spans, key=lambda s: (s[0], -s[1]))
    out: list = []
    stack: list = []
    for s0, s1, name in spans:
        while stack and stack[-1][0] <= s0:
            e, n = stack.pop()
            out.append((e, True, n))
        out.append((s0, False, name))
        stack.append((s1, name))
    while stack:
        e, n = stack.pop()
        out.append((e, True, n))
    return out


def write_pftrace(events: list, out_path: str) -> dict:
    """Serialize a (merged, validated) Chrome event list as a Perfetto
    ``.pftrace`` track_event stream. Returns {packets, bytes}."""
    packets: list = []
    tracks = _Tracks(packets)
    # Pass 1: process names from the merge's "M" rows, so descriptors
    # carry "coord"/"w1234" instead of bare pids.
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            name = (ev.get("args") or {}).get("name")
            if name:
                tracks.set_process_name(ev.get("pid"), str(name))

    spans_by_track: dict = {}
    timed: list = []  # (ts_us, gen_seq, packet_bytes)
    seq = 0
    for ev in events:
        ph = ev.get("ph")
        if ph == "M":
            continue
        pid, tid, ts = ev.get("pid"), ev.get("tid"), float(ev.get("ts", 0))
        name = str(ev.get("name", ""))
        if ph == "X":
            spans_by_track.setdefault((pid, tid), []).append(
                (ts, ts + float(ev.get("dur", 0)), name)
            )
        elif ph == "i":
            te = _track_event(TYPE_INSTANT, tracks.thread(pid, tid), name)
            timed.append((ts, seq, _packet(int(ts * 1e3), te)))
            seq += 1
        elif ph in ("s", "t", "f"):
            te = _track_event(
                TYPE_INSTANT, tracks.thread(pid, tid), name,
                flow_id=_flow_id64(ev.get("id")), terminating=(ph == "f"),
            )
            timed.append((ts, seq, _packet(int(ts * 1e3), te)))
            seq += 1
        elif ph == "C":
            for k, v in (ev.get("args") or {}).items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                u = tracks.counter(pid, f"{name}.{k}" if k else name)
                te = _track_event(TYPE_COUNTER, u, counter_value=v)
                timed.append((ts, seq, _packet(int(ts * 1e3), te)))
                seq += 1
        elif ph in ("B", "E"):
            # Tracer itself emits only "X", but validate_events (the gate
            # merge runs) accepts balanced B/E pairs from foreign files —
            # the validator's balance+nesting guarantee means they map
            # 1:1 onto BEGIN/END in stream order.
            te = _track_event(
                TYPE_SLICE_END if ph == "E" else TYPE_SLICE_BEGIN,
                tracks.thread(pid, tid),
                None if ph == "E" else name,
            )
            timed.append((ts, seq, _packet(int(ts * 1e3), te)))
            seq += 1
    for (pid, tid), spans in spans_by_track.items():
        u = tracks.thread(pid, tid)
        for ts, is_end, name in _nested_slice_stream(spans):
            te = _track_event(
                TYPE_SLICE_END if is_end else TYPE_SLICE_BEGIN, u,
                None if is_end else name,
            )
            timed.append((ts, seq, _packet(int(ts * 1e3), te)))
            seq += 1
    # Stable by (ts, generation order): per-track nesting order survives
    # ties, and the trace processor gets a near-sorted stream.
    timed.sort(key=lambda t: (t[0], t[1]))
    body = b"".join(packets) + b"".join(p for _ts, _s, p in timed)
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{out_path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(body)
    os.replace(tmp, out_path)
    return {"packets": len(packets) + len(timed), "bytes": len(body)}


# ---------------------------------------------------------------------------
# Minimal reader — enough to re-parse what the writer emits (tests, and
# humans spot-checking a .pftrace without a protobuf dependency).
# ---------------------------------------------------------------------------

def _read_varint(buf: bytes, i: int) -> tuple:
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7
        if shift > 63:
            raise ValueError("varint overruns 64 bits")


def _fields(buf: bytes):
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 1:
            v, i = buf[i:i + 8], i + 8
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wt == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        if i > len(buf):
            raise ValueError("field overruns buffer")
        yield field, wt, v


def _parse_track_event(buf: bytes) -> dict:
    out: dict = {"flow_ids": [], "terminating_flow_ids": []}
    for field, _wt, v in _fields(buf):
        if field == 9:
            out["type"] = v
        elif field == 11:
            out["track_uuid"] = v
        elif field == 23:
            out["name"] = v.decode("utf-8", "replace")
        elif field == 30:
            out["counter_value"] = v
        elif field == 44:
            out["double_counter_value"] = struct.unpack("<d", v)[0]
        elif field == 47:
            out["flow_ids"].append(struct.unpack("<Q", v)[0])
        elif field == 48:
            out["terminating_flow_ids"].append(struct.unpack("<Q", v)[0])
    return out


def _parse_track_descriptor(buf: bytes) -> dict:
    out: dict = {}
    for field, _wt, v in _fields(buf):
        if field == 1:
            out["uuid"] = v
        elif field == 2:
            out["name"] = v.decode("utf-8", "replace")
        elif field == 3:
            proc: dict = {}
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:
                    proc["pid"] = v2
                elif f2 == 6:
                    proc["process_name"] = v2.decode("utf-8", "replace")
            out["process"] = proc
        elif field == 4:
            thr: dict = {}
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:
                    thr["pid"] = v2
                elif f2 == 2:
                    thr["tid"] = v2
                elif f2 == 5:
                    thr["thread_name"] = v2.decode("utf-8", "replace")
            out["thread"] = thr
        elif field == 5:
            out["parent_uuid"] = v
        elif field == 8:
            out["counter"] = True
    return out


def iter_packets(path: str):
    """Yield parsed TracePacket dicts ({timestamp?, sequence_id,
    track_event?|track_descriptor?}) from a ``.pftrace`` file written by
    :func:`write_pftrace` (or any track_event-subset stream)."""
    with open(path, "rb") as f:
        buf = f.read()
    for field, wt, payload in _fields(buf):
        if field != 1 or wt != 2:
            raise ValueError(
                f"top level must be Trace.packet (field 1), got field "
                f"{field} wire type {wt}"
            )
        pkt: dict = {}
        for f2, _w2, v2 in _fields(payload):
            if f2 == 8:
                pkt["timestamp"] = v2
            elif f2 == 10:
                pkt["sequence_id"] = v2
            elif f2 == 11:
                pkt["track_event"] = _parse_track_event(v2)
            elif f2 == 60:
                pkt["track_descriptor"] = _parse_track_descriptor(v2)
        yield pkt
