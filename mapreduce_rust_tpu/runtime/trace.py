"""In-process timeline tracer — Chrome trace-event JSON, buffered in RAM.

The observability doctrine (runtime/metrics.py) forbids per-record work on
the hot path; this tracer keeps that contract at the SPAN level: a span is
two ``perf_counter`` reads plus one list append (lists append GIL-atomically,
so producer/scan/consumer threads share one buffer lock-free), and the whole
buffer is serialized exactly once, at job end. Per-chunk and per-round spans
are fine; per-record spans are not.

Output is the Chrome trace-event format — ``{"traceEvents": [...]}`` of
"X" (complete) events with microsecond ``ts``/``dur`` — loadable directly
in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``. Spans on one
thread nest by call structure, so the viewer reconstructs the flame graph
with no explicit parent links.

When JAX is already imported, every span also enters a
``jax.profiler.TraceAnnotation``: a ``Config.profile_dir`` XLA trace taken
in the same run then shows these host spans on the profiler timeline,
lined up with the device ops they dispatched. The import is lazy AND
conditional on ``jax`` being in ``sys.modules`` — control-plane processes
(coordinator) must be able to trace without dragging in a backend.

Tracing is OFF by default: ``trace_span`` with no active tracer is a
single global read. ``run_job`` activates a tracer when
``Config.trace_path`` is set and writes the file in its ``finally``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

_UNSET = object()  # undecided; None = permanently unavailable; else the class
_ANN = _UNSET

_tracer: "Tracer | None" = None

_COUNTER = object()  # t1 slot marker: the event is a "C" counter sample


def _annotation_cls():
    """jax.profiler.TraceAnnotation iff jax is ALREADY imported, else None.

    Three cache states: undecided (_UNSET — jax not seen yet, re-check so a
    later jax import is picked up), permanently unavailable (None — the
    profiler import FAILED once; never re-attempt it on the span hot path),
    or the class. A jax-free process stays undecided forever, cheaply
    (one sys.modules probe per span).
    """
    global _ANN
    if _ANN is _UNSET:
        import sys

        if "jax" not in sys.modules:
            return None  # undecided: don't force a backend into this process
        try:
            from jax.profiler import TraceAnnotation

            _ANN = TraceAnnotation
        except Exception:  # profiler API moved/absent — spans still record
            _ANN = None
    return _ANN


class Tracer:
    """Bounded-overhead span buffer for one run.

    Events are (name, t0, t1, thread_id, args) tuples; timestamps are raw
    ``perf_counter`` seconds, rebased to the tracer's epoch only at
    ``write`` time so the hot path does no arithmetic beyond the clock
    reads themselves.
    """

    def __init__(self) -> None:
        self._epoch = time.perf_counter()
        self._pid = os.getpid()
        self._events: list[tuple] = []  # append is GIL-atomic

    def add_span(self, name: str, t0: float, t1: float, args=None) -> None:
        self._events.append((name, t0, t1, threading.get_ident(), args))

    def instant(self, name: str, **args) -> None:
        t = time.perf_counter()
        self._events.append((name, t, None, threading.get_ident(), args or None))

    def counter(self, name: str, **values) -> None:
        """A Chrome "C" counter sample (numeric values only) — Perfetto
        renders these as a gauge track (e.g. the host-map engine's
        in-flight scan depth over time). Same one-append hot path as a
        span."""
        t = time.perf_counter()
        self._events.append((name, t, _COUNTER, threading.get_ident(), values))

    def __len__(self) -> int:
        return len(self._events)

    def summarize(self, name: str) -> "dict | None":
        """Aggregate of the named complete spans — {count, total_s,
        mean_ms, max_ms} — or None when the buffer holds none. Used at
        manifest-flush time (one pass over the buffer, off the hot path)
        to surface e.g. per-round mesh.all_to_all durations without
        shipping every event into the manifest."""
        durs = [
            t1 - t0
            for n, t0, t1, _tid, _args in self._events
            if n == name and t1 is not None and t1 is not _COUNTER
        ]
        if not durs:
            return None
        total = sum(durs)
        return {
            "count": len(durs),
            "total_s": round(total, 6),
            "mean_ms": round(total / len(durs) * 1e3, 3),
            "max_ms": round(max(durs) * 1e3, 3),
        }

    def events(self) -> list[dict]:
        """The buffer as Chrome trace-event dicts (µs since the epoch)."""
        out = []
        for name, t0, t1, tid, args in self._events:
            ev = {
                "name": name,
                "ph": "C" if t1 is _COUNTER else ("X" if t1 is not None else "i"),
                "ts": (t0 - self._epoch) * 1e6,
                "pid": self._pid,
                "tid": tid,
            }
            if t1 is _COUNTER:
                pass  # counter samples carry only their args values
            elif t1 is not None:
                ev["dur"] = (t1 - t0) * 1e6
            else:
                ev["s"] = "t"  # instant event scope: thread
            if args:
                ev["args"] = {k: v for k, v in args.items()}
            out.append(ev)
        return out

    def write(self, path: str) -> str:
        """Serialize once, atomically (tmp + rename). Returns ``path``.

        Under the sanitizer (MR_SANITIZE=1 / Config.sanitize) the buffer is
        validated first — an unbalanced or ill-typed event stream fails at
        the writer, naming the broken span, instead of shipping a trace
        Perfetto renders as garbage. (Every producer — driver, worker,
        coordinator — writes through here, so they all get the check.)
        """
        from mapreduce_rust_tpu.analysis.sanitize import sanitize_enabled

        if sanitize_enabled():
            validate_events(self.events())
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.{self._pid}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"traceEvents": self.events(), "displayTimeUnit": "ms"},
                f,
                separators=(",", ":"),
            )
        os.replace(tmp, path)
        return path


def start_tracing() -> Tracer:
    """Install a fresh process-global tracer (one tracer per run: run_job
    owns the lifecycle; concurrent run_jobs in one process would interleave
    buffers, which the driver does not do)."""
    global _tracer
    _tracer = Tracer()
    return _tracer


def stop_tracing() -> "Tracer | None":
    """Deactivate and return the current tracer (caller writes it)."""
    global _tracer
    t, _tracer = _tracer, None
    return t


def active_tracer() -> "Tracer | None":
    return _tracer


@contextmanager
def trace_span(name: str, **args):
    """Span context: no-op (one global read) when tracing is off.

    With a tracer active, also enters a ``jax.profiler.TraceAnnotation`` so
    an XLA profile of the same interval shows this span on its timeline.
    """
    tr = _tracer
    if tr is None:
        yield
        return
    ann_cls = _annotation_cls()
    ann = ann_cls(name) if ann_cls is not None else None
    if ann is not None:
        ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        if ann is not None:
            ann.__exit__(None, None, None)
        tr.add_span(name, t0, t1, args or None)


def trace_counter(name: str, **values) -> None:
    """Record a counter sample on the active tracer — no-op (one global
    read) when tracing is off. Values must be numeric (Chrome "C" event
    semantics)."""
    tr = _tracer
    if tr is not None:
        tr.counter(name, **values)


def per_process_path(path: str, tag: str) -> str:
    """Derive a per-process artifact path (`x.json` → `x-w123.json`):
    several workers (or a coordinator) on one host may share a Config, and
    their trace/manifest files must never clobber each other."""
    root, ext = os.path.splitext(path)
    return f"{root}-{tag}{ext or '.json'}"


def validate_events(events: list[dict]) -> None:
    """Structural validator for a Chrome trace-event list (the test,
    ``stats`` and ``lint --check-trace`` consumers share it): required
    fields; per-(pid, tid) "X" spans either nest or are disjoint — never
    partially overlap, which is what makes the Perfetto flame graph
    well-formed; "B"/"E" duration pairs balance per thread (every E
    matches the most recent open B of the same name, nothing left open);
    and "C" counter samples carry only numeric values — Perfetto plots a
    non-numeric gauge as silent garbage, so it is rejected here instead.
    """
    per_thread: dict = {}
    be_events: dict = {}  # (pid, tid) → [(ts, seq, ph, name)]
    for seq, ev in enumerate(events):
        for field in ("name", "ph", "ts", "pid", "tid"):
            if field not in ev:
                raise ValueError(f"event missing {field!r}: {ev}")
        if ev["ph"] == "X":
            if "dur" not in ev or ev["dur"] < 0:
                raise ValueError(f"X event needs dur >= 0: {ev}")
            per_thread.setdefault((ev["pid"], ev["tid"]), []).append(
                (ev["ts"], ev["ts"] + ev["dur"], ev["name"])
            )
        elif ev["ph"] in ("B", "E"):
            be_events.setdefault((ev["pid"], ev["tid"]), []).append(
                (ev["ts"], seq, ev["ph"], ev["name"])
            )
        elif ev["ph"] == "C":
            args = ev.get("args")
            if not args or not isinstance(args, dict):
                raise ValueError(f"C event needs non-empty args: {ev}")
            for k, v in args.items():
                # bool is an int subclass but not a gauge sample.
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(
                        f"C event value {k}={v!r} is not numeric: {ev}"
                    )
    for key, evs in be_events.items():
        # Emission order breaks ties at equal timestamps (stable sort), so
        # a zero-duration B-then-E pair stays balanced.
        evs.sort(key=lambda e: (e[0], e[1]))
        open_spans: list[str] = []
        for ts, _seq, ph, name in evs:
            if ph == "B":
                open_spans.append(name)
            elif not open_spans:
                raise ValueError(
                    f"E event {name!r} at ts={ts} on thread {key} has no "
                    "matching open B"
                )
            elif open_spans[-1] != name:
                raise ValueError(
                    f"E event {name!r} at ts={ts} on thread {key} closes "
                    f"{open_spans[-1]!r} — B/E pairs must nest by name"
                )
            else:
                open_spans.pop()
        if open_spans:
            raise ValueError(
                f"unbalanced B/E spans on thread {key}: "
                f"{open_spans!r} never closed"
            )
    for key, spans in per_thread.items():
        # Sort by start asc, end desc: a containing span precedes its
        # children, so a stack check catches partial overlap.
        spans.sort(key=lambda s: (s[0], -s[1]))
        stack: list[tuple] = []
        for s0, s1, name in spans:
            while stack and stack[-1][1] <= s0:
                stack.pop()
            if stack and s1 > stack[-1][1]:
                raise ValueError(
                    f"span {name!r} [{s0}, {s1}] partially overlaps "
                    f"{stack[-1][2]!r} [{stack[-1][0]}, {stack[-1][1]}] "
                    f"on thread {key}"
                )
            stack.append((s0, s1, name))
