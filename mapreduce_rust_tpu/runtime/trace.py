"""In-process timeline tracer — Chrome trace-event JSON, buffered in RAM.

The observability doctrine (runtime/metrics.py) forbids per-record work on
the hot path; this tracer keeps that contract at the SPAN level: a span is
two ``perf_counter`` reads plus one list append (lists append GIL-atomically,
so producer/scan/consumer threads share one buffer lock-free), and the whole
buffer is serialized exactly once, at job end. Per-chunk and per-round spans
are fine; per-record spans are not.

Output is the Chrome trace-event format — ``{"traceEvents": [...]}`` of
"X" (complete) events with microsecond ``ts``/``dur`` — loadable directly
in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``. Spans on one
thread nest by call structure, so the viewer reconstructs the flame graph
with no explicit parent links.

When JAX is already imported, every span also enters a
``jax.profiler.TraceAnnotation``: a ``Config.profile_dir`` XLA trace taken
in the same run then shows these host spans on the profiler timeline,
lined up with the device ops they dispatched. The import is lazy AND
conditional on ``jax`` being in ``sys.modules`` — control-plane processes
(coordinator) must be able to trace without dragging in a backend.

Tracing is OFF by default: ``trace_span`` with no active tracer is a
single global read. ``run_job`` activates a tracer when
``Config.trace_path`` is set and writes the file in its ``finally``.

Cross-process stitching (distributed-timeline tentpole): every tracer
records a wall-clock anchor next to its ``perf_counter`` epoch, every
written file carries ``metadata`` ({pid, tag, anchors, clock_sync}), and
``merge_traces`` rebases a fleet's files onto one clock — the
coordinator's when NTP-style RPC offsets are available (ClockSync in
coordinator/server.py), the shared wall clock otherwise. Flow events
(``ph: s/t/f``, id = ``phase:tid:attempt``) link a task's grant span in
the coordinator to the worker's task span and the finish-report RPC, so a
re-executed task forks into two visible attempt chains. The flight
recorder makes all of this survive a SIGKILL: an atomic ``*.partial.json``
snapshot is rewritten from the existing consumer/poll loops (never the
span hot path), and ``merge_traces`` accepts partials.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

_UNSET = object()  # undecided; None = permanently unavailable; else the class
_ANN = _UNSET

_tracer: "Tracer | None" = None

_COUNTER = object()  # t1 slot marker: the event is a "C" counter sample


class _Flow:
    """t1 slot marker: a Chrome flow event (ph s/t/f) with its bound id."""

    __slots__ = ("ph", "id")

    def __init__(self, ph: str, flow_id: str) -> None:
        self.ph = ph
        self.id = flow_id


def _annotation_cls():
    """jax.profiler.TraceAnnotation iff jax is ALREADY imported, else None.

    Three cache states: undecided (_UNSET — jax not seen yet, re-check so a
    later jax import is picked up), permanently unavailable (None — the
    profiler import FAILED once; never re-attempt it on the span hot path),
    or the class. A jax-free process stays undecided forever, cheaply
    (one sys.modules probe per span).
    """
    global _ANN
    if _ANN is _UNSET:
        import sys

        if "jax" not in sys.modules:
            return None  # undecided: don't force a backend into this process
        try:
            from jax.profiler import TraceAnnotation

            _ANN = TraceAnnotation
        except Exception:  # profiler API moved/absent — spans still record
            _ANN = None
    return _ANN


class Tracer:
    """Bounded-overhead span buffer for one run.

    Events are (name, t0, t1, thread_id, args) tuples; timestamps are raw
    ``perf_counter`` seconds, rebased to the tracer's epoch only at
    ``write`` time so the hot path does no arithmetic beyond the clock
    reads themselves.
    """

    def __init__(self, tag: "str | None" = None) -> None:
        # The two anchors are read back-to-back so the wall clock names the
        # same instant as the perf_counter epoch: stitching rebases event
        # timestamps across processes through either one.
        self._anchor_unix = time.time()
        self._epoch = time.perf_counter()
        self._pid = os.getpid()
        self._events: list[tuple] = []  # append is GIL-atomic
        self.tag = tag                  # process role for track naming
        self.clock_sync = None          # ClockSync (or dict) to the coordinator
        self.metrics_registry = None    # live MetricsRegistry (ISSUE 8):
        # when set, flight-recorder partials embed the time-series ring, so
        # a SIGKILLed run keeps its sampled series alongside its events
        self.profiler = None            # live SamplingProfiler (ISSUE 19):
        # same contract — partials embed the live profile snapshot, so a
        # SIGKILLed run keeps its flamegraph alongside its events
        self.lineage = None             # live LineageLedger (ISSUE 20):
        # same contract — partials embed the provenance tail, so a
        # SIGKILLed run's backward queries still resolve
        # Flight recorder state (see enable_flight_recorder).
        self._snap_path: "str | None" = None
        self._snap_period = 5.0
        self._snap_min_events = 512
        self._snap_last_t = 0.0
        self._snap_last_n = 0
        self._snap_lock = threading.Lock()

    def add_span(self, name: str, t0: float, t1: float, args=None,
                 tid: "int | None" = None) -> None:
        """``tid`` defaults to the calling thread. Pass an explicit pseudo
        tid for spans whose interval was measured by SOMEONE ELSE'S clock
        (e.g. the XLA compile listener re-emits jax-measured durations):
        on a synthetic track they can never partially overlap this
        thread's own call-structured spans, which the validator rejects."""
        self._events.append(
            (name, t0, t1, threading.get_ident() if tid is None else tid, args)
        )

    def instant(self, name: str, **args) -> None:
        t = time.perf_counter()
        self._events.append((name, t, None, threading.get_ident(), args or None))

    def flow(self, name: str, ph: str, flow_id: str, **args) -> None:
        """A Chrome flow event — ``ph`` "s" starts a chain, "t" steps it,
        "f" finishes it; events with one ``flow_id`` draw as arrows across
        processes once traces are merged. Same one-append hot path as a
        span; emit INSIDE the span the arrow should attach to."""
        if ph not in ("s", "t", "f"):
            raise ValueError(f"flow ph must be s/t/f, got {ph!r}")
        t = time.perf_counter()
        self._events.append(
            (name, t, _Flow(ph, flow_id), threading.get_ident(), args or None)
        )

    def counter(self, name: str, **values) -> None:
        """A Chrome "C" counter sample (numeric values only) — Perfetto
        renders these as a gauge track (e.g. the host-map engine's
        in-flight scan depth over time). Same one-append hot path as a
        span."""
        t = time.perf_counter()
        self._events.append((name, t, _COUNTER, threading.get_ident(), values))

    def __len__(self) -> int:
        return len(self._events)

    def summarize(self, name: str) -> "dict | None":
        """Aggregate of the named complete spans — {count, total_s,
        mean_ms, p50/p95/p99_ms, max_ms} — or None when the buffer holds
        none. Used at manifest-flush time (one pass over the buffer, off
        the hot path) to surface e.g. per-round mesh.all_to_all durations
        without shipping every event into the manifest. Percentiles are
        exact (sorted sample), not bucketed — the buffer already holds
        every duration."""
        durs = sorted(
            t1 - t0
            for n, t0, t1, _tid, _args in self._events
            if n == name and isinstance(t1, float)
        )
        if not durs:
            return None
        total = sum(durs)
        n = len(durs)

        def pct(q: float) -> float:
            return durs[min(int(q * (n - 1) + 0.5), n - 1)]

        return {
            "count": n,
            "total_s": round(total, 6),
            "mean_ms": round(total / n * 1e3, 3),
            "p50_ms": round(pct(0.50) * 1e3, 3),
            "p95_ms": round(pct(0.95) * 1e3, 3),
            "p99_ms": round(pct(0.99) * 1e3, 3),
            "max_ms": round(durs[-1] * 1e3, 3),
        }

    def events(self, limit: "int | None" = None) -> list[dict]:
        """The buffer as Chrome trace-event dicts (µs since the epoch).
        ``limit`` serializes only the first N events — the flight recorder
        snapshots a len() observed under the GIL, so a concurrent append
        can never tear a snapshot."""
        out = []
        buf = self._events if limit is None else self._events[:limit]
        for name, t0, t1, tid, args in buf:
            if t1 is _COUNTER:
                ph = "C"
            elif isinstance(t1, _Flow):
                ph = t1.ph
            elif t1 is not None:
                ph = "X"
            else:
                ph = "i"
            ev = {
                "name": name,
                "ph": ph,
                "ts": (t0 - self._epoch) * 1e6,
                "pid": self._pid,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = (t1 - t0) * 1e6
            elif ph == "i":
                ev["s"] = "t"  # instant event scope: thread
            elif ph in ("s", "t", "f"):
                ev["id"] = t1.id
                if ph == "f":
                    ev["bp"] = "e"  # bind the arrow head to the enclosing slice
            if args:
                ev["args"] = {k: v for k, v in args.items()}
            out.append(ev)
        return out

    def metadata(self, partial: bool = False) -> dict:
        """Stitching identity of this trace file: who wrote it and how its
        timestamps map onto other clocks. ``anchor_perf_s`` is the raw
        perf_counter epoch (the clock RPC offsets are measured against);
        ``anchor_unix_s`` the wall clock at the same instant (the shared
        fallback when no RPC sync exists)."""
        md: dict = {
            "pid": self._pid,
            "tag": self.tag,
            "anchor_unix_s": self._anchor_unix,
            "anchor_perf_s": self._epoch,
        }
        cs = self.clock_sync
        if cs is not None:
            best = cs.best() if hasattr(cs, "best") else dict(cs)
            if best:
                md["clock_sync"] = best
        if partial:
            md["partial"] = True
        return md

    # ---- flight recorder ----

    def enable_flight_recorder(self, partial_path_: str,
                               period_s: "float | None" = None,
                               min_new_events: int = 512) -> None:
        """Arm crash-safe incremental snapshots: ``maybe_snapshot()`` (from
        the existing consumer/poll loops — never the span hot path) rewrites
        ``partial_path_`` atomically every ``period_s`` seconds or
        ``min_new_events`` new events, whichever first. A SIGKILLed process
        leaves its last snapshot; a clean ``write`` removes it. The
        MR_FLIGHT_RECORD_S env var overrides the period (test hook)."""
        env = os.environ.get("MR_FLIGHT_RECORD_S")
        if env:
            try:
                period_s = float(env)
            except ValueError:
                pass
        self._snap_path = partial_path_
        if period_s is not None and period_s > 0:
            self._snap_period = period_s
        self._snap_min_events = max(int(min_new_events), 1)
        # First snapshot one period after arming, not instantly.
        self._snap_last_t = time.monotonic()

    def maybe_snapshot(self, force: bool = False) -> "str | None":
        """Snapshot if armed and due. The not-due path is two reads and a
        compare — cheap enough for a per-chunk/per-poll call site."""
        path = self._snap_path
        if path is None:
            return None
        n = len(self._events)
        if not force:
            if n == self._snap_last_n:
                return None
            if (
                time.monotonic() - self._snap_last_t < self._snap_period
                and n - self._snap_last_n < self._snap_min_events
            ):
                return None
        # Non-blocking: a concurrent snapshot (atexit vs signal vs loop) is
        # already writing this same buffer — skipping loses nothing.
        if not self._snap_lock.acquire(blocking=False):
            return None
        try:
            body = {
                "traceEvents": self.events(limit=n),
                "displayTimeUnit": "ms",
                "metadata": self.metadata(partial=True),
            }
            reg = self.metrics_registry
            if reg is not None:
                try:
                    # The series ride the partial: a SIGKILLed run's ring
                    # would otherwise die with the process before any
                    # manifest flush could serialize it.
                    body["metrics"] = reg.timeseries_dict()
                except Exception:
                    pass  # the recorder must never fail the run
            sprof = self.profiler
            if sprof is not None:
                try:
                    # The flamegraph rides the partial too (ISSUE 19): a
                    # SIGKILLed run's sample aggregate would otherwise die
                    # with the process before any manifest flush.
                    body["profile"] = sprof.profile_dict()
                except Exception:
                    pass  # the recorder must never fail the run
            ledger = self.lineage
            if ledger is not None:
                try:
                    # Provenance rides the partial (ISSUE 20): the jsonl
                    # on disk survives a SIGKILL by itself, but the
                    # embedded tail lets the lineage CLI answer queries
                    # from the partial alone.
                    body["lineage"] = ledger.tail_dict()
                except Exception:
                    pass  # the recorder must never fail the run
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = f"{path}.{self._pid}.tmp"
            with open(tmp, "w") as f:
                json.dump(body, f, separators=(",", ":"))
            os.replace(tmp, path)
            self._snap_last_t = time.monotonic()
            self._snap_last_n = n
            return path
        except OSError:
            return None  # best-effort: the recorder must never fail the run
        finally:
            self._snap_lock.release()

    def write(self, path: str) -> str:
        """Serialize once, atomically (tmp + rename). Returns ``path``.

        Under the sanitizer (MR_SANITIZE=1 / Config.sanitize) the buffer is
        validated first — an unbalanced or ill-typed event stream fails at
        the writer, naming the broken span, instead of shipping a trace
        Perfetto renders as garbage. (Every producer — driver, worker,
        coordinator — writes through here, so they all get the check.)
        """
        from mapreduce_rust_tpu.analysis.sanitize import sanitize_enabled

        if sanitize_enabled():
            validate_events(self.events())
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.{self._pid}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "traceEvents": self.events(),
                    "displayTimeUnit": "ms",
                    "metadata": self.metadata(),
                },
                f,
                separators=(",", ":"),
            )
        os.replace(tmp, path)
        # The run completed and the full trace exists: the crash snapshot
        # is now stale — a later merge must not double-ingest it.
        if self._snap_path:
            try:
                os.remove(self._snap_path)
            except OSError:
                pass
        return path


def start_tracing(tag: "str | None" = None) -> Tracer:
    """Install a fresh process-global tracer (one tracer per run: run_job
    owns the lifecycle; concurrent run_jobs in one process would interleave
    buffers, which the driver does not do)."""
    global _tracer
    _tracer = Tracer(tag=tag)
    return _tracer


def stop_tracing() -> "Tracer | None":
    """Deactivate and return the current tracer (caller writes it)."""
    global _tracer
    t, _tracer = _tracer, None
    return t


def active_tracer() -> "Tracer | None":
    return _tracer


@contextmanager
def trace_span(name: str, **args):
    """Span context: no-op (one global read) when tracing is off.

    With a tracer active, also enters a ``jax.profiler.TraceAnnotation`` so
    an XLA profile of the same interval shows this span on its timeline.
    """
    tr = _tracer
    if tr is None:
        yield
        return
    ann_cls = _annotation_cls()
    ann = ann_cls(name) if ann_cls is not None else None
    if ann is not None:
        ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        if ann is not None:
            ann.__exit__(None, None, None)
        tr.add_span(name, t0, t1, args or None)


def trace_counter(name: str, **values) -> None:
    """Record a counter sample on the active tracer — no-op (one global
    read) when tracing is off. Values must be numeric (Chrome "C" event
    semantics)."""
    tr = _tracer
    if tr is not None:
        tr.counter(name, **values)


def trace_instant(name: str, **args) -> None:
    """Record an instant event on the active tracer — no-op when off. The
    flight recorder's unit of progress: a task-begin mark survives in the
    partial snapshot even though the enclosing span (recorded at exit)
    dies with the process."""
    tr = _tracer
    if tr is not None:
        tr.instant(name, **args)


def trace_flow(name: str, ph: str, flow_id: str, **args) -> None:
    """Record a flow event (ph s/t/f, bound ``flow_id``) on the active
    tracer — no-op when off."""
    tr = _tracer
    if tr is not None:
        tr.flow(name, ph, flow_id, **args)


def maybe_snapshot() -> None:
    """Flight-recorder tick on the active tracer — no-op when tracing is
    off or the recorder is not armed. Call from consumer/poll loops (per
    chunk, per renewal, per serve tick), never per record."""
    tr = _tracer
    if tr is not None:
        tr.maybe_snapshot()


_crash_dump_installed = False


def install_crash_dump() -> None:
    """atexit + SIGTERM dump of the active tracer's flight-recorder
    snapshot: a process dying on an unhandled exception or a polite kill
    leaves its timeline even if no loop ticked again. (SIGKILL cannot be
    caught — that is what the periodic snapshots are for.) CLI entry
    points install this; in-process library use (tests, embedding) must
    not have its signal handlers stolen, so it is opt-in."""
    global _crash_dump_installed
    if _crash_dump_installed:
        return
    _crash_dump_installed = True
    import atexit
    import signal

    def _dump() -> None:
        tr = _tracer
        if tr is not None:
            try:
                tr.maybe_snapshot(force=True)
            except Exception:
                pass  # a dying process must die on ITS error, not ours

    atexit.register(_dump)

    def _on_term(signum, frame):
        _dump()
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)  # re-raise: exit status stays honest

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # not the main thread — atexit still covers clean exits


def per_process_path(path: str, tag: str) -> str:
    """Derive a per-process artifact path (`x.json` → `x-w123.json`):
    several workers (or a coordinator) on one host may share a Config, and
    their trace/manifest files must never clobber each other."""
    root, ext = os.path.splitext(path)
    return f"{root}-{tag}{ext or '.json'}"


def partial_path(path: str) -> str:
    """The flight-recorder snapshot path beside a final trace path
    (`x.json` → `x.partial.json`)."""
    root, ext = os.path.splitext(path)
    return f"{root}.partial{ext or '.json'}"


_FLOW_ORDER = {"s": 0, "t": 1, "f": 2}  # tie-break at equal timestamps


def validate_events(events: list[dict]) -> None:
    """Structural validator for a Chrome trace-event list (the test,
    ``stats`` and ``lint --check-trace`` consumers share it): required
    fields; per-(pid, tid) "X" spans either nest or are disjoint — never
    partially overlap, which is what makes the Perfetto flame graph
    well-formed; "B"/"E" duration pairs balance per thread (every E
    matches the most recent open B of the same name, nothing left open);
    "C" counter samples carry only numeric values — Perfetto plots a
    non-numeric gauge as silent garbage, so it is rejected here instead;
    "s"/"t"/"f" flow events carry a bound id and each id's chain is
    well-formed (started at most once, steps never precede the start,
    nothing after the finish — but a start with no finish is legal: that
    is exactly what a crashed attempt looks like, and a fragment file
    holding only "t" steps merges later); "M" metadata events carry args.
    """
    per_thread: dict = {}
    be_events: dict = {}  # (pid, tid) → [(ts, seq, ph, name)]
    flows: dict = {}      # flow id → [(ts, order, seq, ph)]
    for seq, ev in enumerate(events):
        for field in ("name", "ph", "ts", "pid", "tid"):
            if field not in ev:
                raise ValueError(f"event missing {field!r}: {ev}")
        if ev["ph"] in ("s", "t", "f"):
            fid = ev.get("id")
            if fid is None or (isinstance(fid, str) and not fid):
                raise ValueError(f"flow event needs a bound id: {ev}")
            flows.setdefault(fid, []).append(
                (ev["ts"], _FLOW_ORDER[ev["ph"]], seq, ev["ph"])
            )
        elif ev["ph"] == "M":
            args = ev.get("args")
            if not args or not isinstance(args, dict):
                raise ValueError(f"M metadata event needs non-empty args: {ev}")
        elif ev["ph"] == "X":
            if "dur" not in ev or ev["dur"] < 0:
                raise ValueError(f"X event needs dur >= 0: {ev}")
            per_thread.setdefault((ev["pid"], ev["tid"]), []).append(
                (ev["ts"], ev["ts"] + ev["dur"], ev["name"])
            )
        elif ev["ph"] in ("B", "E"):
            be_events.setdefault((ev["pid"], ev["tid"]), []).append(
                (ev["ts"], seq, ev["ph"], ev["name"])
            )
        elif ev["ph"] == "C":
            args = ev.get("args")
            if not args or not isinstance(args, dict):
                raise ValueError(f"C event needs non-empty args: {ev}")
            for k, v in args.items():
                # bool is an int subclass but not a gauge sample.
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ValueError(
                        f"C event value {k}={v!r} is not numeric: {ev}"
                    )
    for fid, fevs in flows.items():
        # Stable order: ts, then s<t<f at equal timestamps (a grant and its
        # task step can land on the same microsecond after merging), then
        # emission order.
        fevs.sort()
        phs = [ph for _ts, _o, _seq, ph in fevs]
        starts = [i for i, ph in enumerate(phs) if ph == "s"]
        if len(starts) > 1:
            raise ValueError(f"flow id {fid!r} started twice")
        if starts and starts[0] != 0:
            raise ValueError(f"flow id {fid!r} has steps before its start")
        if "f" in phs and phs.index("f") != len(phs) - 1:
            raise ValueError(f"flow id {fid!r} continues after its finish")
    for key, evs in be_events.items():
        # Emission order breaks ties at equal timestamps (stable sort), so
        # a zero-duration B-then-E pair stays balanced.
        evs.sort(key=lambda e: (e[0], e[1]))
        open_spans: list[str] = []
        for ts, _seq, ph, name in evs:
            if ph == "B":
                open_spans.append(name)
            elif not open_spans:
                raise ValueError(
                    f"E event {name!r} at ts={ts} on thread {key} has no "
                    "matching open B"
                )
            elif open_spans[-1] != name:
                raise ValueError(
                    f"E event {name!r} at ts={ts} on thread {key} closes "
                    f"{open_spans[-1]!r} — B/E pairs must nest by name"
                )
            else:
                open_spans.pop()
        if open_spans:
            raise ValueError(
                f"unbalanced B/E spans on thread {key}: "
                f"{open_spans!r} never closed"
            )
    for key, spans in per_thread.items():
        # Sort by start asc, end desc: a containing span precedes its
        # children, so a stack check catches partial overlap.
        spans.sort(key=lambda s: (s[0], -s[1]))
        stack: list[tuple] = []
        for s0, s1, name in spans:
            while stack and stack[-1][1] <= s0:
                stack.pop()
            if stack and s1 > stack[-1][1]:
                raise ValueError(
                    f"span {name!r} [{s0}, {s1}] partially overlaps "
                    f"{stack[-1][2]!r} [{stack[-1][0]}, {stack[-1][1]}] "
                    f"on thread {key}"
                )
            stack.append((s0, s1, name))


# ---------------------------------------------------------------------------
# Cross-process stitching
# ---------------------------------------------------------------------------

def load_trace(path: str) -> tuple[list[dict], dict]:
    """(events, metadata) of one trace file — final or ``*.partial.json``
    (the flight recorder writes the same schema). Pre-metadata files (a
    bare event list, or no ``metadata`` key) load with empty metadata."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
        md = doc.get("metadata") or {}
    else:
        events, md = doc, {}
    if not isinstance(events, list):
        raise ValueError(f"{path}: no traceEvents list")
    return events, md


def _rebase_delta(md: dict, ref_md: dict) -> tuple[float, str]:
    """Seconds to add to this trace's timestamps to land on the reference
    trace's timeline, and which clock relation justified it. Preference
    order: the NTP-style RPC offset (valid only against the coordinator's
    perf_counter clock), then the shared wall clock, then nothing."""
    cs = md.get("clock_sync")
    if (
        cs
        and ref_md.get("tag") == "coord"
        and md.get("anchor_perf_s") is not None
        and ref_md.get("anchor_perf_s") is not None
    ):
        return (
            md["anchor_perf_s"] + cs["offset_s"] - ref_md["anchor_perf_s"],
            "rpc",
        )
    if md.get("anchor_unix_s") is not None and ref_md.get("anchor_unix_s") is not None:
        return md["anchor_unix_s"] - ref_md["anchor_unix_s"], "wall"
    return 0.0, "none"


def _repair_flow_causality(merged: "list[dict]") -> None:
    """Clamp sub-tolerance flow inversions introduced by the rebase.

    The protocol guarantees grant (s) → task step (t) → finish (f), but
    cross-process timestamps are only accurate to the rebase's residual
    error (±RTT/2 for RPC offsets, worse for wall fallback): a worker's
    step can land a few hundred µs before its grant and the merged file
    would then fail its own flow validation — losing the whole artifact
    over known clock noise. Inversions BETWEEN files within the combined
    tolerance are lifted to the causal bound; same-file inversions and
    anything beyond tolerance are left for validate_events to reject
    (those are writer bugs or broken clocks, not noise)."""
    by_id: dict = {}
    for ev in merged:
        if ev.get("ph") in ("s", "t", "f"):
            by_id.setdefault(ev["id"], []).append(ev)
    for evs in by_id.values():
        starts = [e for e in evs if e["ph"] == "s"]
        finishes = [e for e in evs if e["ph"] == "f"]
        if len(starts) == 1:
            s = starts[0]
            for e in evs:
                if (
                    e["ph"] != "s"
                    and e["_src"] != s["_src"]
                    and e["ts"] < s["ts"]
                    and (s["ts"] - e["ts"]) <= (e["_tol"] + s["_tol"]) * 1e6
                ):
                    e["ts"] = s["ts"]  # equal ts: s<t<f tie-break keeps order
        if len(finishes) == 1:
            f = finishes[0]
            for e in evs:
                if (
                    e["ph"] != "f"
                    and e["_src"] != f["_src"]
                    and e["ts"] > f["ts"]
                    and (e["ts"] - f["ts"]) <= (e["_tol"] + f["_tol"]) * 1e6
                ):
                    e["ts"] = f["ts"]


def merge_traces(out_path: str, paths: "list[str]",
                 out_format: str = "json") -> dict:
    """Stitch per-process trace files (partials included) onto ONE
    timeline and write a Perfetto-loadable file to ``out_path``.

    The reference clock is the coordinator's (tag "coord") when present —
    workers carry an RPC-measured offset to it — else the earliest
    wall-clock anchor. Each input keeps its own pid track (colliding pids,
    e.g. two hosts, are remapped) and gets a ``process_name`` metadata
    event from its tag, so the merged view reads "coord / w1234 / ...".
    The merged stream is validated before writing: a stitched file that
    fails ``validate_events`` is a bug here, not a viewer surprise.
    Returns a summary dict (events, processes, per-file clock domains).
    """
    if not paths:
        raise ValueError("trace merge needs at least one input trace")
    traces = []
    for p in paths:
        events, md = load_trace(p)
        traces.append({"path": p, "events": events, "md": md})
    ref = next((t for t in traces if t["md"].get("tag") == "coord"), None)
    if ref is None:
        anchored = [t for t in traces if t["md"].get("anchor_unix_s") is not None]
        ref = min(anchored, key=lambda t: t["md"]["anchor_unix_s"]) if anchored \
            else traces[0]

    merged: list[dict] = []
    processes: list[dict] = []
    used_pids: set = set()
    used_labels: set = set()
    for t in traces:
        md = t["md"]
        delta_s, domain = (0.0, "reference") if t is ref \
            else _rebase_delta(md, ref["md"])
        # One pid per input file keeps tracks distinct even when metadata
        # is absent; collisions (same pid from two hosts, or a final trace
        # merged next to its own stale partial) are remapped.
        pids = {ev["pid"] for ev in t["events"]}
        if md.get("pid") is not None:
            pids.add(md["pid"])
        remap = {}
        for pid in sorted(pids, key=str):
            new = pid
            while new in used_pids:
                new = (new if isinstance(new, int) else 0) + 100000
            remap[pid] = new
            used_pids.add(new)
        tag = md.get("tag") or os.path.splitext(os.path.basename(t["path"]))[0]
        # Duplicate (pid, tag) metadata across input files (pid reuse on
        # another host mints the same "w<pid>" tag; or one file fed in
        # twice): the pids above were remapped apart, but two tracks with
        # ONE name silently read as one process — remap the tag too, like
        # the pid, so every track stays attributable. The partial flag is
        # part of the identity: a final trace beside its own stale partial
        # is the legitimate same-tag pair and keeps its bare name.
        key = (tag, bool(md.get("partial")))
        if key in used_labels:
            n = 2
            while (f"{tag}#{n}", key[1]) in used_labels:
                n += 1
            tag = f"{tag}#{n}"
            key = (tag, key[1])
        used_labels.add(key)
        label = f"{tag}{' [partial]' if md.get('partial') else ''}"
        for pid in sorted(remap.values(), key=str):
            merged.append({
                "name": "process_name", "ph": "M", "ts": 0,
                "pid": pid, "tid": 0, "args": {"name": label},
            })
            processes.append({
                "pid": pid, "tag": tag, "path": t["path"],
                "clock_domain": domain,
                "partial": bool(md.get("partial")),
            })
        for ev in t["events"]:
            ev = dict(ev)
            ev["pid"] = remap[ev["pid"]]
            if ev.get("ph") != "M":
                ev["ts"] = ev["ts"] + delta_s * 1e6
            ev["_src"] = t["path"]
            ev["_tol"] = 0.0 if t is ref else (
                # Residual clock error after the rebase: ±RTT/2 for the
                # RPC-measured offset, a generous bound for wall-clock
                # fallback (NTP-class skew), zero for the reference.
                md["clock_sync"]["rtt_s"] if domain == "rpc" else 0.05
            )
            merged.append(ev)

    _repair_flow_causality(merged)
    for ev in merged:
        ev.pop("_src", None)  # the process_name "M" rows never carried them
        ev.pop("_tol", None)

    # Normalize so the earliest real event sits at ts 0 (wall-anchored
    # deltas are epoch-sized; Perfetto handles them, humans do not).
    real_ts = [ev["ts"] for ev in merged if ev.get("ph") != "M"]
    t_min = min(real_ts) if real_ts else 0.0
    for ev in merged:
        if ev.get("ph") != "M":
            ev["ts"] -= t_min
    merged.sort(key=lambda ev: (0 if ev.get("ph") == "M" else 1, ev["ts"]))

    validate_events(merged)
    if out_format == "perfetto":
        # Binary track_event protobuf (ISSUE 8 satellite — the PR 4
        # leftover): same merged-and-validated stream, serialized for the
        # timelines the JSON loader chokes on. JSON stays the default.
        from mapreduce_rust_tpu.runtime.perfetto import write_pftrace

        write_pftrace(merged, out_path)
    elif out_format == "json":
        d = os.path.dirname(os.path.abspath(out_path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{out_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "traceEvents": merged,
                    "displayTimeUnit": "ms",
                    "metadata": {
                        "merged_from": [t["path"] for t in traces],
                        "reference": {
                            "path": ref["path"],
                            "tag": ref["md"].get("tag"),
                        },
                    },
                },
                f,
                separators=(",", ":"),
            )
        os.replace(tmp, out_path)
    else:
        raise ValueError(f"unknown trace merge format {out_format!r}")
    span_s = (max(real_ts) - t_min) / 1e6 if real_ts else 0.0
    return {
        "out": out_path,
        "events": sum(1 for ev in merged if ev.get("ph") != "M"),
        "processes": processes,
        "reference": ref["path"],
        "span_s": round(span_s, 6),
    }
