"""Control-plane telemetry + the machine-readable run manifest.

Two consumers, one module:

- **JobReport** — per-task control-plane accounting shared by the
  coordinator and the worker: state transitions (grant → renew → finish),
  lease expiries, re-executions (grants beyond the first), task durations,
  and RPC latencies. The coordinator serves its report over the new
  ``stats`` RPC and dumps it to ``{work_dir}/job_report.json`` when the
  job completes, so a BENCH probe reads structured state instead of
  re-reading stderr. Everything is plain ints/floats — JSON-serializable
  by construction, like the RPC plane it describes.

- **Run manifest** — one ``manifest.json`` per driver/bench run: config,
  platform, git rev, the full ``JobStats`` (including the
  ingest/device/host-map/host-glue wait split and ``shuffle_wire_bytes``),
  phase times, trace path, probe outcomes. ``python -m mapreduce_rust_tpu
  stats <manifest> [other]`` pretty-prints one or diffs two.

No jax import at module level: the coordinator process must be able to
build reports without dragging in a backend (same rule as runtime/trace).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

from mapreduce_rust_tpu.runtime.histogram import Histogram

MANIFEST_SCHEMA = 1


# ---------------------------------------------------------------------------
# Control-plane job report
# ---------------------------------------------------------------------------

class JobReport:
    """Per-task control-plane event log, aggregated — not per-RPC rows.

    Counters only: each record_* call is a dict update on the (phase, tid)
    slot, so a chatty renewal loop costs O(1) memory, in keeping with the
    aggregate-counters doctrine of runtime/metrics.py.
    """

    #: Ordered-event-log cap: the log exists for mrcheck's state-machine
    #: replay, and state-CHANGING events (grants, expiries, finishes,
    #: revocations — never renewals) are bounded by task count × attempts,
    #: so a real job sits far under this. The cap is a backstop against a
    #: pathological grant storm turning the report into the hot path;
    #: overflow is counted, never silent.
    EVENT_CAP = 20000

    def __init__(self, job_id: "str | None" = None, now=None) -> None:
        # Injectable clock seam (ISSUE 18): every wall-clock read in this
        # report goes through ``self._now`` so mrmodel can drive the real
        # control plane under a virtual clock. ``now=None`` keeps the
        # monotonic default — real runs are bit-identical.
        self._now = now if now is not None else time.monotonic
        # Multi-tenant job service (ISSUE 14): a per-job report carries
        # its job id on every event-log row, so a combined/multi-job
        # artifact stays per-job replayable (mrcheck keys its machines by
        # (job, phase, tid)) and a mis-routed cross-job event is
        # detectable (the grant-across-jobs invariant). None = the
        # single-job coordinator's report — rows stay unstamped, exactly
        # the pre-service wire format.
        self.job_id = job_id
        # ``row_job`` is the job stamped onto event ROWS (defaults to the
        # report identity). A multi-job WRITER — the ServiceWorker, whose
        # one report spans every job it serves — switches this per job so
        # its grant/finish rows replay under per-job machines, while its
        # report identity stays None (the report is the worker's, not any
        # one job's).
        self.row_job = job_id
        self._tasks: dict[tuple, dict] = {}  # (job-dim, phase, tid) → slot
        self._rpc: dict[str, Histogram] = {}
        # The ordered control-plane event log (mrcheck's replay substrate):
        # one row per STATE TRANSITION of the lease/attempt machine —
        # grant/speculate/expire/finish/late_finish/revoke/deregister, each
        # with (t, phase, tid, attempt, wid). Renewals are deliberately NOT
        # logged (renewed* is unbounded and extends a lease without
        # changing its state), so the log stays O(tasks), in keeping with
        # the aggregate-counters doctrine.
        self._events: list[dict] = []
        self._events_dropped = 0
        # Per-worker attribution (ISSUE 5 satellite — the PR 4 leftover):
        # wid → counters + an attempt-duration histogram. Grants, renewals
        # and finish reports carry the worker id, so `watch` shows a
        # per-worker column and the doctor's straggler pass compares each
        # worker's p50 against the fleet median.
        self._workers: dict[int, dict] = {}
        self._phase_hist: dict[str, Histogram] = {}  # attempt durations
        # Speculation accounting (ISSUE 6): per-phase attempts issued, the
        # won/wasted split once races settle, and the estimated time saved
        # vs the lease-expiry-only recovery — the doctor's
        # speculation-effectiveness input.
        self._speculation: dict[str, dict] = {}
        # Per-reduce-partition readiness (ISSUE 16): r → {bytes, shards,
        # ready_s}. Fed by map finish reports that ship their per-partition
        # intermediate-bytes vector; ``ready_s`` is the report-epoch
        # instant the LAST byte-contributing map shard for r landed — the
        # fleet profiler's pipelining-opportunity input.
        self._partitions: dict[int, dict] = {}
        # Scheduling mode stamp (ISSUE 17): "pipeline" when the producing
        # coordinator granted reduce tasks per-partition (no global map
        # barrier). Offline consumers key off this — the fleet profiler
        # stops counting the barrier window as a bubble, and the doctor's
        # barrier-bubble advice goes quiet (the opportunity is realized).
        self.sched: "str | None" = None
        self._t0 = self._now()

    def _jdim(self) -> "str | None":
        """Job dimension of the per-task aggregation: only a MULTI-job
        writer (row_job switched away from the report identity — the
        ServiceWorker) splits task slots by job; a per-job coordinator
        report (job_id == row_job) and the classic single-job world keep
        plain (phase, tid) slots. Without this a fleet member serving
        two jobs' task 0 would merge them into one row — grants=2 reads
        as a re-execution that never happened and the second job's
        duration is never recorded."""
        return self.row_job if self.row_job != self.job_id else None

    def _task(self, phase: str, tid: int) -> dict:
        key = (self._jdim(), phase, tid)
        t = self._tasks.get(key)
        if t is None:
            t = self._tasks[key] = {
                "grants": 0,
                "speculations": 0,
                "renewals": 0,
                "stale_renewals": 0,
                "expiries": 0,
                "reports": 0,
                "late_reports": 0,
                "first_grant_s": None,
                "last_grant_s": None,
                "done_s": None,
                "wid": None,
            }
        return t

    def _worker(self, wid) -> "dict | None":
        if wid is None or (isinstance(wid, int) and wid < 0):
            return None  # pre-wid client / in-process caller: per-task only
        w = self._workers.get(wid)
        if w is None:
            w = self._workers[wid] = {
                "grants": 0,
                "renewals": 0,
                "stale_renewals": 0,
                "reports": 0,
                "late_reports": 0,
                "task_s": Histogram(),
            }
        return w

    def record_event(self, ev: str, phase=None, tid=None, attempt=None,
                     wid=None) -> None:
        """Append one state-transition row to the ordered event log. The
        wall-clock context (``t``, seconds since this report's epoch) is
        what mrcheck prints next to an offending event pair."""
        if len(self._events) >= self.EVENT_CAP:
            self._events_dropped += 1
            return
        row: dict = {"t": round(self._now() - self._t0, 6), "ev": ev}
        if self.row_job is not None:
            row["job"] = self.row_job
        if phase is not None:
            row["phase"] = phase
        if tid is not None:
            row["tid"] = tid
        if attempt is not None:
            row["attempt"] = attempt
        if wid is not None and not (isinstance(wid, int) and wid < 0):
            row["wid"] = wid
        self._events.append(row)

    def events(self) -> list[dict]:
        return list(self._events)

    def attempts(self, phase: str, tid: int) -> int:
        """How many times (phase, tid) has been granted — the attempt
        number of the CURRENT grant, and the suffix of its flow id."""
        t = self._tasks.get((self._jdim(), phase, tid))
        return t["grants"] if t is not None else 0

    def task_wid(self, phase: str, tid: int) -> "int | None":
        """The worker id of the task's most recent grant (None when the
        grant was anonymous) — the speculation picker's don't-speculate-
        to-the-holder check."""
        t = self._tasks.get((self._jdim(), phase, tid))
        return t["wid"] if t is not None else None

    def phase_task_p50(self, phase: str, min_count: int = 1) -> "float | None":
        """The live attempt-duration median of a phase, or None until the
        histogram holds at least ``min_count`` samples — the speculation
        picker's slowness yardstick."""
        h = self._phase_hist.get(phase)
        if h is None or h.count < min_count:
            return None
        return h.percentile(0.5)

    def record_speculation(self, phase: str, tid: int, wid=None) -> None:
        """Mark the NEXT grant of (phase, tid) as speculative. The grant
        itself still goes through record_grant — a speculative grant IS a
        grant (the attempt number bumps, the flow chain forks); this only
        adds the speculation accounting on top."""
        self._task(phase, tid)["speculations"] += 1
        self._spec_phase(phase)["attempts"] += 1
        # Logged BEFORE the grant it arms: the replay reads "speculate then
        # grant" as one lease-SHARING attempt, not a grant-over-live-lease.
        self.record_event("speculate", phase, tid,
                          attempt=self.attempts(phase, tid) + 1, wid=wid)

    def record_revocation(self, phase: str, tid: int, wid=None) -> None:
        """A renewal was answered revoked=True: the renewing attempt lost
        a speculation race (the task is already reported). State-changing
        for that attempt (→ revoked), so it is logged."""
        self.record_event("revoke", phase, tid, wid=wid)

    def record_deregister(self, wid) -> None:
        """Graceful drain: the wid must never be granted again."""
        self.record_event("deregister", wid=wid)

    def record_speculation_result(self, phase: str, won: bool,
                                  time_saved_s: float = 0.0) -> None:
        s = self._spec_phase(phase)
        s["won" if won else "wasted"] += 1
        if won:
            s["time_saved_s"] += max(time_saved_s, 0.0)

    def _spec_phase(self, phase: str) -> dict:
        s = self._speculation.get(phase)
        if s is None:
            s = self._speculation[phase] = {
                "attempts": 0, "won": 0, "wasted": 0, "time_saved_s": 0.0,
            }
        return s

    def phase_expiries(self, phase: str) -> int:
        return sum(
            t["expiries"]
            for (_j, p, _tid), t in self._tasks.items() if p == phase
        )

    def phase_late_reports(self, phase: str) -> int:
        return sum(
            t["late_reports"]
            for (_j, p, _tid), t in self._tasks.items()
            if p == phase
        )

    def uptime_s(self) -> float:
        return self._now() - self._t0

    def record_grant(self, phase: str, tid: int, wid=None,
                     attempt=None) -> None:
        # ``attempt`` overrides the local grant count on the event row: a
        # worker's side of the log must carry the COORDINATOR's attempt
        # number (a re-execution grant arrives as attempt 2 even though it
        # is this worker's first grant of the tid).
        t = self._task(phase, tid)
        t["grants"] += 1
        now = self._now() - self._t0
        if t["first_grant_s"] is None:
            t["first_grant_s"] = now
        t["last_grant_s"] = now
        if wid is not None and not (isinstance(wid, int) and wid < 0):
            t["wid"] = wid
        w = self._worker(wid)
        if w is not None:
            w["grants"] += 1
        self.record_event("grant", phase, tid,
                          attempt=attempt or t["grants"], wid=wid)

    def record_renewal(self, phase: str, tid: int, ok: bool, wid=None) -> None:
        # Update-only: a renewal for a task this incarnation never granted
        # (a surviving worker's lease after a journal-resume restart) must
        # not fabricate a grants=0/incomplete phantom entry in the report.
        t = self._tasks.get((self._jdim(), phase, tid))
        if t is not None:
            t["renewals" if ok else "stale_renewals"] += 1
        w = self._worker(wid)
        if w is not None:
            w["renewals" if ok else "stale_renewals"] += 1

    def record_expiry(self, phase: str, tid: int) -> None:
        t = self._task(phase, tid)
        t["expiries"] += 1
        self.record_event("expire", phase, tid, attempt=t["grants"])

    def record_finish(self, phase: str, tid: int, late: bool = False,
                      wid=None, attempt=None) -> None:
        # Update-only, like record_renewal: a finish report for a task this
        # incarnation never granted (journal-resume restart) must not
        # fabricate a completed-but-never-granted entry whose duration_s
        # would be null.
        t = self._tasks.get((self._jdim(), phase, tid))
        if t is None:
            return
        self.record_event("late_finish" if late else "finish", phase, tid,
                          attempt=attempt, wid=wid)
        w = self._worker(wid)
        if late:
            # A duplicate completion (original + re-executed worker both
            # reporting the same tid) is a DISTINCT stat, not a second
            # "reports" tick: double-counting skewed task durations and
            # completion totals (ISSUE 4 satellite).
            t["late_reports"] += 1
            if w is not None:
                w["late_reports"] += 1
            return
        t["reports"] += 1
        if t["done_s"] is None:
            now = self._now() - self._t0
            t["done_s"] = now
            # Attempt duration: this grant → this (first) finish. Under a
            # re-execution the last grant belongs to the attempt that is
            # reporting, so per-worker attribution stays honest even when
            # attempt 1's worker is dead.
            if t["last_grant_s"] is not None:
                dur = max(now - t["last_grant_s"], 0.0)
                h = self._phase_hist.get(phase)
                if h is None:
                    h = self._phase_hist[phase] = Histogram()
                h.add(dur)
                if w is not None:
                    w["task_s"].add(dur)
        if w is not None:
            w["reports"] += 1

    #: Remote-input backstop: a part_bytes vector longer than this is a
    #: malformed (or hostile) report, not a real reduce_n — dropped.
    PARTITIONS_CAP = 4096

    def record_partition_ready(self, tid: int, part_bytes) -> None:
        """Fold one map task's per-reduce-partition intermediate-bytes
        vector (the trailing-default finish-report field) into the
        readiness table. Only shards that carry bytes advance ``ready_s``
        — an all-empty shard for r never gates r's pipeline start. The
        caller (report_map_task_finish) invokes this on FIRST reports
        only; duplicates re-wrote identical shard files."""
        if not isinstance(part_bytes, (list, tuple)) \
                or len(part_bytes) > self.PARTITIONS_CAP:
            return
        now = round(self._now() - self._t0, 6)
        for r, b in enumerate(part_bytes):
            if isinstance(b, bool) or not isinstance(b, (int, float)):
                return  # malformed vector: drop whole report, half a
                # vector folded in would under-count some partitions
        for r, b in enumerate(part_bytes):
            slot = self._partitions.get(r)
            if slot is None:
                slot = self._partitions[r] = {
                    "bytes": 0, "shards": 0, "ready_s": None,
                }
            slot["shards"] += 1
            if b > 0:
                slot["bytes"] += int(b)
                slot["ready_s"] = now

    def partitions_summary(self) -> dict:
        return {
            str(r): dict(slot)
            for r, slot in sorted(self._partitions.items())
        }

    def in_flight(self) -> list[tuple]:
        """(phase, tid) — or (job, phase, tid) for a multi-job writer's
        job-split slots — of tasks granted but not yet reported finished:
        leases currently held, as this side observed them."""
        return [
            key[1:] if key[0] is None else key
            for key, t in self._tasks.items()
            if t["grants"] > 0 and t["done_s"] is None
        ]

    def record_rpc(self, method: str, seconds: float) -> None:
        h = self._rpc.get(method)
        if h is None:
            h = self._rpc[method] = Histogram()
        h.add(seconds)

    def workers_summary(self) -> dict:
        """wid → counters + attempt-duration percentiles (ms): the live
        per-worker view `watch` renders and the doctor's straggler input."""
        out: dict = {}
        for wid, w in sorted(self._workers.items(), key=lambda kv: str(kv[0])):
            out[str(wid)] = {
                "grants": w["grants"],
                "renewals": w["renewals"],
                "stale_renewals": w["stale_renewals"],
                "reports": w["reports"],
                "late_reports": w["late_reports"],
                "task_s": w["task_s"].to_dict(),
            }
        return out

    def to_dict(self) -> dict:
        phases: dict[str, dict] = {}
        # Multi-job writers' slots render as "job:tid" keys (single-job
        # and per-job-coordinator reports keep plain tids — the shape
        # every existing consumer parses).
        for (jk, phase, tid), t in sorted(
            self._tasks.items(), key=lambda kv: (kv[0][0] or "", *kv[0][1:])
        ):
            duration = (
                round(t["done_s"] - t["first_grant_s"], 6)
                if t["done_s"] is not None and t["first_grant_s"] is not None
                else None
            )
            tid_key = f"{jk}:{tid}" if jk else str(tid)
            phases.setdefault(phase, {})[tid_key] = {
                "grants": t["grants"],
                "re_executions": max(t["grants"] - 1, 0),
                "speculations": t["speculations"],
                "expiries": t["expiries"],
                "renewals": t["renewals"],
                "stale_renewals": t["stale_renewals"],
                "reports": t["reports"],
                "late_reports": t["late_reports"],
                "duration_s": duration,
                "completed": t["done_s"] is not None,
                "wid": t["wid"],
            }
        totals = {
            phase: {
                "tasks": len(tasks),
                "completed": sum(1 for t in tasks.values() if t["completed"]),
                "re_executions": sum(t["re_executions"] for t in tasks.values()),
                "expiries": sum(t["expiries"] for t in tasks.values()),
                "late_reports": sum(t["late_reports"] for t in tasks.values()),
            }
            for phase, tasks in phases.items()
        }
        for phase, h in self._phase_hist.items():
            if phase in totals:
                # Attempt-duration distribution (seconds): the doctor's
                # lease-tuning input (expiries vs task p99).
                totals[phase]["task_s"] = h.to_dict()
        for phase, s in self._speculation.items():
            if phase in totals:
                totals[phase]["speculation"] = {
                    "attempts": s["attempts"],
                    "won": s["won"],
                    "wasted": s["wasted"],
                    "time_saved_s": round(s["time_saved_s"], 6),
                }
        rpc = {
            m: {
                # Keys preserved from the aggregate-counter era (count /
                # total_s / mean_ms / max_ms) plus the percentile tail the
                # doctor reads — all derived from one mergeable histogram.
                "count": h.count,
                "total_s": round(h.total, 6),
                "mean_ms": round(h.mean * 1e3, 3),
                "p50_ms": round((h.percentile(0.50) or 0.0) * 1e3, 3),
                "p95_ms": round((h.percentile(0.95) or 0.0) * 1e3, 3),
                "p99_ms": round((h.percentile(0.99) or 0.0) * 1e3, 3),
                "max_ms": round(h.max * 1e3, 3),
                "hist": h.to_dict(),
            }
            for m, h in sorted(self._rpc.items())
        }
        out = {"tasks": phases, "totals": totals, "rpc": rpc,
               "events": self.events()}
        if self.job_id is not None:
            out["job"] = self.job_id
        if self._events_dropped:
            out["events_dropped"] = self._events_dropped
        if self._workers:
            out["workers"] = self.workers_summary()
        if self._partitions:
            out["partitions"] = self.partitions_summary()
        if self.sched is not None:
            out["sched"] = self.sched
        return out

    def summary(self) -> str:
        d = self.to_dict()
        parts = []
        for phase, tot in d["totals"].items():
            parts.append(
                f"{phase}: {tot['completed']}/{tot['tasks']} done, "
                f"{tot['expiries']} expiries, {tot['re_executions']} re-execs"
            )
        n_rpc = sum(r["count"] for r in d["rpc"].values())
        parts.append(f"{n_rpc} RPCs")
        return "; ".join(parts)


def format_progress(stats: dict) -> str:
    """Plain-text live job view of a coordinator ``stats`` RPC response —
    what the ``watch`` subcommand repaints at 1 Hz. Degrades gracefully on
    a pre-progress coordinator (totals only)."""
    prog = stats.get("progress") or {}
    workers = prog.get("workers") or {}
    drained = workers.get("drained") or []
    lines = [
        f"coordinator: phase {prog.get('phase', '?')}"
        f" · workers {workers.get('registered', '?')}/{workers.get('expected', '?')}"
        + (
            f" ({len(drained)} drained: "
            + ", ".join(f"w{w}" for w in drained) + ")"
            if drained else ""
        )
        + f" · up {prog.get('uptime_s', 0.0):.1f}s"
    ]
    totals = stats.get("totals") or {}
    for name in ("map", "reduce"):
        spec = (totals.get(name) or {}).get("speculation")
        ph = (prog.get("phases") or {}).get(name)
        if ph is None:
            tot = totals.get(name)
            if tot:
                lines.append(
                    f"  {name:<7} {tot['completed']}/{tot['tasks']} done"
                )
            continue
        n = ph["tasks_total"]
        done = ph["done"]
        width = 24
        filled = int(width * done / n) if n else width
        bar = "#" * filled + "-" * (width - filled)
        lines.append(
            f"  {name:<7} [{bar}] {done}/{n} done · "
            f"{ph['in_flight']} in-flight · {ph['pending']} pending · "
            f"{ph['expired']} expired · {ph['late_reports']} late"
            + (
                f" · spec {spec['won']}w/{spec['wasted']}x"
                f"/{spec['attempts']}a"
                if spec and spec.get("attempts") else ""
            )
        )
        for tid, lease in sorted(
            (ph.get("leases") or {}).items(), key=lambda kv: int(kv[0])
        ):
            since = lease.get("since_activity_s")
            since_s = f"{since:.1f}s ago" if since is not None else "never"
            state = "live" if lease.get("live") else "STALE"
            lines.append(
                f"    task {tid:>3}  attempt {lease['attempt']}  "
                f"lease {lease['lease_remaining_s']:+.1f}s  "
                f"renewed {since_s}  [{state}]"
            )
    by_worker = stats.get("workers") or {}
    for wid, w in sorted(by_worker.items(), key=lambda kv: str(kv[0])):
        ts = w.get("task_s") or {}
        p50 = ts.get("p50")
        lines.append(
            f"  w{wid}: {w.get('reports', 0)} done · "
            f"{w.get('grants', 0)} grants · {w.get('renewals', 0)} renewals"
            + (f" · task p50 {p50:.2f}s" if p50 is not None else "")
        )
    rpc = stats.get("rpc") or {}
    if rpc:
        calls = sum(r["count"] for r in rpc.values())
        total_s = sum(r["total_s"] for r in rpc.values())
        max_ms = max(r["max_ms"] for r in rpc.values())
        lines.append(
            f"  rpc: {calls} calls · mean "
            f"{total_s / calls * 1e3 if calls else 0.0:.2f} ms · "
            f"max {max_ms:.2f} ms"
        )
    if prog.get("done"):
        lines.append("  job complete")
    return "\n".join(lines)


def format_jobs(view: dict) -> str:
    """Plain-text service-wide queue/running/done table of a JobService
    ``list_jobs`` RPC response — what ``watch`` (no --job) and the
    ``jobs`` subcommand render. One row per job, newest done last."""
    sv = view.get("service") or {}
    cache = sv.get("cache") or {}
    lines = [
        f"service: {sv.get('running', 0)} running · "
        f"{sv.get('queued', 0)} queued · {sv.get('done', 0)} done · "
        f"workers {sv.get('workers', 0)}"
        + (f" ({len(sv['drained'])} drained)" if sv.get("drained") else "")
        # MiB, matching the service_inflight_budget_mb knob (mb << 20):
        # the displayed budget must equal the configured number.
        + f" · inflight {sv.get('inflight_bytes', 0) / (1 << 20):.1f}"
        f"/{sv.get('budget_bytes', 0) / (1 << 20):.1f} MB"
        + (" [SATURATED]" if sv.get("admission_blocked") else "")
        + (" [DRAINING]" if sv.get("draining") else "")
        + f" · cache {cache.get('hits', 0)}h/{cache.get('misses', 0)}m"
        f"/{cache.get('entries', 0)}e"
        + f" · up {sv.get('uptime_s', 0.0):.1f}s"
    ]
    rows = view.get("jobs") or []
    if rows:
        lines.append(
            f"  {'JOB':<8} {'STATE':<9} {'APP':<15} {'PRI':>3} "
            f"{'WAIT':>7} {'RUN':>7}  TASKS"
        )
    for j in rows:
        tasks = j.get("tasks") or {}
        task_s = " ".join(
            f"{p} {t.get('done', 0)}/{t.get('total', 0)}"
            for p, t in sorted(tasks.items())
        ) or ("cache hit" if j.get("cached") else "-")
        wait = j.get("queue_wait_s")
        run = j.get("run_s")
        lines.append(
            f"  {j.get('job', '?'):<8} {j.get('state', '?'):<9} "
            f"{j.get('app', '?'):<15} {j.get('priority', 0):>3} "
            f"{(f'{wait:.1f}s' if wait is not None else '-'):>7} "
            f"{(f'{run:.1f}s' if run is not None else '-'):>7}  {task_s}"
            + (f"  [{j['error']}]" if j.get("error") else "")
        )
    # Live fleet series (ISSUE 16): per-worker utilization + current job
    # from the service's fleet_view(). Absent on pre-fleet services —
    # the table renders without the block.
    fl = sv.get("fleet_util") or {}
    workers = fl.get("workers") or {}
    if workers:
        lines.append(
            f"  fleet: util {fl.get('util_frac', 0.0):.0%} · "
            f"bubble {fl.get('bubble_frac', 0.0):.0%}"
        )
        lines.append(f"  {'WID':>5} {'UTIL':>5} {'GRANTS':>6}  CURRENT")
        for wid in sorted(workers, key=lambda w: int(w)):
            row = workers[wid]
            cur = "-"
            if row.get("drained"):
                cur = "(drained)"
            elif row.get("job") is not None:
                cur = f"{row['job']}:{row.get('phase', '?')}"
            lines.append(
                f"  {wid:>5} {row.get('util_frac', 0.0):>5.0%} "
                f"{row.get('grants', 0):>6}  {cur}"
            )
    return "\n".join(lines)


def write_job_report(path: str, report) -> str:
    """``report`` is a JobReport or an already-snapshotted to_dict()
    dict — the latter lets a server snapshot ON its event loop (where
    the report mutates) and ship only the JSON dump + file write to an
    executor thread (blocking-in-async doctrine)."""
    return write_manifest(path, {
        "schema": MANIFEST_SCHEMA,
        "kind": "job_report",
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "report": report.to_dict() if isinstance(report, JobReport)
        else report,
    })


# ---------------------------------------------------------------------------
# Run manifest
# ---------------------------------------------------------------------------

def git_rev(repo_dir: str | None = None) -> str | None:
    """Current commit hash, or None outside a repo / without git."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=repo_dir or os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
            capture_output=True, text=True, timeout=5,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.SubprocessError):
        return None


def platform_info() -> dict:
    """Host + (when already imported) jax/device identity. Never imports
    jax itself: a control-plane manifest must not initialize a backend."""
    import platform as _platform

    info: dict = {
        "python": sys.version.split()[0],
        "machine": _platform.machine(),
        "system": _platform.system(),
        "hostname": _platform.node(),
        "pid": os.getpid(),
    }
    jax = sys.modules.get("jax")
    if jax is not None:
        info["jax"] = jax.__version__
        try:
            from jax._src import xla_bridge

            if not xla_bridge._backends:
                # jax imported but no backend initialized: jax.devices()
                # here would TRIGGER init — the exact wedge class the
                # worker gauge hit in PR 6, hiding in a manifest flush. A
                # manifest from such a process simply omits device
                # identity (mrlint: backend-init-in-probe).
                return info
            devs = jax.devices()
            info["backend"] = devs[0].platform
            info["device_count"] = len(devs)
            info["process_count"] = jax.process_count()
        except Exception:  # backend probe failed — manifest still writes
            info["backend"] = "unavailable"
    return info


def stats_to_dict(stats) -> dict:
    """Every JobStats field (the full dataclass — including the
    ingest/device/host-map/host-glue wait split and shuffle_wire_bytes)
    plus the derived properties and two structured attributions:

    - ``host_map_split`` (host-map engine runs): scan vs glue vs device,
      with the worker count, the consumer's scan-stall time and the scan
      arenas' resident bytes — what the next BENCH round reads to see
      where the ceiling moved after the fan-out.
    - ``ici_split`` (mesh runs): all_to_all block seconds vs the rest of
      the stream phase, with rounds and wire bytes — interconnect vs
      compute, before any multi-chip perf claim.
    """
    d = dataclasses.asdict(stats)
    # The raw hists field holds Histogram objects (asdict deep-copies them
    # verbatim); serialize into the manifest's "histograms" block instead —
    # sparse buckets + precomputed p50/p95/p99, mergeable across runs.
    d.pop("hists", None)
    d["histograms"] = {
        name: h.to_dict() for name, h in sorted(stats.hists.items())
    }
    if stats.compile_count:
        d["compile"] = {
            "count": stats.compile_count,
            "total_s": round(stats.compile_s, 6),
            "cache_hits": stats.compile_cache_hits,
            "cache_misses": stats.compile_cache_misses,
        }
    d["gb_per_s"] = stats.gb_per_s
    d["bottleneck"] = stats.bottleneck
    stream_s = stats.phase_seconds.get("stream", 0.0)
    if stats.host_map_workers > 0:
        d["host_map_split"] = {
            "workers": stats.host_map_workers,
            "scan_s": round(stats.host_map_s, 6),          # aggregate, all workers
            "scan_stall_s": round(stats.scan_wait_s, 6),   # consumer starved
            "glue_s": round(stats.host_glue_s, 6),
            "device_wait_s": round(stats.device_wait_s, 6),
            "arena_bytes": stats.host_arena_bytes,
            # scan seconds actually overlapped per worker per stream second;
            # ~1.0 at W=1, → W when the fan-out scales perfectly
            "scan_parallelism": (
                round(stats.host_map_s / stream_s, 3) if stream_s else None
            ),
        }
    if stats.fold_shards > 1:
        shard_s = [round(v, 6) for v in stats.fold_shard_s]
        mean = (sum(shard_s) / len(shard_s)) if shard_s else 0.0
        d["fold_split"] = {
            "shards": stats.fold_shards,
            # per_shard_s sums to fold_s by construction: the per-shard
            # balance the doctor's fold-shard-skew finding scores.
            "fold_s": round(stats.fold_s, 6),
            "fold_stall_s": round(stats.fold_stall_s, 6),
            "per_shard_s": shard_s,
            "per_shard_idle_s": [round(v, 6) for v in stats.fold_shard_idle_s],
            # 1.0 = perfectly balanced; 2.0 = the hottest shard folds twice
            # its fair share (same convention as the doctor's skew scores).
            "balance": (
                round(max(shard_s) / mean, 3) if shard_s and mean else None
            ),
            # fold seconds overlapped per stream second — → S when the
            # sharded fold scales perfectly (the host_map_split twin).
            "fold_parallelism": (
                round(stats.fold_s / stream_s, 3) if stream_s else None
            ),
        }
    if stats.merge_dispatches > 0:
        # Device-merge dispatch plane (ISSUE 13): which plane ran (async /
        # sync, coalesced or not), dispatch-thread seconds (overlapped
        # time made visible), router backpressure, dispatch count and the
        # mean update fill — the raise-cap-vs-threshold evidence the
        # doctor's merge-dispatch finding reads.
        d["dispatch_split"] = {
            "mode": stats.dispatch_mode,
            "dispatch_s": round(stats.dispatch_s, 6),
            "stall_s": round(stats.dispatch_stall_s, 6),
            "dispatches": stats.merge_dispatches,
            "fill_frac": round(stats.merge_fill_frac, 6),
            # dispatch seconds overlapped per stream second — >0 on the
            # async plane means the sync plane would have added that
            # fraction to the router's wall (the spill write_overlap twin).
            "dispatch_overlap": (
                round(stats.dispatch_s / stream_s, 3) if stream_s else None
            ),
        }
    if stats.dict_spill_runs or stats.accum_spill_runs or stats.spill_bytes:
        # Binary async spill plane (ISSUE 11): the disk-tier attribution —
        # writer seconds (overlapped with compute), owner stall seconds
        # (backpressure = "the disk is the ceiling"), bytes, run counts,
        # the egress merge fan-in, and the run format so every manifest
        # says which plane produced its numbers.
        from mapreduce_rust_tpu.runtime.spill import RUN_FORMAT

        d["spill_split"] = {
            "format": RUN_FORMAT,
            "write_s": round(stats.spill_s, 6),
            "stall_s": round(stats.spill_stall_s, 6),
            "bytes": stats.spill_bytes,
            "dict_runs": stats.dict_spill_runs,
            "accum_runs": stats.accum_spill_runs,
            "merge_fanin": stats.merge_fanin,
            # writer seconds overlapped per stream second — >0 means the
            # old sync plane would have added that fraction to the wall.
            "write_overlap": (
                round(stats.spill_s / stream_s, 3) if stream_s else None
            ),
        }
    if stats.mesh_rounds > 0:
        d["ici_split"] = {
            "rounds": stats.mesh_rounds,
            "all_to_all_s": round(stats.all_to_all_s, 6),
            "device_wait_s": round(stats.device_wait_s, 6),
            "stream_s": round(stream_s, 6),
            "stream_other_s": round(
                max(stream_s - stats.all_to_all_s - stats.device_wait_s, 0.0), 6
            ),
            "wire_bytes": stats.shuffle_wire_bytes,
            "wire_mb_per_s": (
                round(stats.shuffle_wire_bytes / stats.all_to_all_s / 1e6, 3)
                if stats.all_to_all_s else None
            ),
        }
    return d


def build_manifest(cfg, stats=None, app_name: str | None = None,
                   inputs=None, output_files=None, trace_path: str | None = None,
                   probes=None, extra: dict | None = None) -> dict:
    """Assemble one run's manifest dict. ``cfg`` may be a Config (asdict'd)
    or a plain dict (bench harness config); everything else is optional so
    partial failures still produce a manifest naming what ran."""
    m: dict = {
        "schema": MANIFEST_SCHEMA,
        "kind": "run_manifest",
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_rev": git_rev(),
        "platform": platform_info(),
        "argv": list(sys.argv),
    }
    if cfg is not None:
        m["config"] = cfg if isinstance(cfg, dict) else dataclasses.asdict(cfg)
    if app_name is not None:
        m["app"] = app_name
    if inputs is not None:
        m["inputs"] = [str(p) for p in inputs]
    if output_files is not None:
        m["output_files"] = [str(p) for p in output_files]
    if stats is not None:
        m["stats"] = stats_to_dict(stats)
        m["phase_seconds"] = dict(stats.phase_seconds)
    if trace_path is not None:
        m["trace_path"] = os.path.abspath(trace_path)
    if probes is not None:
        m["probes"] = probes
    if extra:
        m.update(extra)
    # Live metrics ring (ISSUE 8): whatever registry is active in THIS
    # process serializes into stats.timeseries — for the driver beside the
    # full JobStats dict, for the coordinator/worker (no JobStats in their
    # manifests) as the stats block's only member. A final forced sample
    # first, so even a sub-period run carries at least one point.
    try:
        from mapreduce_rust_tpu.runtime.metrics import active_registry

        reg = active_registry()
        if reg is not None:
            stats_block = m.setdefault("stats", {})
            if "timeseries" not in stats_block:
                # An explicit ring in ``extra`` wins: the coordinator owns
                # an instance registry (in-process clusters share this
                # process with workers, whose rings own the global slot).
                reg.maybe_sample(force=True)
                stats_block["timeseries"] = reg.timeseries_dict()
    except Exception:
        pass  # telemetry stays best-effort
    # Sampling profile (ISSUE 19) — same pattern: whatever profiler is
    # active in THIS process lands as stats.profile (per-plane self-time
    # split, top-N frames, collapsed stacks), read back by the jax-free
    # `prof` subcommand and the doctor's roofline findings.
    try:
        from mapreduce_rust_tpu.runtime.prof import active_profiler

        p = active_profiler()
        if p is not None:
            stats_block = m.setdefault("stats", {})
            if "profile" not in stats_block:
                stats_block["profile"] = p.profile_dict()
    except Exception:
        pass  # telemetry stays best-effort
    # Provenance ledger (ISSUE 20) — same pattern: whatever ledger is
    # active in THIS process lands as stats.lineage (counts + folded
    # corpus digests + the jsonl path), read back by the jax-free
    # `lineage` subcommand and the doctor's incremental-opportunity
    # finding. Summary only: the per-chunk records stay in the jsonl.
    try:
        from mapreduce_rust_tpu.runtime.lineage import active_ledger

        led = active_ledger()
        if led is not None:
            stats_block = m.setdefault("stats", {})
            if "lineage" not in stats_block:
                stats_block["lineage"] = led.lineage_dict()
    except Exception:
        pass  # telemetry stays best-effort
    return m


def write_manifest(path: str, manifest: dict) -> str:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_manifest(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def flush_run_artifacts(cfg, tracer=None, tag: str | None = None,
                        logger=None, **manifest_fields) -> str | None:
    """End-of-run teardown shared by the driver, worker and coordinator:
    write the tracer's buffer to ``cfg.trace_path`` and a manifest to
    ``cfg.manifest_path`` (both suffixed per-process when ``tag`` is given
    — co-hosted processes must never clobber each other's files). Strictly
    best-effort: nothing here may raise, or telemetry would mask the run's
    real outcome. Returns the trace file path (or None)."""
    from mapreduce_rust_tpu.runtime.trace import per_process_path

    if tracer is not None:
        # Per-round mesh.all_to_all span durations, aggregated (count /
        # total / mean / max): the traced complement of stats.ici_split —
        # wall attribution per collective round, not just the stream total.
        try:
            rounds = tracer.summarize("mesh.all_to_all")
            if rounds:
                extra = dict(manifest_fields.get("extra") or {})
                extra["mesh_round_spans"] = rounds
                manifest_fields["extra"] = extra
        except Exception:
            pass  # telemetry stays best-effort

    trace_file = None
    if tracer is not None and cfg.trace_path:
        try:
            path = per_process_path(cfg.trace_path, tag) if tag else cfg.trace_path
            trace_file = tracer.write(path)
            if logger:
                logger.info("trace: %d spans → %s", len(tracer), trace_file)
        except Exception as e:
            if logger:
                logger.warning("trace write failed: %s", e)
    if cfg.manifest_path:
        try:
            path = (
                per_process_path(cfg.manifest_path, tag) if tag
                else cfg.manifest_path
            )
            write_manifest(path, build_manifest(
                cfg, trace_path=trace_file, **manifest_fields
            ))
            if logger:
                logger.info("manifest → %s", path)
            # Collapsed-stack export beside the manifest (ISSUE 19):
            # flamegraph.pl / speedscope load the .folded directly;
            # `prof --folded` re-derives the same lines from the
            # manifest's stats.profile for files shipped elsewhere.
            try:
                from mapreduce_rust_tpu.runtime.prof import active_profiler

                p = active_profiler()
                if p is not None:
                    folded = os.path.splitext(path)[0] + ".folded"
                    p.write_folded(folded)
                    if logger:
                        logger.info("profile → %s", folded)
            except Exception:
                pass  # telemetry stays best-effort
        except Exception as e:
            if logger:
                logger.warning("manifest write failed: %s", e)
    return trace_file


def _flatten(d: dict, prefix: str = "") -> dict:
    out: dict = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def format_manifest(m: dict) -> str:
    """Human view of one manifest: identity header, then the stats that
    decide a BENCH verdict, then phase times."""
    lines = [
        f"run manifest (schema {m.get('schema')}) — {m.get('created')}",
        f"  app: {m.get('app', '?')}  git: {str(m.get('git_rev'))[:12]}",
    ]
    p = m.get("platform", {})
    lines.append(
        f"  platform: {p.get('backend', 'none')} x{p.get('device_count', '?')} "
        f"jax={p.get('jax', '-')} python={p.get('python', '?')} ({p.get('machine', '?')})"
    )
    s = m.get("stats")
    if s:
        lines.append(
            f"  {s['bytes_in'] / 1e6:.2f} MB in {s['wall_seconds']:.3f}s "
            f"({s['gb_per_s']:.4f} GB/s) — bottleneck: {s['bottleneck']}"
        )
        lines.append(
            f"  distinct={s['distinct_keys']} chunks={s['chunks']} "
            f"spills={s['spill_events']}({s['spilled_keys']} keys) "
            f"replays={s['partial_overflow_replays']}+{s['bucket_skew_replays']}skew "
            f"collisions={s['hash_collisions']} unknown={s['unknown_keys']}"
        )
        lines.append(
            f"  shuffle: {s['mesh_rounds']} rounds, "
            f"{s['shuffle_wire_bytes'] / 1e6:.1f} MB wire"
        )
        lines.append(
            f"  waits: ingest={s['ingest_wait_s']:.3f}s device={s['device_wait_s']:.3f}s "
            f"host_map={s['host_map_s']:.3f}s host_glue={s['host_glue_s']:.3f}s"
        )
        hm = s.get("host_map_split")
        if hm:
            lines.append(
                f"  host-map split: {hm['workers']} workers, "
                f"scan={hm['scan_s']:.3f}s (x{hm['scan_parallelism'] or 0:.2f} "
                f"parallel), stall={hm['scan_stall_s']:.3f}s "
                f"glue={hm['glue_s']:.3f}s device={hm['device_wait_s']:.3f}s "
                f"arenas={hm['arena_bytes'] / 1e6:.0f} MB"
            )
        fs = s.get("fold_split")
        if fs:
            lines.append(
                f"  fold split: {fs['shards']} shards, "
                f"fold={fs['fold_s']:.3f}s "
                f"(x{fs['fold_parallelism'] or 0:.2f} parallel, "
                f"balance {fs['balance'] or 0:.2f}) "
                f"stall={fs['fold_stall_s']:.3f}s"
            )
        dp = s.get("dispatch_split")
        if dp:
            lines.append(
                f"  dispatch split [{dp['mode']}]: "
                f"dispatch={dp['dispatch_s']:.3f}s "
                f"stall={dp['stall_s']:.3f}s "
                f"{dp['dispatches']} merges "
                f"(fill {dp['fill_frac']:.2f})"
            )
        sp = s.get("spill_split")
        if sp:
            lines.append(
                f"  spill split [{sp.get('format')}]: "
                f"write={sp['write_s']:.3f}s stall={sp['stall_s']:.3f}s "
                f"{sp['bytes'] / 1e6:.1f} MB in "
                f"{sp['dict_runs']}+{sp['accum_runs']} runs "
                f"(egress fan-in {sp['merge_fanin']})"
            )
        ici = s.get("ici_split")
        if ici:
            lines.append(
                f"  ICI split: all_to_all={ici['all_to_all_s']:.3f}s "
                f"drain={ici['device_wait_s']:.3f}s "
                f"other={ici['stream_other_s']:.3f}s of {ici['stream_s']:.3f}s "
                f"stream ({ici['rounds']} rounds, "
                f"{ici['wire_bytes'] / 1e6:.1f} MB wire)"
            )
        comp = s.get("compile")
        if comp:
            lines.append(
                f"  compile: {comp['count']} XLA compiles, "
                f"{comp['total_s']:.2f}s ({comp['cache_hits']} cache hits, "
                f"{comp['cache_misses']} misses)"
            )
        if s.get("device_mem_high_bytes"):
            lines.append(
                f"  device memory high-water: "
                f"{s['device_mem_high_bytes'] / 1e6:.1f} MB"
            )
        for name, h in sorted((s.get("histograms") or {}).items()):
            if not h.get("count"):
                continue
            unit = 1e3 if name.endswith("_s") else 1.0  # seconds → ms
            lines.append(
                f"  hist {name:<18} n={h['count']:<6} "
                f"p50={h['p50'] * unit:.3g} p95={h['p95'] * unit:.3g} "
                f"p99={h['p99'] * unit:.3g} max={h['max'] * unit:.3g}"
                + (" ms" if unit == 1e3 else "")
            )
    for name, secs in (m.get("phase_seconds") or {}).items():
        lines.append(f"  phase {name:<10} {secs:8.3f}s")
    if m.get("trace_path"):
        lines.append(f"  trace: {m['trace_path']}")
    for probe in m.get("probes") or []:
        status = "ok" if probe.get("ok") else f"FAILED ({probe.get('error', '?')})"
        lines.append(f"  probe {probe.get('leg', '?'):<14} {status}")
    return "\n".join(lines)


def diff_manifests(a: dict, b: dict) -> list[str]:
    """Field-level diff of two manifests, numeric fields with deltas —
    the BENCH round-over-round comparison, machine-checkable."""
    fa, fb = _flatten(a), _flatten(b)
    skip = ("created", "argv", "platform.pid", "platform.hostname")
    lines = []
    for key in sorted(set(fa) | set(fb)):
        if key.startswith(skip) or key in skip:
            continue
        # Raw histogram internals (sparse bucket maps, embedded hist
        # copies), the ordered event log (mrcheck's replay substrate) and
        # the live time-series ring (wall-clock-stamped points — they
        # differ every run by construction): the aggregate fields beside
        # them carry the comparable signal.
        if any(seg in ("buckets", "hist", "events", "timeseries")
               for seg in key.split(".")):
            continue
        va, vb = fa.get(key, "<absent>"), fb.get(key, "<absent>")
        if va == vb:
            continue
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)) \
                and not isinstance(va, bool) and not isinstance(vb, bool):
            delta = vb - va
            rel = f" ({delta / va:+.1%})" if va else ""
            lines.append(f"  {key}: {va} -> {vb} [{delta:+g}{rel}]")
        else:
            lines.append(f"  {key}: {va!r} -> {vb!r}")
    return lines
