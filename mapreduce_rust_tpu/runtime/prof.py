"""mrprof: in-process sampling profiler for the data plane (ISSUE 19).

One sampler thread walks ``sys._current_frames()`` at ~97 Hz (a prime
rate, so the sampler never phase-locks with 1 ms/10 ms periodic work)
and aggregates collapsed stacks keyed by the stable plane-thread names
satellite 1 establishes (``mr/scan-*``, ``mr/fold-*``, ``mr/spill-*``,
``mr/dispatch``, ``mr/ingest``, the router/consumer on ``MainThread``).
Everything is observational — the sampler takes no lock any plane thread
holds and mutates nothing the data plane reads, so outputs are
bit-identical profile ON vs OFF and the tax is bounded by the bench's
interleaved ``--profile-overhead`` leg (≤ 2 % wall).

Memory is bounded by a capped frame table (distinct code locations) and
a capped stack table (distinct collapsed stacks); past either cap new
entries fold into a reserved overflow bucket instead of growing, so a
pathological workload cannot balloon the profile.

Lifecycle mirrors the metrics plane (metrics.py): a process-global slot
installed by the run owner beside ``start_metrics``, compare-and-clear
teardown, ``active_profiler()`` for the manifest embed. The live
profiler also rides the flight recorder (``tracer.profiler``) so a
SIGKILLed run keeps its flamegraph in the ``*.partial.json``, and it
feeds per-plane self-time counter tracks into the tracer for the
``trace merge`` Perfetto path.

This module is jax-free stdlib-only: the ``prof`` CLI and the manifest
reader import it from any process.
"""

from __future__ import annotations

import os
import sys
import threading
import time

DEFAULT_HZ = 97.0          # prime — avoids aliasing with 10/100 Hz work
MAX_FRAMES = 8192          # distinct (file, firstlineno, func) entries
MAX_STACKS = 8192          # distinct collapsed stacks
MAX_DEPTH = 64             # frames kept per stack (deepest dropped first)
TOP_N = 20                 # frames reported in the manifest block
COUNTER_PERIOD_S = 1.0     # per-plane tracer counter cadence

# Thread-name prefix -> plane. Order matters (longest prefix first).
# MainThread is the router/consumer: the host-map engine folds window
# results and drives dispatch handoff from the calling thread.
_PLANE_PREFIXES = (
    ("mr/scan", "scan"),
    ("mr/fold", "fold"),
    ("mr/spill", "spill"),
    ("mr/dispatch", "dispatch"),
    ("mr/ingest", "ingest"),
    ("mr/metrics", "metrics"),
    ("mr/prof", "prof"),
)


def plane_of(thread_name: str) -> str:
    """Map a plane-thread name (satellite 1's ``mr/`` scheme) to its
    plane. Unknown threads land in ``other`` rather than vanishing —
    a rename regression shows up as an ``other`` bulge, not silence."""
    for prefix, plane in _PLANE_PREFIXES:
        if thread_name.startswith(prefix):
            return plane
    if thread_name == "MainThread":
        return "router"
    return "other"


class SamplingProfiler:
    """The sampler + aggregate. ``start()``/``stop()`` own the thread;
    every read path (``profile_dict``, ``folded_lines``) snapshots under
    the same small lock the sampler aggregates under, so a manifest
    flush or flight-recorder partial can read a LIVE profile."""

    def __init__(self, hz: float = DEFAULT_HZ, max_frames: int = MAX_FRAMES,
                 max_stacks: int = MAX_STACKS, max_depth: int = MAX_DEPTH):
        self.hz = float(hz)
        self.period_s = 1.0 / self.hz
        self.max_frames = int(max_frames)
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._lock = threading.Lock()
        # Frame table: (filename, firstlineno, name) -> small int id.
        # Rendered lazily; id 0 is the reserved overflow frame.
        self._frame_ids: dict = {None: 0}
        self._frame_strs: list = ["<frame-table-full>"]
        self._frames_dropped = 0
        # (plane, thread_name, frame-id tuple root..leaf) -> sample count
        self._stacks: dict = {}
        self._stacks_dropped = 0
        self._plane_samples: dict = {}   # plane -> leaf samples
        self._leaf_samples: dict = {}    # frame id -> leaf samples
        self._ticks = 0
        self._samples = 0
        self._t0 = time.perf_counter()
        self._t1: "float | None" = None
        self._stop_evt = threading.Event()
        self._thread: "threading.Thread | None" = None
        # Optional live tracer: the sampler publishes per-plane self-time
        # counter tracks through it (Chrome "C" events -> trace merge).
        self.tracer = None
        self._last_counter_t = 0.0

    # -- sampling -----------------------------------------------------

    def start(self) -> "SamplingProfiler":
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(
            target=self._loop, name="mr/prof-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        if self._t1 is None:
            self._t1 = time.perf_counter()

    def _loop(self) -> None:
        my_ident = threading.get_ident()
        while not self._stop_evt.wait(self.period_s):
            try:
                self._sample_once(my_ident)
            except Exception:
                # The profiler must never fail the run. A torn frame walk
                # (thread died mid-iteration) just skips the tick.
                pass
        self._t1 = time.perf_counter()

    def _sample_once(self, my_ident: int) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        now = time.perf_counter()
        with self._lock:
            self._ticks += 1
            for ident, frame in frames.items():
                if ident == my_ident:
                    continue
                name = names.get(ident)
                if name is None:
                    continue  # thread died between enumerate and walk
                self._record(name, frame)
        self._maybe_publish_counters(now)

    def _record(self, thread_name: str, frame) -> None:
        # Walk leaf -> root, then reverse: collapsed stacks read
        # root-left, leaf-right.
        ids = []
        f = frame
        while f is not None and len(ids) < self.max_depth:
            code = f.f_code
            key = (code.co_filename, code.co_firstlineno, code.co_name)
            fid = self._frame_ids.get(key)
            if fid is None:
                if len(self._frame_strs) >= self.max_frames:
                    fid = 0  # capped: fold into the overflow frame
                    self._frames_dropped += 1
                else:
                    fid = len(self._frame_strs)
                    self._frame_ids[key] = fid
                    base = os.path.basename(code.co_filename)
                    self._frame_strs.append(
                        _clean(f"{base}:{code.co_name}:{code.co_firstlineno}")
                    )
            ids.append(fid)
            f = f.f_back
        if not ids:
            return
        leaf = ids[0]
        ids.reverse()
        plane = plane_of(thread_name)
        skey = (plane, thread_name, tuple(ids))
        n = self._stacks.get(skey)
        if n is None and len(self._stacks) >= self.max_stacks:
            skey = (plane, thread_name, (0,))  # overflow stack
            n = self._stacks.get(skey)
            self._stacks_dropped += 1
        self._stacks[skey] = (n or 0) + 1
        self._plane_samples[plane] = self._plane_samples.get(plane, 0) + 1
        self._leaf_samples[leaf] = self._leaf_samples.get(leaf, 0) + 1
        self._samples += 1

    def _maybe_publish_counters(self, now: float) -> None:
        tr = self.tracer
        if tr is None or now - self._last_counter_t < COUNTER_PERIOD_S:
            return
        self._last_counter_t = now
        try:
            with self._lock:
                split = self._self_seconds_locked()
            for plane, s in sorted(split.items()):
                tr.counter(f"prof.self_s.{plane}", seconds=round(s, 4))
        except Exception:
            pass  # observational: never fail the run

    # -- aggregate views ----------------------------------------------

    def wall_s(self) -> float:
        end = self._t1 if self._t1 is not None else time.perf_counter()
        return max(end - self._t0, 0.0)

    def _self_seconds_locked(self) -> dict:
        # Self-time per plane in THREAD-seconds: each tick distributes
        # (wall / ticks) to every sampled thread's leaf plane, so a
        # single-busy-thread run's plane split sums to ~wall and an
        # N-thread run sums to ~N*wall (CPU-time semantics). Scaling by
        # measured wall/ticks (not the nominal period) keeps the sum
        # honest even when sampling runs slow under load.
        ticks = self._ticks
        if ticks == 0:
            return {}
        tick_s = self.wall_s() / ticks
        return {p: n * tick_s for p, n in self._plane_samples.items()}

    def profile_dict(self) -> dict:
        """The manifest block (``stats.profile``): per-plane self-time
        split, top-N hottest frames, the collapsed stacks (top by count,
        enough for ``prof --folded`` to reconstruct a flamegraph), and
        the sampler's own accounting."""
        with self._lock:
            split = self._self_seconds_locked()
            ticks = self._ticks
            tick_s = (self.wall_s() / ticks) if ticks else 0.0
            total = self._samples
            top = sorted(self._leaf_samples.items(),
                         key=lambda kv: (-kv[1], kv[0]))[:TOP_N]
            top_frames = [
                {"frame": self._frame_strs[fid], "samples": n,
                 "self_s": round(n * tick_s, 4),
                 "pct": round(100.0 * n / total, 2) if total else 0.0}
                for fid, n in top
            ]
            folded = self._folded_lines_locked()
            return {
                "hz": self.hz,
                "wall_s": round(self.wall_s(), 4),
                "ticks": ticks,
                "samples": total,
                "planes": {
                    p: {"samples": self._plane_samples.get(p, 0),
                        "self_s": round(s, 4)}
                    for p, s in sorted(split.items())
                },
                "top_frames": top_frames,
                "stacks": folded,
                "frame_table": {
                    "entries": len(self._frame_strs),
                    "cap": self.max_frames,
                    "dropped": self._frames_dropped,
                },
                "stack_table": {
                    "entries": len(self._stacks),
                    "cap": self.max_stacks,
                    "dropped": self._stacks_dropped,
                },
            }

    def _folded_lines_locked(self, limit: int = 512) -> list:
        rows = sorted(self._stacks.items(),
                      key=lambda kv: (-kv[1], kv[0][1]))[:limit]
        out = []
        for (plane, tname, ids), n in rows:
            stack = ";".join([_clean(tname)] +
                             [self._frame_strs[i] for i in ids])
            out.append(f"{stack} {n}")
        return out

    def folded_lines(self, limit: int = 512) -> list:
        """Collapsed-stack lines (``frame;frame;... count``), thread
        name as the root frame — flamegraph.pl / speedscope load these
        directly."""
        with self._lock:
            return self._folded_lines_locked(limit)

    def write_folded(self, path: str) -> str:
        lines = self.folded_lines()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        os.replace(tmp, path)
        return path


def _clean(frame: str) -> str:
    """Folded-format frames must not contain the two separators (';'
    between frames, ' ' before the count)."""
    return frame.replace(";", "_").replace(" ", "_")


# ---------------------------------------------------------------------------
# Process-global lifecycle — the metrics.py pattern: one profiler per
# run, installed by the run owner beside start_metrics, compare-and-clear
# teardown so co-hosted in-process runs can't tear down each other's.
# ---------------------------------------------------------------------------

_profiler: "SamplingProfiler | None" = None


def start_profiler(hz: float = DEFAULT_HZ) -> SamplingProfiler:
    global _profiler
    _profiler = SamplingProfiler(hz=hz).start()
    return _profiler


def stop_profiler(expected: "SamplingProfiler | None" = None) \
        -> "SamplingProfiler | None":
    """Stop sampling and clear the global slot. With ``expected``,
    compare-and-clear (see ``metrics.stop_metrics``). The stopped
    profiler stays readable — callers flush the manifest first and
    stop after, same order as the metrics registry."""
    global _profiler
    if expected is not None and _profiler is not expected:
        expected.stop()
        return None
    p, _profiler = _profiler, None
    if p is not None:
        p.stop()
    return p


def active_profiler() -> "SamplingProfiler | None":
    return _profiler
