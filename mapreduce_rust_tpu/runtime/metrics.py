"""Job metrics & phase timing — per-phase, never per-record.

The reference's only observability is ~30 ``println!`` protocol lines plus
one log line *per emitted KV pair* inside the map hot loop
(src/mr/worker.rs:131-136) — the most expensive "observability" in the
system. Here counters accumulate in one dataclass and are logged once per
phase (driver) or once per task (worker); per-chunk detail is DEBUG level.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from contextlib import contextmanager

from mapreduce_rust_tpu.runtime.histogram import Histogram
from mapreduce_rust_tpu.runtime.trace import trace_span

log = logging.getLogger("mapreduce_rust_tpu")


@dataclasses.dataclass
class JobStats:
    bytes_in: int = 0
    chunks: int = 0
    forced_cuts: int = 0          # tokens longer than chunk_bytes, split
    distinct_keys: int = 0        # final distinct key count
    spill_events: int = 0         # merges whose evicted tail was non-empty
    spilled_keys: int = 0         # records moved device → host accumulator
    partial_overflow_replays: int = 0  # chunks re-run on the full-width path
    bucket_skew_replays: int = 0       # mesh groups re-run on the skew tier
    halo_truncations: int = 0     # sharded-stream tokens longer than the halo
                                  # (possibly truncated hash — exactness fault)
    mesh_rounds: int = 0          # all_to_all rounds executed (incl. replays)
    shuffle_wire_bytes: int = 0   # bytes through the all_to_all: the padded
    # bucket payload every chip exchanges each round — D*D*bucket_cap
    # records x 13 B (k1+k2+value+valid). This is what actually crosses the
    # interconnect (buckets are fixed-capacity under jit), so mesh runs can
    # attribute time to ICI vs compute before any multi-chip perf claim.
    accum_spill_runs: int = 0     # accrun-* disk runs the accumulator's
                                  # budget tier wrote (counted at job end,
                                  # before the run files are deleted — the
                                  # post-hoc proof the bounded-memory tier
                                  # actually engaged)
    dict_spill_runs: int = 0      # dictrun-* disk runs, same contract
    dictionary_words: int = 0
    hash_collisions: int = 0
    unknown_keys: int = 0         # final keys missing from the dictionary
    wall_seconds: float = 0.0
    phase_seconds: dict = dataclasses.field(default_factory=dict)
    # Utilization split (who is the bottleneck): time the consumer loop sat
    # idle waiting for host ingest (read→normalize→chunk) vs time it sat
    # blocked on device results. ingest_wait ≫ device_wait → host-bound.
    ingest_wait_s: float = 0.0
    device_wait_s: float = 0.0
    host_map_s: float = 0.0       # CPU seconds in the host-map engine's scan
    # — AGGREGATE across scan workers (with host_map_workers > 1 this can
    # legitimately exceed the stream wall time; divide by the worker count
    # for per-core scan time)
    host_glue_s: float = 0.0      # host-map engine consumer-thread work
    # between scans: dictionary fold + update pack + device_put + merge
    # dispatch — on a 1-core host this steals directly from the scan
    # thread, so the split names which of the two to optimize
    host_map_workers: int = 0     # scan threads the host-map engine ran
                                  # (0 = engine not used this run)
    scan_wait_s: float = 0.0      # consumer wall time blocked waiting for
    # the next IN-ORDER scan result: the parallel engine's starvation
    # signal — large scan_wait means more workers (or a faster scan) would
    # raise throughput; ~0 means the scans are fully hidden and glue or
    # device is the ceiling
    all_to_all_s: float = 0.0     # wall seconds inside mesh.all_to_all
    # blocks (tokenize + bucket scatter + collective dispatch, replays
    # included) — the ICI-vs-compute split's numerator: with the per-round
    # wire bytes (shuffle_wire_bytes) this attributes mesh time to the
    # interconnect before any multi-chip perf claim
    host_arena_bytes: int = 0     # native scan scratch resident across ALL
    # scan threads at job end (native/host.arena_bytes): the memory price
    # of host_map_workers, flat per thread by construction
    # ---- doctor instrumentation (ISSUE 5) ----
    compile_count: int = 0        # XLA backend compiles this run triggered
    compile_s: float = 0.0        # wall seconds inside those compiles —
    # overlaps the phase that triggered them (a cold first window pays it),
    # so the doctor can name "compile" as the real ceiling of a short run
    compile_cache_hits: int = 0   # persistent-compilation-cache hits
    compile_cache_misses: int = 0  # consulted-but-absent (cold) compiles
    device_mem_high_bytes: int = 0  # high-water bytes_in_use across local
    # devices, sampled from the existing drain/consume loops (0 when the
    # backend exposes no memory_stats, e.g. CPU)
    partition_bytes: list = dataclasses.field(default_factory=list)
    # bytes of formatted output per reduce partition (index = r): the
    # reduce-side skew signal the doctor scores — a hot partition here is
    # the key-distribution problem the reference can't even see
    mesh_shard_rows: list = dataclasses.field(default_factory=list)
    # final valid records per mesh shard (hash-class skew across chips)
    hists: dict = dataclasses.field(default_factory=dict)
    # name → runtime.histogram.Histogram: the latency distributions behind
    # the aggregate counters above (host_map.scan_s, a2a.round_s,
    # device.drain_s, ingest.wait_s, ...). Serialized into the manifest as
    # "histograms" by telemetry.stats_to_dict; per-window/per-round sites
    # only — never per-record (the add is a bisect, not free).

    def record_hist(self, name: str, value: float) -> None:
        """Fold one sample into the named latency/size histogram. Same
        ownership contract as every other stats write: consumer thread
        only (the sanitizer's registered-writer gate covers the attribute
        reads here; the dict insert happens on first use)."""
        h = self.hists.get(name)
        if h is None:
            h = self.hists[name] = Histogram()
        h.add(value)

    def register_writer(self) -> None:
        """Sanitizer hook: announce the calling thread as a legitimate
        concurrent writer (the ingest producer calls this — it owns
        bytes_in/chunks/forced_cuts by design). No-op here; the sanitized
        subclass (analysis/sanitize.SanitizedJobStats) records the thread
        and rejects writes from any thread that never registered."""

    @property
    def gb_per_s(self) -> float:
        return self.bytes_in / self.wall_seconds / 1e9 if self.wall_seconds else 0.0

    @property
    def bottleneck(self) -> str:
        # With parallel scan workers the aggregate host_map_s no longer
        # measures wall time; the consumer's scan starvation (scan_wait_s)
        # is the honest wall-clock attribution for "the scans are the
        # ceiling" — a fully hidden scan pool must not keep claiming the
        # bottleneck it used to be.
        scan = self.host_map_s if self.host_map_workers <= 1 else self.scan_wait_s
        parts = {
            "host-ingest": self.ingest_wait_s,
            "device": self.device_wait_s,
            "host-map": scan,
            "host-glue": self.host_glue_s,
        }
        name, val = max(parts.items(), key=lambda kv: kv[1])
        return name if val > 0 else "balanced"

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            # Phases double as top-level timeline spans ("phase.stream",
            # "phase.finalize", "phase.egress") when tracing is on.
            with trace_span(f"phase.{name}"):
                yield
        finally:
            dt = time.perf_counter() - t0
            self.phase_seconds[name] = self.phase_seconds.get(name, 0.0) + dt
            log.info("phase %-10s %8.3fs", name, dt)

    def summary(self) -> str:
        phases = " ".join(f"{k}={v:.2f}s" for k, v in self.phase_seconds.items())
        return (
            f"{self.bytes_in / 1e6:.2f} MB in {self.wall_seconds:.3f}s "
            f"({self.gb_per_s:.3f} GB/s) chunks={self.chunks} "
            f"distinct={self.distinct_keys} dict={self.dictionary_words} "
            f"spills={self.spill_events}({self.spilled_keys} keys) "
            f"replays={self.partial_overflow_replays}+{self.bucket_skew_replays}skew "
            f"shuffle[{self.mesh_rounds} rounds, {self.shuffle_wire_bytes / 1e6:.1f} MB wire] "
            f"collisions={self.hash_collisions} unknown={self.unknown_keys} "
            f"waits[ingest={self.ingest_wait_s:.2f}s device={self.device_wait_s:.2f}s "
            f"map={self.host_map_s:.2f}s"
            + (
                f"/{self.host_map_workers}w stall={self.scan_wait_s:.2f}s"
                if self.host_map_workers > 1 else ""
            )
            + f" glue={self.host_glue_s:.2f}s → {self.bottleneck}] [{phases}]"
        )
