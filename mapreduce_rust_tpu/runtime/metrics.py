"""Job metrics & phase timing — per-phase, never per-record — plus the
live metrics registry (ISSUE 8).

The reference's only observability is ~30 ``println!`` protocol lines plus
one log line *per emitted KV pair* inside the map hot loop
(src/mr/worker.rs:131-136) — the most expensive "observability" in the
system. Here counters accumulate in one dataclass and are logged once per
phase (driver) or once per task (worker); per-chunk detail is DEBUG level.

Two layers share this module:

- :class:`JobStats` — the one-shot per-run dataclass every engine fills
  and the manifest serializes. Unchanged contract: single-writer (the
  consumer thread), aggregate counters only.
- :class:`MetricsRegistry` — the LIVE layer on top: named counters /
  gauges / histograms with label support, registered once and sampled by
  ``maybe_sample()`` into a bounded in-memory time-series ring of
  wall-clock-bucketed points. The sampler is piggybacked on the existing
  consumer/poll/renewal loops exactly like the flight recorder
  (``trace.maybe_snapshot``) — the not-due path is two reads and a
  compare, and NOTHING here may run per record (mrlint rule
  ``metric-in-hot-loop`` enforces that at the known hot loops). The ring
  lands in run manifests as ``stats.timeseries``, rides flight-recorder
  partials so a SIGKILLed run keeps its series, ships to the coordinator
  in the renewal-RPC envelope, and renders as Prometheus text exposition
  on the coordinator's ``--metrics-port`` endpoint.

No jax import and no backend probe anywhere in this module: the registry
must be constructible in the coordinator and in ``watch`` — control-plane
processes that never load a backend.

Job-isolation audit (ISSUE 14). The module-global registry slot
(``start_metrics``/``active_registry``/``metrics_tick``) is PROCESS
state, documented as shared: it exists so build_manifest and the
engine-side ticks of an OS-process driver/worker find "the" registry
without plumbing. It is last-writer-wins under co-hosting, which is why
every multi-tenant owner uses an INSTANCE registry instead — the
coordinator and the JobService construct their own (per-job series are
``job=<id>``-LABELED on that one instance, never one registry per job),
and each Worker ships from ``self.registry``. Nothing job-scoped may
ever live in the global slot.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import re
import threading
import time
from contextlib import contextmanager

from mapreduce_rust_tpu.runtime.histogram import EDGES, Histogram
from mapreduce_rust_tpu.runtime.trace import trace_span

log = logging.getLogger("mapreduce_rust_tpu")


@dataclasses.dataclass
class JobStats:
    bytes_in: int = 0
    chunks: int = 0
    forced_cuts: int = 0          # tokens longer than chunk_bytes, split
    distinct_keys: int = 0        # final distinct key count
    spill_events: int = 0         # merges whose evicted tail was non-empty
    spilled_keys: int = 0         # records moved device → host accumulator
    partial_overflow_replays: int = 0  # chunks re-run on the full-width path
    bucket_skew_replays: int = 0       # mesh groups re-run on the skew tier
    halo_truncations: int = 0     # sharded-stream tokens longer than the halo
                                  # (possibly truncated hash — exactness fault)
    mesh_rounds: int = 0          # all_to_all rounds executed (incl. replays)
    shuffle_wire_bytes: int = 0   # bytes through the all_to_all: the padded
    # bucket payload every chip exchanges each round — D*D*bucket_cap
    # records x 13 B (k1+k2+value+valid). This is what actually crosses the
    # interconnect (buckets are fixed-capacity under jit), so mesh runs can
    # attribute time to ICI vs compute before any multi-chip perf claim.
    accum_spill_runs: int = 0     # accrun-* disk runs the accumulator's
                                  # budget tier wrote (counted at job end,
                                  # before the run files are deleted — the
                                  # post-hoc proof the bounded-memory tier
                                  # actually engaged)
    dict_spill_runs: int = 0      # dictrun-* disk runs, same contract
    dictionary_words: int = 0
    hash_collisions: int = 0
    unknown_keys: int = 0         # final keys missing from the dictionary
    wall_seconds: float = 0.0
    phase_seconds: dict = dataclasses.field(default_factory=dict)
    # Utilization split (who is the bottleneck): time the consumer loop sat
    # idle waiting for host ingest (read→normalize→chunk) vs time it sat
    # blocked on device results. ingest_wait ≫ device_wait → host-bound.
    ingest_wait_s: float = 0.0
    device_wait_s: float = 0.0
    host_map_s: float = 0.0       # CPU seconds in the host-map engine's scan
    # — AGGREGATE across scan workers (with host_map_workers > 1 this can
    # legitimately exceed the stream wall time; divide by the worker count
    # for per-core scan time)
    host_glue_s: float = 0.0      # host-map engine consumer-thread work
    # between scans: dictionary fold + update pack + device_put + merge
    # dispatch — on a 1-core host this steals directly from the scan
    # thread, so the split names which of the two to optimize
    host_map_workers: int = 0     # scan threads the host-map engine ran
                                  # (0 = engine not used this run)
    # ---- sharded egress fold (ISSUE 9) ----
    fold_shards: int = 0          # fold shards the host-map engine ran
    # (0 = engine not used; 1 = legacy inline fold on the consumer thread;
    # >1 = the sharded fold plane: S fold threads, each the sole owner of
    # one key-hash-disjoint dictionary shard)
    fold_s: float = 0.0           # seconds fold threads spent folding scan
    # results into their shards — AGGREGATE across fold threads (like
    # host_map_s across scan workers: with S>1 this may exceed wall time;
    # per-shard balance lives in fold_shard_s)
    fold_stall_s: float = 0.0     # router wall seconds blocked on fold
    # backpressure: full shard queues plus the end-of-stream join. The
    # wall-clock "the fold is the ceiling" signal, exactly as scan_wait_s
    # is for the scans — large means more shards (or a flatter key hash)
    # would raise throughput
    fold_shard_s: list = dataclasses.field(default_factory=list)
    # per-shard fold seconds (index = shard): the fold-balance signal the
    # doctor's fold-shard-skew finding scores
    fold_shard_idle_s: list = dataclasses.field(default_factory=list)
    # per-shard seconds the fold thread sat waiting for routed work
    # ---- binary async spill plane (ISSUE 11) ----
    spill_s: float = 0.0          # background-writer seconds spent
    # sorting/packing/writing spill runs (dictionary + accumulator tiers,
    # aggregate across writer threads — overlapped with the scan, so with
    # the async plane this can exceed nothing: it is hidden time made
    # visible)
    spill_stall_s: float = 0.0    # fold/consumer wall seconds blocked on
    # a full spill-writer queue: the wall-clock "the disk is the ceiling"
    # signal, exactly as fold_stall_s is for the fold — large means raise
    # the budgets (fewer, larger runs), add fold shards (one writer per
    # shard), or find a faster disk
    spill_bytes: int = 0          # bytes written to spill runs (both tiers)
    merge_fanin: int = 0          # sources the egress k-way merge saw
    # (runs + RAM tiers across every shard; 0 = in-RAM egress)
    # ---- device-merge dispatch plane (ISSUE 13) ----
    dispatch_mode: str = ""       # "" = plane not used (non-host engines);
    # "async"/"sync" + "+coalesce" when cross-window coalescing engaged —
    # every manifest says which dispatch plane produced its numbers
    dispatch_s: float = 0.0       # dispatch-thread seconds in scan-order
    # scatter-back + staging combine + pack + device_put + the jit call —
    # with the async plane this is overlapped (hidden) time made visible,
    # exactly like spill_s for the writers; in sync mode the same work is
    # also part of host_glue_s (the PR 10 accounting, kept for A/B)
    dispatch_stall_s: float = 0.0  # router wall seconds blocked on a full
    # dispatch queue plus the end-of-stream join: the wall-clock "the
    # dispatch is the ceiling" signal, exactly as fold_stall_s is for the
    # fold — large means the device hop itself (or the coalesce combine)
    # is slower than the scans feeding it
    merge_dispatches: int = 0     # packed device merges dispatched (with
    # coalescing this is windows ÷ coalesce factor, the lever the plane
    # exists to pull)
    merge_fill_frac: float = 0.0  # mean records-per-dispatch ÷ cap: how
    # full the fixed-shape update actually was. Low = the 1+3·cap
    # transfer is mostly sentinel padding (lower host_update_cap or raise
    # dispatch_fill_frac); the doctor's merge-dispatch finding reads this
    scan_wait_s: float = 0.0      # consumer wall time blocked waiting for
    # the next IN-ORDER scan result: the parallel engine's starvation
    # signal — large scan_wait means more workers (or a faster scan) would
    # raise throughput; ~0 means the scans are fully hidden and glue or
    # device is the ceiling
    all_to_all_s: float = 0.0     # wall seconds inside mesh.all_to_all
    # blocks (tokenize + bucket scatter + collective dispatch, replays
    # included) — the ICI-vs-compute split's numerator: with the per-round
    # wire bytes (shuffle_wire_bytes) this attributes mesh time to the
    # interconnect before any multi-chip perf claim
    host_arena_bytes: int = 0     # native scan scratch resident across ALL
    # scan threads at job end (native/host.arena_bytes): the memory price
    # of host_map_workers, flat per thread by construction
    # ---- doctor instrumentation (ISSUE 5) ----
    compile_count: int = 0        # XLA backend compiles this run triggered
    compile_s: float = 0.0        # wall seconds inside those compiles —
    # overlaps the phase that triggered them (a cold first window pays it),
    # so the doctor can name "compile" as the real ceiling of a short run
    compile_cache_hits: int = 0   # persistent-compilation-cache hits
    compile_cache_misses: int = 0  # consulted-but-absent (cold) compiles
    device_mem_high_bytes: int = 0  # high-water bytes_in_use across local
    # devices, sampled from the existing drain/consume loops (0 when the
    # backend exposes no memory_stats, e.g. CPU)
    partition_bytes: list = dataclasses.field(default_factory=list)
    # bytes of formatted output per reduce partition (index = r): the
    # reduce-side skew signal the doctor scores — a hot partition here is
    # the key-distribution problem the reference can't even see
    # ---- workload plane (ISSUE 15) ----
    partition_mode: str = "hash"  # how this run's egress routed keys to
    # partitions: "hash" (k1 % reduce_n) or "range" (searchsorted over
    # sampled splitters — sort). The doctor reads it to pick which skew
    # advice applies to partition_bytes (raise reduce_n vs raise
    # split_samples).
    splitter_samples: int = 0     # tokens the sampled-splitter pre-pass
    # drew across all inputs (range apps only; 0 = no pre-pass ran)
    splitter_s: float = 0.0       # wall seconds of the sample+derive
    # pre-pass — the splitter-overhead the bench sort leg records; it
    # must stay O(samples), invisible next to the stream
    mesh_shard_rows: list = dataclasses.field(default_factory=list)
    # final valid records per mesh shard (hash-class skew across chips)
    hists: dict = dataclasses.field(default_factory=dict)
    # name → runtime.histogram.Histogram: the latency distributions behind
    # the aggregate counters above (host_map.scan_s, a2a.round_s,
    # device.drain_s, ingest.wait_s, ...). Serialized into the manifest as
    # "histograms" by telemetry.stats_to_dict; per-window/per-round sites
    # only — never per-record (the add is a bisect, not free).

    def record_hist(self, name: str, value: float) -> None:
        """Fold one sample into the named latency/size histogram. Same
        ownership contract as every other stats write: consumer thread
        only (the sanitizer's registered-writer gate covers the attribute
        reads here; the dict insert happens on first use)."""
        h = self.hists.get(name)
        if h is None:
            h = self.hists[name] = Histogram()
        h.add(value)

    def register_writer(self) -> None:
        """Sanitizer hook: announce the calling thread as a legitimate
        concurrent writer (the ingest producer calls this — it owns
        bytes_in/chunks/forced_cuts by design). No-op here; the sanitized
        subclass (analysis/sanitize.SanitizedJobStats) records the thread
        and rejects writes from any thread that never registered."""

    @property
    def gb_per_s(self) -> float:
        return self.bytes_in / self.wall_seconds / 1e9 if self.wall_seconds else 0.0

    @property
    def bottleneck(self) -> str:
        # With parallel scan workers the aggregate host_map_s no longer
        # measures wall time; the consumer's scan starvation (scan_wait_s)
        # is the honest wall-clock attribution for "the scans are the
        # ceiling" — a fully hidden scan pool must not keep claiming the
        # bottleneck it used to be.
        scan = self.host_map_s if self.host_map_workers <= 1 else self.scan_wait_s
        parts = {
            "host-ingest": self.ingest_wait_s,
            "device": self.device_wait_s,
            "host-map": scan,
            "host-glue": self.host_glue_s,
        }
        if self.fold_shards > 1:
            # Sharded fold plane (ISSUE 9): folding runs off the consumer
            # thread, so host_glue_s no longer contains it — the honest
            # wall-clock "the fold is the ceiling" signal is the router's
            # fold backpressure, same logic as scan_wait_s for the scans.
            parts["host-fold"] = self.fold_stall_s
        if self.spill_s > 0 or self.spill_stall_s > 0:
            # Async spill plane (ISSUE 11): run writes happen off the hot
            # threads, so the honest "the disk is the ceiling" signal is
            # the owner-side writer backpressure — the same stall logic as
            # host-fold. (The doctor's _bottleneck_attribution mirrors
            # this arm exactly; keep them in lockstep.)
            parts["spill"] = self.spill_stall_s
        if self.dispatch_mode.startswith("async"):
            # Async dispatch plane (ISSUE 13): the device hop runs off the
            # router, so "the dispatch is the ceiling" reads as router
            # backpressure — same stall logic again. Sync mode keeps the
            # PR 10 attribution (the hop is glue), so the arm stays off
            # there and the A/B story stays honest. (Doctor mirror:
            # _bottleneck_attribution, keep in lockstep.)
            parts["merge-dispatch"] = self.dispatch_stall_s
        name, val = max(parts.items(), key=lambda kv: kv[1])
        return name if val > 0 else "balanced"

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            # Phases double as top-level timeline spans ("phase.stream",
            # "phase.finalize", "phase.egress") when tracing is on.
            with trace_span(f"phase.{name}"):
                yield
        finally:
            dt = time.perf_counter() - t0
            self.phase_seconds[name] = self.phase_seconds.get(name, 0.0) + dt
            log.info("phase %-10s %8.3fs", name, dt)

    def summary(self) -> str:
        phases = " ".join(f"{k}={v:.2f}s" for k, v in self.phase_seconds.items())
        return (
            f"{self.bytes_in / 1e6:.2f} MB in {self.wall_seconds:.3f}s "
            f"({self.gb_per_s:.3f} GB/s) chunks={self.chunks} "
            f"distinct={self.distinct_keys} dict={self.dictionary_words} "
            f"spills={self.spill_events}({self.spilled_keys} keys) "
            f"replays={self.partial_overflow_replays}+{self.bucket_skew_replays}skew "
            f"shuffle[{self.mesh_rounds} rounds, {self.shuffle_wire_bytes / 1e6:.1f} MB wire] "
            f"collisions={self.hash_collisions} unknown={self.unknown_keys} "
            f"waits[ingest={self.ingest_wait_s:.2f}s device={self.device_wait_s:.2f}s "
            f"map={self.host_map_s:.2f}s"
            + (
                f"/{self.host_map_workers}w stall={self.scan_wait_s:.2f}s"
                if self.host_map_workers > 1 else ""
            )
            + f" glue={self.host_glue_s:.2f}s"
            + (
                f" fold={self.fold_s:.2f}s/{self.fold_shards}sh "
                f"fstall={self.fold_stall_s:.2f}s"
                if self.fold_shards > 1 else ""
            )
            + (
                f" spillw={self.spill_s:.2f}s sstall={self.spill_stall_s:.2f}s"
                if self.spill_s > 0 or self.spill_stall_s > 0 else ""
            )
            + (
                f" disp[{self.dispatch_mode}]={self.dispatch_s:.2f}s"
                f"/{self.merge_dispatches}m "
                f"fill={self.merge_fill_frac:.2f} "
                f"dstall={self.dispatch_stall_s:.2f}s"
                if self.dispatch_mode else ""
            )
            + f" → {self.bottleneck}] [{phases}]"
        )


# ---------------------------------------------------------------------------
# Live metrics registry (ISSUE 8 tentpole)
# ---------------------------------------------------------------------------

TIMESERIES_SCHEMA = 1

#: Prometheus metric-name charset; anything else becomes "_".
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _series_key(name: str, labels: tuple) -> str:
    """Flat series identity: ``name`` or ``name{k=v,k2=v2}`` — the key the
    ring, the manifest and the scrape endpoint all agree on."""
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


def _prom_name(name: str, prefix: str = "mr_") -> str:
    return prefix + _NAME_RE.sub("_", name)


def _prom_labels(labels: tuple) -> str:
    if not labels:
        return ""
    body = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", r"\\").replace('"', r"\""))
        for k, v in labels
    )
    return "{" + body + "}"


def _prom_num(v) -> str:
    if isinstance(v, float):
        return format(v, ".10g")
    return str(v)


class _Instrument:
    """One named metric; label-sets map to independent values. Mutations
    take the registry lock — cheap at the allowed per-window/per-poll
    rate, and the doctrine (module docstring) forbids per-record calls."""

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help: str = "") -> None:
        self._registry = registry
        self.name = name
        self.help = help
        self._values: dict = {}

    @staticmethod
    def _labelkey(labels: dict) -> tuple:
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def remove_labels(self, **labels) -> int:
        """Drop every label-set whose labels INCLUDE the given pairs
        (``remove_labels(job="j3")`` drops all of j3's series whatever
        the other labels say). The long-lived-server hygiene hook
        (ISSUE 14): a multi-tenant registry that only ever adds
        label-sets grows without bound and keeps exporting a finished
        tenant's stale last values. Returns the number dropped; already-
        recorded ring points keep their history (the ring is bounded)."""
        want = {(k, str(v)) for k, v in labels.items()}
        with self._registry._lock:
            victims = [
                key for key in self._values if want <= set(key)
            ]
            for key in victims:
                del self._values[key]
        return len(victims)


class Counter(_Instrument):
    """Monotonic count. ``inc`` for push-style sites; ``set_total`` for
    pull-style mirrors of an externally-accumulated total (e.g. the
    coordinator re-publishing JobReport RPC counts each serve tick)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._labelkey(labels)
        with self._registry._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def set_total(self, value: float, **labels) -> None:
        key = self._labelkey(labels)
        with self._registry._lock:
            # Monotonicity kept even against a sloppy publisher: a counter
            # that goes backwards reads as a process restart to scrapers.
            if value >= self._values.get(key, 0):
                self._values[key] = value


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._labelkey(labels)
        with self._registry._lock:
            self._values[key] = value

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self._labelkey(labels)
        with self._registry._lock:
            self._values[key] = self._values.get(key, 0) + amount


class HistogramMetric(_Instrument):
    """Label-set → runtime.histogram.Histogram (the same mergeable
    log-bucket primitive the manifests carry). ``observe`` folds one
    sample; ``set_hist`` adopts a copy of an externally-maintained
    histogram (pull-style, e.g. JobReport's per-RPC latency hists)."""

    kind = "histogram"

    def observe(self, value: float, **labels) -> None:
        key = self._labelkey(labels)
        with self._registry._lock:
            h = self._values.get(key)
            if h is None:
                h = self._values[key] = Histogram()
            h.add(value)

    def set_hist(self, hist: Histogram, **labels) -> None:
        key = self._labelkey(labels)
        snap = Histogram().merge(hist)  # copy: the source keeps mutating
        with self._registry._lock:
            self._values[key] = snap


class MetricsRegistry:
    """Named instruments + a bounded time-series ring of their sampled
    values.

    - Registration is idempotent by name; re-registering under a
      different kind raises (two subsystems fighting over one name is a
      bug, not a merge).
    - ``add_collector(fn)`` attaches a pull source: ``fn() -> {name:
      number}``, called only when a sample is actually taken (never the
      hot path); its values land in the ring and the scrape text as
      gauges. This is how JobStats rides along without double-
      instrumenting every engine (see :func:`jobstats_collector`).
    - ``maybe_sample()`` is the piggyback tick: wall-clock-bucketed (one
      point per ``period_s`` bucket however many loops tick), bounded by
      ``capacity`` points (oldest evicted, eviction counted).
    """

    def __init__(self, period_s: float = 1.0, capacity: int = 512) -> None:
        if period_s <= 0:
            raise ValueError("metrics period_s must be positive")
        if capacity < 8:
            raise ValueError("metrics ring capacity must be >= 8")
        self.period_s = float(period_s)
        self.capacity = int(capacity)
        self._instruments: dict[str, _Instrument] = {}
        self._collectors: list = []
        self._points: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._last_bucket: "int | None" = None
        self.dropped_points = 0
        self.collector_errors = 0

    # ---- registration ----

    def _register(self, cls, name: str, help: str):
        inst = self._instruments.get(name)
        if inst is not None:
            if not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"not {cls.kind}"
                )
            return inst
        inst = self._instruments[name] = cls(self, name, help)
        return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> HistogramMetric:
        return self._register(HistogramMetric, name, help)

    def add_collector(self, fn) -> None:
        self._collectors.append(fn)

    # ---- sampling ----

    def current_values(self) -> dict:
        """Flat {series_key: number} of every instrument + collector right
        now. Histograms contribute ``<series>.count`` and ``<series>.sum``
        (rates and means are derivable; percentiles stay in the full
        histogram blocks the manifest already carries)."""
        out: dict = {}
        for fn in self._collectors:
            try:
                vals = fn() or {}
            except Exception:
                # A telemetry pull must never fail the loop that ticked it.
                self.collector_errors += 1
                continue
            for k, v in vals.items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                out[str(k)] = v
        with self._lock:
            for name, inst in self._instruments.items():
                for key, v in inst._values.items():
                    sk = _series_key(name, key)
                    if isinstance(v, Histogram):
                        out[f"{sk}.count"] = v.count
                        out[f"{sk}.sum"] = round(v.total, 9)
                    else:
                        out[sk] = v
        return out

    def due(self) -> bool:
        """Would ``maybe_sample()`` take a point right now? The cheap
        pre-check for callers whose PREPARATION for a sample is itself
        expensive (the coordinator republishes its control plane and
        renders the scrape text — work worth skipping on the serve-loop
        passes between buckets)."""
        last = self._last_bucket
        return last is None or int(time.time() / self.period_s) > last

    def maybe_sample(self, force: bool = False) -> bool:
        """The piggyback tick. Wall-clock-bucketed: however many loops
        call this, at most one point lands per ``period_s`` bucket. The
        not-due path is two reads and a compare (plus one uncontended
        lock round when the bucket rolls over). The bucket is CLAIMED
        under the lock before the (lock-taking) collector walk runs, so
        two threads ticking the same registry at the rollover cannot
        both sample it."""
        now = time.time()
        # Integer bucket index: `now - now % period` floats differently
        # across two calls inside the SAME bucket (mod rounding), which
        # would let two threads claim "different" buckets that stamp the
        # same point.
        bucket = int(now / self.period_s)
        last = self._last_bucket
        if not force and last is not None and bucket <= last:
            return False
        with self._lock:
            last = self._last_bucket
            if not force and last is not None and bucket <= last:
                return False  # another thread claimed this bucket — and a
                # stalled claimer must never move the high-water mark BACK
                # (that would re-open the newer bucket for a duplicate)
            self._last_bucket = max(bucket, last or 0)
        point = {"t": round(bucket * self.period_s if not force else now, 3),
                 "v": self.current_values()}
        with self._lock:
            if len(self._points) == self.capacity:
                self.dropped_points += 1
            self._points.append(point)
        return True

    def points(self) -> list:
        # Sorted on read: a claimer that stalled between claiming its
        # bucket and appending its point can land behind a newer one.
        with self._lock:
            return sorted(self._points, key=lambda p: p["t"])

    def latest(self) -> "dict | None":
        with self._lock:
            return self._points[-1] if self._points else None

    def ship_sample(self) -> dict:
        """The renewal-envelope payload: one fresh point (not ring-gated —
        the renewal period already paces it). Small flat dict by
        construction."""
        return {"t": round(time.time(), 3), "v": self.current_values()}

    # ---- serialization ----

    def series_catalog(self) -> dict:
        """series_key → {kind} for every series seen so far (collector
        series appear once a point holds them, as gauges)."""
        catalog: dict = {}
        with self._lock:
            for name, inst in self._instruments.items():
                for key in inst._values:
                    sk = _series_key(name, key)
                    if inst.kind == "histogram":
                        catalog[f"{sk}.count"] = {"kind": "histogram"}
                        catalog[f"{sk}.sum"] = {"kind": "histogram"}
                    else:
                        catalog[sk] = {"kind": inst.kind}
            known = set(catalog)
            for p in self._points:
                for sk in p["v"]:
                    if sk not in known:
                        catalog[sk] = {"kind": "gauge"}
                        known.add(sk)
        return catalog

    def timeseries_dict(self) -> dict:
        """The manifest block (``stats.timeseries``) and flight-recorder
        payload: the series catalog + every ring point, JSON-safe."""
        return {
            "schema": TIMESERIES_SCHEMA,
            "period_s": self.period_s,
            "capacity": self.capacity,
            "dropped_points": self.dropped_points,
            "series": self.series_catalog(),
            "points": self.points(),
        }

    # ---- Prometheus text exposition ----

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def prometheus_text(self, prefix: str = "mr_") -> str:
        """Render instruments + the freshest collector values in the
        Prometheus text exposition format (counters/gauges as single
        samples; histograms as cumulative ``_bucket{le=...}`` series over
        the log-bucket edges, plus ``_sum``/``_count``)."""
        lines: list[str] = []
        collected: dict = {}
        for fn in self._collectors:
            try:
                collected.update(fn() or {})
            except Exception:
                self.collector_errors += 1
        with self._lock:
            instruments = {
                name: (inst.kind, inst.help, dict(inst._values))
                for name, inst in sorted(self._instruments.items())
            }
        for name, (kind, help_, values) in instruments.items():
            pname = _prom_name(name, prefix)
            if help_:
                lines.append(f"# HELP {pname} {help_}")
            lines.append(f"# TYPE {pname} {kind}")
            for key, v in sorted(values.items()):
                lab = _prom_labels(key)
                if kind != "histogram":
                    lines.append(f"{pname}{lab} {_prom_num(v)}")
                    continue
                cum = 0
                for idx in sorted(v.buckets):
                    cum += v.buckets[idx]
                    le = ("+Inf" if idx >= len(EDGES)
                          else format(EDGES[min(idx, len(EDGES) - 1)], ".6g"))
                    blab = _prom_labels(key + (("le", le),))
                    lines.append(f"{pname}_bucket{blab} {cum}")
                inf_lab = _prom_labels(key + (("le", "+Inf"),))
                if f"{pname}_bucket{inf_lab} {v.count}" != (
                    lines[-1] if lines else ""
                ):
                    lines.append(f"{pname}_bucket{inf_lab} {v.count}")
                lines.append(f"{pname}_sum{lab} {_prom_num(round(v.total, 9))}")
                lines.append(f"{pname}_count{lab} {v.count}")
        for k, v in sorted(collected.items()):
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            pname = _prom_name(str(k), prefix)
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_prom_num(v)}")
        return "\n".join(lines) + "\n"


def jobstats_collector(stats: JobStats):
    """Pull source bridging the one-shot JobStats into the live ring: the
    sampler reads these aggregate fields when a point is due — no engine
    grows a second instrumentation site, and the read is benign (plain
    int/float attribute loads, no iteration over mutating containers)."""

    def collect() -> dict:
        return {
            "job.bytes_in": stats.bytes_in,
            "job.chunks": stats.chunks,
            "job.spill_events": stats.spill_events,
            "job.spilled_keys": stats.spilled_keys,
            "job.ingest_wait_s": round(stats.ingest_wait_s, 6),
            "job.device_wait_s": round(stats.device_wait_s, 6),
            "job.host_map_s": round(stats.host_map_s, 6),
            "job.host_glue_s": round(stats.host_glue_s, 6),
            "job.fold_s": round(stats.fold_s, 6),
            "job.fold_stall_s": round(stats.fold_stall_s, 6),
            "job.spill_s": round(stats.spill_s, 6),
            "job.spill_stall_s": round(stats.spill_stall_s, 6),
            "job.spill_bytes": stats.spill_bytes,
            "job.dispatch_s": round(stats.dispatch_s, 6),
            "job.dispatch_stall_s": round(stats.dispatch_stall_s, 6),
            "job.merge_dispatches": stats.merge_dispatches,
            "job.merge_fill_frac": round(stats.merge_fill_frac, 6),
            "job.scan_wait_s": round(stats.scan_wait_s, 6),
            "job.all_to_all_s": round(stats.all_to_all_s, 6),
            "job.mesh_rounds": stats.mesh_rounds,
            "job.shuffle_wire_bytes": stats.shuffle_wire_bytes,
            "job.compile_s": round(stats.compile_s, 6),
            "job.device_mem_high_bytes": stats.device_mem_high_bytes,
        }

    return collect


# ---------------------------------------------------------------------------
# Process-global registry lifecycle — the trace.py pattern: one registry
# per run, installed by the run owner (run_job / Worker.run / Coordinator
# CLI), ticked by module-level maybe_sample() from the existing loops.
# ---------------------------------------------------------------------------

_registry: "MetricsRegistry | None" = None


def start_metrics(period_s: float = 1.0,
                  capacity: int = 512) -> MetricsRegistry:
    global _registry
    _registry = MetricsRegistry(period_s=period_s, capacity=capacity)
    return _registry


def stop_metrics(expected: "MetricsRegistry | None" = None) \
        -> "MetricsRegistry | None":
    """Clear the global slot. With ``expected``, compare-and-clear: an
    in-process co-hosted run (tests drive several Workers in one
    interpreter) may have REPLACED the slot since this owner started —
    tearing down someone else's live registry would silence their
    renewal samples and manifest ring."""
    global _registry
    if expected is not None and _registry is not expected:
        return None
    r, _registry = _registry, None
    return r


def active_registry() -> "MetricsRegistry | None":
    return _registry


def metrics_tick() -> None:
    """Sampler tick on the active registry — no-op (one global read) when
    metrics are off. Call from consumer/poll/renewal loops, beside the
    flight recorder's ``maybe_snapshot()`` — never per record."""
    r = _registry
    if r is not None:
        r.maybe_sample()


# ---------------------------------------------------------------------------
# Prometheus scrape endpoint (coordinator --metrics-port)
# ---------------------------------------------------------------------------

class MetricsHTTPServer:
    """Text-exposition endpoint (``GET /metrics``) on its own thread —
    stdlib ``http.server``, zero new deps, so standard scrapers work
    against a long-lived coordinator.

    Publish/serve split: the OWNER thread (the coordinator's event loop,
    serialized with every RPC handler) renders the text and calls
    ``publish``; the HTTP thread only ever serves the last published
    bytes. The scrape path therefore never iterates a dict an RPC handler
    is mutating — the same discipline as the report snapshot at teardown.
    Port 0 binds an ephemeral port (tests); ``.port`` is the bound one.
    """

    def __init__(self, port: int, host: str = "127.0.0.1") -> None:
        import http.server

        outer = self
        self._body = b"# metrics: no samples published yet\n"
        self._pub_lock = threading.Lock()

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path not in ("/", "/metrics"):
                    self.send_error(404, "try /metrics")
                    return
                with outer._pub_lock:
                    body = outer._body
                self.send_response(200)
                self.send_header("Content-Type", MetricsRegistry.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass  # scrapes poll; stderr chatter is not telemetry

        self._srv = http.server.ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="mr/metrics-http", daemon=True
        )
        self._thread.start()

    def publish(self, text: str) -> None:
        body = text.encode()
        with self._pub_lock:
            self._body = body

    def close(self) -> None:
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except OSError:
            pass
        self._thread.join(timeout=5)
