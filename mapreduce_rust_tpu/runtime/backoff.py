"""Jittered exponential backoff with a cap and a budget.

The RPC plane's retry primitive (ISSUE 6 piece 3): every loop that used to
sleep a fixed constant on failure — the worker's connect retry, transient
call timeouts, the sentinel poll — now draws its delays from one of these.
Three properties, each encoding a production incident class:

- **exponential with jitter**: a fleet of workers reconnecting to a
  restarted coordinator must not arrive in lockstep (thundering herd); the
  jitter decorrelates them, the growth stops a tight failure loop from
  busy-hammering a struggling peer.
- **cap**: the delay never grows past ``cap_s`` — a transient blip must
  not leave a worker sleeping minutes after the peer recovered.
- **budget**: the total slept time is bounded by ``budget_s``; when it is
  spent, :meth:`next_delay` raises :class:`BackoffExhausted` so the caller
  surfaces the real error instead of retrying forever. ``budget_s=None``
  disarms the bound (sentinel polls: the phase gate can legitimately take
  arbitrarily long).

Pure stdlib — usable from the jax-free control-plane processes. The
mrlint ``unbounded-retry`` rule recognizes ``next_delay()`` as the
shipped-fix pattern for constant-sleep retry loops.
"""

from __future__ import annotations

import random


class BackoffExhausted(RuntimeError):
    """The retry budget is spent: stop retrying, raise the real error."""


class Backoff:
    def __init__(self, base_s: float, cap_s: float | None = None,
                 budget_s: float | None = None, factor: float = 2.0,
                 jitter: float = 0.5, rng: "random.Random | None" = None) -> None:
        if base_s <= 0:
            raise ValueError("base_s must be positive")
        if factor < 1.0:
            raise ValueError("factor must be >= 1.0 (delays must not shrink)")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.base_s = base_s
        self.cap_s = cap_s if cap_s is not None else base_s * 32
        self.budget_s = budget_s
        self.factor = factor
        self.jitter = jitter
        self._rng = rng or random
        self.attempts = 0
        self.spent_s = 0.0

    def next_delay(self) -> float:
        """The next sleep in seconds (monotonically growing envelope,
        jittered downward so concurrent retriers decorrelate). Raises
        :class:`BackoffExhausted` once ``budget_s`` is spent."""
        if self.budget_s is not None and self.spent_s >= self.budget_s:
            raise BackoffExhausted(
                f"retry budget exhausted after {self.attempts} attempts "
                f"({self.spent_s:.2f}s of {self.budget_s:.2f}s slept)"
            )
        delay = min(self.base_s * self.factor ** self.attempts, self.cap_s)
        if self.jitter:
            delay *= 1.0 - self.jitter * self._rng.random()
        if self.budget_s is not None:
            # The last sleep lands exactly on the budget, never past it.
            delay = min(delay, self.budget_s - self.spent_s)
        self.attempts += 1
        self.spent_s += delay
        return delay

    def reset(self) -> None:
        """Back to the base delay — call after a SUCCESS, so the next
        failure starts the envelope over instead of resuming at the cap."""
        self.attempts = 0
        self.spent_s = 0.0
