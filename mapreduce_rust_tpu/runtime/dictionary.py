"""Host-side hash→word dictionary — the egress join table.

The TPU data plane computes on 64-bit hash pairs only; word bytes never
cross the interconnect (core/hashing.py). The reference instead shuffles the
strings themselves through `mr-{m}-{r}.txt` files and emits them verbatim at
reduce time (src/mr/worker.rs:180-183). To print real words at egress we
build this dictionary on the host *during ingest*: every chunk's distinct
words are hashed with the same pair of polynomial lanes the device uses, so
`hash pair → word` lookup at egress is exact.

Hash-collision policy (SURVEY.md §7 hard part 3): inserts that map a *new*
word onto an *existing* pair are detected here — the one place collisions
are observable — counted, and the first word wins (a collision would also
merge the two words' counts on device; at ~2^64 pair space and <10^7 word
vocabularies the birthday bound makes this astronomically unlikely, but it
is checked, not assumed).

Word extraction is C-speed: ASCII punctuation is deleted with
``bytes.translate`` and tokens split on ASCII whitespace — valid only on
*normalized* bytes (core/normalize.py guarantees non-ASCII bytes occur only
inside genuine words), where it exactly matches the device tokenizer.
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator

import numpy as np

from mapreduce_rust_tpu.core.hashing import byte_class_tables, hash_words
from mapreduce_rust_tpu.runtime import spill as spill_io


def _delete_table() -> bytes:
    """ASCII bytes that are neither whitespace nor word chars — deleted by
    tokenization without splitting the token (the reference's ``[^\\w\\s]``
    strip, src/app/wc.rs:7-8)."""
    ws, wc = byte_class_tables()
    return bytes(b for b in range(0x80) if not ws[b] and not wc[b])

_DELETE = _delete_table()


def extract_words(normalized: bytes) -> list[bytes]:
    """Cleaned words of a normalized byte chunk, in order, duplicates kept.

    Identical semantics to core/hashing.tokenize_host (the per-byte oracle)
    but via two C-level passes; pure-punctuation tokens vanish because they
    translate to b"" and split() drops empties.
    """
    return normalized.translate(None, _DELETE).split()


def new_run_token() -> str:
    """Per-instance spill-run filename token — THE shared naming policy of
    both disk tiers (dictionary dictrun-* and accumulator accrun-*). pid
    alone is NOT unique: two tiers in one process (back-to-back jobs
    sharing a work_dir) or a stale crashed run's leftovers must never
    collide on run names (ADVICE r5)."""
    import uuid

    return uuid.uuid4().hex[:8]


def remove_run_files(runs: list) -> None:
    """Delete spill run files and clear the list (job-end cleanup: runs
    must not accumulate in a shared work_dir across jobs, ADVICE r5).
    Idempotent; missing files are fine (another cleanup or `clean` got
    there first)."""
    for path in runs:
        try:
            os.unlink(path)
        except OSError:
            pass
    runs.clear()


_SHARD_MIX = 0x9E3779B97F4A7C15  # splitmix64 finalizer multiplier
_U64 = (1 << 64) - 1


def shard_of_packed(packed: int, n_shards: int) -> int:
    """THE fold-shard routing function (ISSUE 9): xor-shift + odd-multiply
    bit mix of the packed key, then high-bits modulo. One definition
    shared by the native kernel (loader.cpp mr_scan_count_sharded computes
    the identical expression), the Python fallback scan
    (:func:`shard_ids_of_packed`), the sanitizer's route check and the
    egress lookup — a second copy that drifted would silently split a
    key's folds across two shards. The mix matters: a bare ``packed % S``
    is just the low bits of the h2 polynomial lane, and structurally
    correlated token classes (e.g. equal-length doubled-letter words)
    collapse onto one shard there, zeroing fold parallelism."""
    packed = int(packed) & _U64
    x = ((packed ^ (packed >> 33)) * _SHARD_MIX) & _U64
    return (x >> 32) % int(n_shards)


def shard_ids_of_packed(packed, n_shards: int):
    """Vectorized :func:`shard_of_packed` over a uint64 array — the
    Python-fallback router's and the sanitizer route check's shared
    implementation (numpy uint64 arithmetic wraps exactly like the C
    kernel's)."""
    packed = np.asarray(packed, dtype=np.uint64)
    x = (packed ^ (packed >> np.uint64(33))) * np.uint64(_SHARD_MIX)
    return (x >> np.uint64(32)) % np.uint64(n_shards)


class Dictionary:
    """hash pair → word bytes, built incrementally at ingest.

    Bounded-memory tier (VERDICT r4 missing 3): with ``budget_words`` set,
    the word store flushes to a SORTED run file on disk
    (``spill_dir/dictrun-*.bin``, the binary columnar format of
    runtime/spill.py — packed-uint64 key column + varint lengths + word
    bytes, ISSUE 11) whenever it crosses the budget, keeping only the
    packed-key/length arrays (8+8 bytes per word) in RAM for dedup +
    collision probing. The flush is a HANDOFF, not a write: the RAM tier
    freezes into a snapshot and a background
    :class:`~mapreduce_rust_tpu.runtime.spill.AsyncSpillWriter` sorts,
    packs and writes it while this thread keeps scanning
    (``async_spill=False`` / ``MR_SPILL_SYNC=1`` restores the inline
    write). A spilled dictionary no longer serves point ``lookup`` for
    flushed words — egress must consume ``iter_sorted()`` /
    ``run_sources()`` (the streaming merge-join in runtime/driver.run_job
    does). Equal-length pair collisions on flushed words pass undetected,
    the same degradation add_scanned_raw documents.
    """

    def __init__(self, budget_words: int | None = None,
                 spill_dir: str | None = None,
                 async_spill: bool = True) -> None:
        if budget_words is not None and not spill_dir:
            raise ValueError("budget_words needs a spill_dir")
        self.budget_words = budget_words
        self.spill_dir = spill_dir
        self.async_spill = async_spill
        self._writer: "spill_io.AsyncSpillWriter | None" = None
        self._word_of: dict[tuple[int, int], bytes] = {}
        self._seen: set[bytes] = set()
        # (k1<<32)|k2 (always non-negative Python int) → stored word length.
        # Doubles as the fast-path membership filter AND the cheap collision
        # probe: a same-pair different-length word is caught without slicing.
        self._len_of: dict[int, int] = {}
        self.collisions: list[tuple[bytes, bytes]] = []  # (kept, rejected)
        # Vectorized steady-state filter for add_scanned_raw: sorted packed
        # keys + aligned stored lengths. Keys inserted since the last merge
        # wait in _fresh_* (they just take the slow per-key path until
        # merged), so membership for a saturated vocabulary is one
        # searchsorted instead of 10^4-10^5 dict lookups per window.
        self._packed_sorted = np.empty(0, dtype=np.uint64)
        self._sorted_lens = np.empty(0, dtype=np.int64)
        self._fresh_keys: list[int] = []
        self._fresh_lens: list[int] = []
        self._runs: list[str] = []
        self._total_words = 0  # RAM + flushed distinct words
        self._run_token = new_run_token()

    def __len__(self) -> int:
        return self._total_words

    def _guard_ram_only(self, what: str) -> None:
        """A budget flush moved words to disk runs: a RAM-tier point probe
        would silently answer from a PARTIAL store (flushed words absent).
        Raise instead — spilled dictionaries serve iter_sorted() only."""
        if self._runs:
            raise RuntimeError(
                f"Dictionary.{what} after a budget flush would only see the "
                "RAM tier (flushed words live in disk runs) — consume "
                "iter_sorted() instead"
            )

    def __contains__(self, key: tuple[int, int]) -> bool:
        self._guard_ram_only("__contains__")
        return key in self._word_of

    @property
    def spilled(self) -> bool:
        return bool(self._runs)

    @property
    def run_count(self) -> int:
        return len(self._runs)

    def lookup(self, k1: int, k2: int) -> bytes | None:
        """Point lookup — RAM-resident words only. A spilled dictionary
        (see class docstring) serves flushed words via iter_sorted()."""
        return self._word_of.get((k1, k2))

    def _maybe_flush(self) -> None:
        if self.budget_words is not None and len(self._word_of) >= self.budget_words:
            self._flush_words()

    def _flush_words(self) -> None:
        """Spill the in-RAM word store as one sorted binary run; keep only
        the packed-key/length arrays for membership + collision probes.
        The expensive half — ``np.argsort`` over the packed keys, the
        varint pack, the write itself — runs on the background writer
        thread against a FROZEN snapshot; this thread only swaps in fresh
        containers and enqueues (spill backpressure, when the writer falls
        two runs behind, is timed into the writer's ``stall_s``)."""
        if not self._word_of:
            return
        self._merge_fresh()
        os.makedirs(self.spill_dir, exist_ok=True)
        run_index = len(self._runs)
        path = os.path.join(
            self.spill_dir,
            spill_io.run_file_name("dictrun", self._run_token, run_index,
                                   "bin"),
        )
        # Freeze the RAM tier: the snapshot dict is never touched again by
        # this thread (fresh containers swap in), so the writer reads it
        # without a lock. Membership stays exact via _packed_sorted; the
        # per-key dicts would otherwise grow unbounded beside the words.
        snapshot = self._word_of
        self._word_of = {}
        self._seen = set()
        self._len_of = {}
        self._runs.append(path)
        token = self._run_token

        def task() -> int:
            from mapreduce_rust_tpu.runtime.trace import trace_span

            with trace_span("dictionary.flush", words=len(snapshot),
                            run=run_index):
                keys, ends, buf = spill_io.pack_word_map(snapshot)
                return spill_io.write_run_file(
                    path, token, keys, ends, buf, run_index=run_index
                )

        self._ensure_writer().submit(task)

    def _ensure_writer(self) -> "spill_io.AsyncSpillWriter":
        self._writer = spill_io.ensure_writer(
            self._writer, f"mr/spill-dict-{self._run_token}",
            sync=not self.async_spill,
        )
        return self._writer

    def drain_spills(self) -> None:
        """Barrier: every enqueued run is on disk (or the writer's error
        re-raises here, on the owner thread). Called before any read of
        the runs — egress merge, iter_sorted, save — and before final
        spill accounting."""
        if self._writer is not None:
            self._writer.drain()

    def close_spills(self, abort: bool = True) -> None:
        """Stop the writer thread; pending snapshots are discarded on
        abort (the caller is deleting the run files anyway). Idempotent,
        never raises — exception-path teardown must not mask the job's
        real error."""
        if self._writer is not None:
            self._writer.close(abort=abort)

    def spill_stats(self) -> dict:
        """Final spill accounting (collect AFTER drain/close): writer
        seconds, owner stall seconds, bytes, runs, and the per-run
        write_s histogram. Zeros when this dictionary never spilled."""
        return spill_io.tier_spill_stats(self._writer, len(self._runs))

    def spill_snapshot(self) -> "tuple[float, float, int] | None":
        """(write_s, stall_s, bytes) right now — benign-stale reads of the
        writer's float cells for the live metrics ring (the PR 9 fold
        pattern: exact finals land at collect time). None = never spilled
        (the common case stays two attribute reads)."""
        return spill_io.tier_spill_snapshot(self._writer)

    def remove_runs(self) -> None:
        """Job-end cleanup of this dictionary's spill run files (the driver
        owns the lifecycle). Closes the writer first — a run mid-write
        must finish (or be discarded) before its file is unlinked."""
        self.close_spills(abort=True)
        remove_run_files(self._runs)

    def _stored_len(self, packed: int) -> "int | None":
        """Stored word length for a packed key, or None if unseen — exact
        membership across BOTH tiers (fresh dict + merged sorted arrays),
        which is what keeps dedup correct after a flush clears the dicts."""
        v = self._len_of.get(packed)
        if v is not None:
            return v
        if len(self._packed_sorted):
            p = np.uint64(packed)
            i = int(np.searchsorted(self._packed_sorted, p))
            if i < len(self._packed_sorted) and self._packed_sorted[i] == p:
                return int(self._sorted_lens[i])
        return None

    def _insert_hashed(self, words, keys) -> int:
        """Single insert/collision-detection path shared by the Python and
        native ingest branches (first word wins; differing word on an
        existing pair is a recorded collision)."""
        added = 0
        seen, word_of = self._seen, self._word_of
        for w, (k1, k2) in zip(words, keys.tolist()):
            if w in seen:
                continue
            seen.add(w)
            key = (k1, k2)
            packed = (k1 << 32) | k2
            if self._stored_len(packed) is None:
                self._len_of[packed] = len(w)
                # Every insert path must feed the vectorized filter, or the
                # key stays permanently "suspicious" to add_scanned_raw.
                self._fresh_keys.append(packed)
                self._fresh_lens.append(len(w))
                word_of[key] = w
                added += 1
                self._total_words += 1
            else:
                prev = word_of.get(key)
                if prev is not None and prev != w:
                    self.collisions.append((prev, w))
                elif prev is None:
                    # Flushed word recurring after a budget flush: dedup
                    # held via _stored_len, but it must NOT rejoin _seen —
                    # that set would regrow toward the whole vocabulary,
                    # defeating the budget (it costs a re-hash per later
                    # recurrence on this fallback path; bounded beats fast
                    # here). An equal-pair different word goes undetected
                    # (class-docstring degradation).
                    seen.discard(w)
        self._maybe_flush()
        return added

    def add_scanned_raw(self, raw: bytes, ends: np.ndarray, keys: np.ndarray) -> int:
        """Fold a scan_unique_raw result. Keys are filtered against the
        packed-key table first; word bytes are sliced only for unseen keys,
        so in steady state (saturated vocabulary) this touches almost
        nothing. Collision checking on this path: a repeated pair whose
        word LENGTH differs from the stored word's is sliced and verified
        (recorded if different); an equal-length different-word pair
        collision passes undetected — covered by the same ~2^-64 birthday
        bound as the pair keying itself (SURVEY.md §7 hard part 3)."""
        n = len(ends)
        if n == 0:
            return 0
        packed = (
            (keys[:, 0].astype(np.uint64) << np.uint64(32)) | keys[:, 1].astype(np.uint64)
        )
        wlens = np.diff(ends, prepend=np.int64(0))
        # Steady-state fast path: a key already in the sorted table with a
        # matching length needs no Python at all.
        if len(self._packed_sorted):
            idx = np.searchsorted(self._packed_sorted, packed)
            idx_c = np.minimum(idx, len(self._packed_sorted) - 1)
            known = (self._packed_sorted[idx_c] == packed) & (
                self._sorted_lens[idx_c] == wlens
            )
        else:
            known = np.zeros(n, dtype=bool)
        suspicious = np.nonzero(~known)[0]
        added = 0
        if len(suspicious):
            # Vectorized tier membership for the whole suspicious batch:
            # merged tier via searchsorted, unmerged tier via np.isin over
            # the fresh buffer. Their union IS _len_of's key set (inserts
            # feed _fresh_keys; _merge_fresh moves them to _packed_sorted),
            # so no per-key dict probe is needed to find the NEW keys —
            # the per-key Python loop here was the high-cardinality ingest
            # bottleneck (≈half the host-glue time at 1e6 distinct/window).
            p_sus = packed[suspicious]
            if len(self._packed_sorted):
                # Reuse the full-batch bisection from the fast path above.
                in_sorted = self._packed_sorted[idx_c[suspicious]] == p_sus
            else:
                in_sorted = np.zeros(len(p_sus), dtype=bool)
            if self._fresh_keys:
                in_fresh = np.isin(p_sus, np.asarray(self._fresh_keys, dtype=np.uint64))
            else:
                in_fresh = np.zeros(len(p_sus), dtype=bool)
            new_mask = ~in_sorted & ~in_fresh

            new_i = suspicious[new_mask]
            if len(new_i):
                # Intra-batch pair collisions (two DIFFERENT words, equal
                # packed key, in one window): keep the FIRST occurrence
                # (scan order = first occurrence order) and record the
                # rest — 'checked, not assumed' (module docstring) even
                # inside a single batch.
                _uniq, first_pos = np.unique(packed[new_i], return_index=True)
                if len(first_pos) != len(new_i):
                    keep = np.zeros(len(new_i), dtype=bool)
                    keep[first_pos] = True
                    dup_i = new_i[~keep]
                    new_i = new_i[keep]
                else:
                    dup_i = new_i[:0]
                starts = np.where(new_i > 0, ends[new_i - 1], 0)
                words = [
                    raw[s:e]
                    for s, e in zip(starts.tolist(), ends[new_i].tolist())
                ]
                key_pairs = list(
                    zip(keys[new_i, 0].tolist(), keys[new_i, 1].tolist())
                )
                p_new = packed[new_i].tolist()
                w_new = wlens[new_i].tolist()
                # Batch C-loop updates (keys unique within the batch after
                # the dedup above and new to both tiers — no clobbering).
                self._word_of.update(zip(key_pairs, words))
                self._seen.update(words)
                self._len_of.update(zip(p_new, w_new))
                self._fresh_keys.extend(p_new)
                self._fresh_lens.extend(w_new)
                added += len(words)
                self._total_words += len(words)
                for i, s in zip(dup_i.tolist(),
                                np.where(dup_i > 0, ends[dup_i - 1], 0).tolist()):
                    w = raw[s:ends[i]]
                    prev = self._word_of.get((int(keys[i, 0]), int(keys[i, 1])))
                    if prev is not None and prev != w:
                        self._seen.add(w)
                        self.collisions.append((prev, w))

            # Known keys (either tier): the rare collision-candidate set —
            # per-key work is fine here. (A suspicious in_sorted key has a
            # length mismatch by construction: `known` required the match.)
            mm = suspicious[in_sorted | in_fresh]
            if len(mm):
                mm_starts = np.where(mm > 0, ends[mm - 1], 0)
                word_of, seen = self._word_of, self._seen
                for i, s in zip(mm.tolist(), mm_starts.tolist()):
                    e = int(ends[i])
                    stored = self._stored_len(int(packed[i]))
                    if stored is None or stored == e - s:
                        continue
                    w = raw[s:e]
                    prev = word_of.get((int(keys[i, 0]), int(keys[i, 1])))
                    if prev is not None and prev != w and w not in seen:
                        seen.add(w)
                        self.collisions.append((prev, w))
            # Geometric threshold: rebuilding the sorted table costs O(V),
            # so amortize it against a constant fraction of V — a fixed
            # batch size would make maintenance O(V^2/batch) on
            # high-cardinality corpora.
            if len(self._fresh_keys) >= max(1024, len(self._packed_sorted) // 4):
                self._merge_fresh()
            self._maybe_flush()
        return added

    def _merge_fresh(self) -> None:
        if not self._fresh_keys:
            return
        pk = np.concatenate(
            [self._packed_sorted, np.asarray(self._fresh_keys, dtype=np.uint64)]
        )
        ln = np.concatenate(
            [self._sorted_lens, np.asarray(self._fresh_lens, dtype=np.int64)]
        )
        order = np.argsort(pk, kind="stable")
        self._packed_sorted = pk[order]
        self._sorted_lens = ln[order]
        self._fresh_keys.clear()
        self._fresh_lens.clear()

    def add_words(self, words: Iterable[bytes]) -> int:
        """Insert unseen words; returns the number of new entries.

        Dedup is C-speed set algebra (set() + difference), not a per-token
        Python loop — this runs once per chunk on the ingest hot path,
        overlapped with device compute.
        """
        fresh = list(set(words) - self._seen)
        if not fresh:
            return 0
        return self._insert_hashed(fresh, hash_words(fresh))

    def add_scanned(self, words: list[bytes], keys: np.ndarray) -> int:
        """Insert a pre-scanned (words, hash pairs) batch — the driver runs
        scan_unique on a thread pool (the C pass releases the GIL) and folds
        results here on one thread; dict state is never touched concurrently."""
        return self._insert_hashed(words, keys)

    def add_text(self, normalized: bytes) -> int:
        """Ingest one normalized chunk. Prefers the one-pass native scanner
        (native/loader.cpp: tokenize+dedupe+hash in C++); falls back to the
        pure-Python three-pass path when the toolchain is unavailable."""
        from mapreduce_rust_tpu.native.host import scan_unique_raw

        res = scan_unique_raw(normalized)
        if res is None:
            return self.add_words(extract_words(normalized))
        return self.add_scanned_raw(*res)

    def items(self) -> Iterator[tuple[tuple[int, int], bytes]]:
        """RAM-resident entries; raises once any run has been flushed to
        disk (a partial iteration would silently drop flushed words) —
        spilled dictionaries are served whole by iter_sorted()."""
        self._guard_ram_only("items")
        return iter(self._word_of.items())

    def run_sources(self) -> "list[spill_io.RunSource]":
        """The key-disjoint sorted merge sources of this dictionary: every
        binary disk run memory-mapped, plus the RAM tier packed with the
        same vectorized argsort the flush uses. Drains the async writer
        first — a run still in flight must hit disk before it is read."""
        self.drain_spills()
        sources = [spill_io.read_run_file(p) for p in self._runs]
        if self._word_of:
            keys, ends, buf = spill_io.pack_word_map(self._word_of)
            sources.append(spill_io.RunSource(keys, ends, buf))
        return sources

    def iter_sorted(self) -> Iterator[tuple[int, int, int, bytes]]:
        """(packed, k1, k2, word) over the WHOLE dictionary — disk runs
        plus the RAM tier — in ascending packed-key order. Tiers are
        key-disjoint by construction (membership spans both), so this is a
        plain k-way merge with no dedup, generated from the SAME block
        merge the batched egress consumes (runtime/spill.merge_sources:
        native loser tree over the memory-mapped key columns, argsort
        fallback) — the per-line text parse this replaces was half the
        spill-engaged egress wall (ISSUE 11)."""
        return spill_io.iter_sources_sorted(self.run_sources())

    def merge(self, other: "Dictionary") -> None:
        if other.spilled:
            raise ValueError("cannot merge a disk-spilled dictionary")
        self.collisions.extend(other.collisions)
        for key, w in other._word_of.items():
            packed = (key[0] << 32) | key[1]
            if self._stored_len(packed) is None:
                self._word_of[key] = w
                self._seen.add(w)
                self._len_of[packed] = len(w)
                self._fresh_keys.append(packed)
                self._fresh_lens.append(len(w))
                self._total_words += 1
            else:
                prev = self._word_of.get(key)
                if prev is not None and prev != w:
                    self.collisions.append((prev, w))
        self._maybe_flush()

    # ---- persistence (the multi-process control-plane path: map tasks
    # write dictionary shards next to their spilled partials, reduce tasks
    # merge them — the TPU analog of the reference's mr-{m}-{r}.txt files) --

    def save(self, path: str | os.PathLike) -> None:
        """One binary container (the runtime/spill run format + a
        collision section): the tiers merge into a single globally sorted
        key column, and the word bytes STREAM to disk per merge block in
        a second pass — a spilled dictionary saves in O(keys + block)
        memory, never rehydrated into a Python dict (the bounded-memory
        contract that made it spill in the first place). ``load`` sniffs
        the magic, so pre-binary text saves (the 'k1 k2 word' /
        '! kept rejected' line format) still load."""
        sources = self.run_sources()  # drains the writer
        # Pass 1: ONE k-way merge; the key/length columns plus the
        # (src, idx) streams are retained (~28 B/key — small next to the
        # word bytes, which never materialize whole). The header needs
        # the totals up front, so the word bytes stream in pass 2 from
        # the retained blocks without re-running the merge.
        key_parts: list[np.ndarray] = []
        len_parts: list[np.ndarray] = []
        blocks: list[tuple] = []
        for keys, src, idx in spill_io.merge_sources(sources):
            key_parts.append(keys)
            blocks.append((src, idx))
            lens = np.empty(len(keys), dtype=np.int64)
            for s in np.unique(src).tolist():
                sel = np.nonzero(src == s)[0]
                ends_arr = sources[s].ends
                ii = idx[sel]
                lens[sel] = ends_arr[ii] - np.where(
                    ii > 0, ends_arr[ii - 1], 0
                )
            len_parts.append(lens)
        if key_parts:
            all_keys = np.ascontiguousarray(
                np.concatenate(key_parts), dtype="<u8")
            all_lens = np.concatenate(len_parts)
        else:
            all_keys = np.empty(0, dtype="<u8")
            all_lens = np.empty(0, dtype=np.int64)
        lens_b = spill_io.encode_varints(all_lens)
        with open(path, "wb") as f:
            f.write(spill_io.pack_header_for_save(
                self._run_token, len(all_keys), len(lens_b),
                len(self.collisions),
            ))
            f.write(all_keys.tobytes())
            f.write(lens_b)
            # Pass 2: word bytes, one joined buffer per retained block.
            for src, idx in blocks:
                f.write(b"".join(
                    spill_io.slice_block_words(sources, src, idx)
                ))
            for kept, rejected in self.collisions:
                f.write(spill_io.encode_varints(
                    np.asarray([len(kept)], dtype=np.uint64)))
                f.write(kept)
                f.write(spill_io.encode_varints(
                    np.asarray([len(rejected)], dtype=np.uint64)))
                f.write(rejected)

    @classmethod
    def load(cls, path: str | os.PathLike) -> "Dictionary":
        """Version-sniffing load (ISSUE 11 satellite): the binary magic
        selects the columnar parse; anything else takes the legacy text
        parse, so dictionaries saved by the text plane still load. An
        unknown BINARY schema version fails loudly in read_run_header —
        the migration exit path, never a silent misparse."""
        with open(path, "rb") as f:
            head = f.read(4)
        if head == spill_io.RUN_MAGIC:
            return cls._load_binary(path)
        return cls._load_text(path)

    @classmethod
    def _load_binary(cls, path) -> "Dictionary":
        d = cls()
        src = spill_io.read_run_file(str(path))
        d.collisions.extend(src.collisions)
        keys = src.keys
        ends = src.ends.tolist()
        data = src.data
        data_b = data if isinstance(data, bytes) else bytes(
            memoryview(data))
        start = 0
        for packed, end in zip(keys.tolist(), ends):
            w = data_b[start:end]
            start = end
            k1, k2 = packed >> 32, packed & 0xFFFFFFFF
            if (k1, k2) not in d._word_of:
                d._total_words += 1
            d._insert_loaded(k1, k2, packed, w)
        return d

    @classmethod
    def _load_text(cls, path) -> "Dictionary":
        d = cls()
        with open(path, "rb") as f:
            for line in f:
                if line.startswith(b"! "):
                    _, kept, rejected = line.rstrip(b"\n").split(b" ", 2)
                    d.collisions.append((kept, rejected))
                    continue
                a, b, w = line.rstrip(b"\n").split(b" ", 2)
                k1, k2 = int(a), int(b)
                if (k1, k2) not in d._word_of:
                    d._total_words += 1
                d._insert_loaded(k1, k2, (k1 << 32) | k2, w)
        return d

    def _insert_loaded(self, k1: int, k2: int, packed: int, w: bytes) -> None:
        self._word_of[(k1, k2)] = w
        self._seen.add(w)
        if packed not in self._len_of:
            self._len_of[packed] = len(w)
            # Every insert path must feed the vectorized tiers:
            # add_scanned_raw's membership is (merged | fresh), so a
            # loaded key that skipped them would be re-insertable.
            self._fresh_keys.append(packed)
            self._fresh_lens.append(len(w))


class ShardedDictionary:
    """Key-hash-sharded egress dictionary (ISSUE 9): S independent
    :class:`Dictionary` shards, each owned by exactly one fold thread of
    the host-map engine's fold plane (runtime/driver._FoldShardPlane),
    merged only at egress.

    Shards are key-DISJOINT by construction — a key lives on shard
    ``shard_of_packed(packed, S)`` and nowhere else — so no cross-shard
    dedup exists and ``iter_sorted`` is a plain k-way interleave of the
    per-shard sorted streams (each shard's runs + RAM tier ride inside its
    own ``Dictionary.iter_sorted``, so the spill tiers compose for free).
    Collision accounting, word totals and spill-run counts aggregate over
    the shards; collision ORDER across shards is not meaningful (only the
    count is observable downstream).

    Mutations go through the shards directly (the fold plane holds each
    shard and folds into it on its owner thread); this wrapper exposes only
    the READ/lifecycle surface run_job's finalize paths consume. It is a
    single-process host-engine structure: the checkpoint/multihost
    ``save``/``merge`` persistence contract stays on plain Dictionary
    (those paths never construct a sharded instance — run_job gates on it).
    """

    def __init__(self, shards: "list[Dictionary]") -> None:
        if not shards:
            raise ValueError("ShardedDictionary needs at least one shard")
        self.shards = list(shards)

    @property
    def n_shards(self) -> int:
        return len(self.shards)

    def shard_of(self, k1: int, k2: int) -> int:
        return shard_of_packed((k1 << 32) | k2, len(self.shards))

    def __len__(self) -> int:
        return sum(len(s) for s in self.shards)

    @property
    def collisions(self) -> list:
        return [c for s in self.shards for c in s.collisions]

    @property
    def spilled(self) -> bool:
        return any(s.spilled for s in self.shards)

    @property
    def run_count(self) -> int:
        return sum(s.run_count for s in self.shards)

    def remove_runs(self) -> None:
        for s in self.shards:
            s.remove_runs()

    def drain_spills(self) -> None:
        for s in self.shards:
            s.drain_spills()

    def close_spills(self, abort: bool = True) -> None:
        for s in self.shards:
            s.close_spills(abort=abort)

    def spill_stats(self) -> dict:
        """Aggregate spill accounting over the shards (one async writer
        per shard): write/stall seconds and bytes sum; the per-run write
        histograms merge into one."""
        from mapreduce_rust_tpu.runtime.histogram import Histogram

        out = {"write_s": 0.0, "stall_s": 0.0, "bytes": 0, "runs": 0,
               "hist": None}
        hist = None
        for s in self.shards:
            st = s.spill_stats()
            out["write_s"] += st["write_s"]
            out["stall_s"] += st["stall_s"]
            out["bytes"] += st["bytes"]
            out["runs"] += st["runs"]
            h = st["hist"]
            if h is not None and h.count:
                if hist is None:
                    hist = Histogram()
                hist.merge(h)
        out["hist"] = hist
        return out

    def spill_snapshot(self) -> "tuple[float, float, int] | None":
        total = None
        for s in self.shards:
            snap = s.spill_snapshot()
            if snap is None:
                continue
            if total is None:
                total = [0.0, 0.0, 0]
            total[0] += snap[0]
            total[1] += snap[1]
            total[2] += snap[2]
        return tuple(total) if total is not None else None

    def run_sources(self) -> list:
        """Every shard's merge sources in one flat list: shards are
        key-disjoint like tiers, so the batched egress merges ALL of them
        in one k-way pass — no per-shard interleave layer."""
        out: list = []
        for s in self.shards:
            out.extend(s.run_sources())
        return out

    def lookup(self, k1: int, k2: int) -> "bytes | None":
        return self.shards[self.shard_of(k1, k2)].lookup(k1, k2)

    def items(self):
        """RAM-resident entries across all shards; each shard raises its
        own spilled-API guard (same contract as Dictionary.items)."""
        import itertools

        return itertools.chain.from_iterable(s.items() for s in self.shards)

    def iter_sorted(self):
        """(packed, k1, k2, word) over ALL shards in ascending packed-key
        order — the same contract Dictionary.iter_sorted serves, so the
        streaming merge-join egress is shard-count-blind. Shards are
        key-disjoint, hence one flat dedup-free k-way merge over every
        shard's runs + RAM tiers (ISSUE 11: the loser tree sees all
        sources at once instead of a heap-of-heaps interleave)."""
        return spill_io.iter_sources_sorted(self.run_sources())
