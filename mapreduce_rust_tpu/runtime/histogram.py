"""Streaming log-bucket histogram — the percentile primitive of the doctor.

The observability doctrine (runtime/metrics.py) allows per-chunk/per-round
work but forbids per-record work; this histogram keeps that contract: an
``add`` is one bisect over a fixed 61-edge table plus a few scalar updates,
cheap enough for every site we currently only sum — per-window host-map
scan/glue durations, per-round ``mesh.all_to_all`` latencies, per-RPC
control-plane latencies, per-task attempt durations. Manifests then carry
p50/p95/p99/max where they used to carry a single total, which is what
lets ``doctor`` tell a uniformly slow run from one dragged by a tail.

Design constraints:

- **Fixed log-spaced buckets** (5 per decade, 1e-7 .. 1e5 — sub-µs RPC
  dispatch up to day-long jobs), so two histograms from different
  processes/runs are ALWAYS mergeable bucket-for-bucket: no rescaling, no
  resampling. Values outside the range land in under/overflow buckets and
  their percentiles clamp to the exact min/max, which are tracked
  separately.
- **Sparse serialization**: only occupied buckets are written, so a
  manifest histogram is a few dozen ints, not a 61-wide array.
- **Self-describing**: ``to_dict`` precomputes p50/p95/p99 so a reader
  (the doctor, a human in a manifest diff) needs no bucket math; the
  buckets ride along for exact re-merging.

No imports beyond the stdlib and no jax: control-plane processes
(coordinator, doctor CLI) must use this without dragging in a backend.
"""

from __future__ import annotations

import math
from bisect import bisect_right

_PER_DECADE = 5
_LO_EXP = -7            # lowest edge 1e-7 (0.1 µs)
_HI_EXP = 5             # highest edge 1e5 (~28 h)
_N_BUCKETS = (_HI_EXP - _LO_EXP) * _PER_DECADE
#: Bucket edges; value v lands in bucket ``bisect_right(EDGES, v)``:
#: index 0 is the underflow bucket (v <= 1e-7, incl. zeros/negatives),
#: index len(EDGES) the overflow bucket (v > 1e5).
EDGES: tuple = tuple(
    10.0 ** (_LO_EXP + i / _PER_DECADE) for i in range(_N_BUCKETS + 1)
)

SCHEMA = 1


class Histogram:
    """Mergeable streaming histogram with exact count/sum/min/max and
    log-bucket percentiles (geometric-midpoint estimate, clamped to the
    exact extremes — a one-sample histogram reports p50 == that sample).
    """

    __slots__ = ("count", "total", "min", "max", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.buckets: dict[int, int] = {}

    def add(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        idx = bisect_right(EDGES, v)
        self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def merge(self, other: "Histogram") -> "Histogram":
        self.count += other.count
        self.total += other.total
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        for idx, n in other.buckets.items():
            self.buckets[idx] = self.buckets.get(idx, 0) + n
        return self

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def percentile(self, q: float) -> "float | None":
        """Value at quantile ``q`` in [0, 1], or None when empty. Exact at
        the extremes (min/max), bucket-geometric-midpoint in between."""
        if self.count == 0:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        # Nearest-rank: the bucket holding the ceil(q * count)-th sample.
        target = max(int(math.ceil(q * self.count)), 1)
        cum = 0
        for idx in sorted(self.buckets):
            cum += self.buckets[idx]
            if cum >= target:
                return self._representative(idx)
        return self.max  # unreachable unless buckets were hand-corrupted

    def _representative(self, idx: int) -> float:
        if idx <= 0:                     # underflow: <= the lowest edge
            return self.min
        if idx >= len(EDGES):            # overflow: > the highest edge
            return self.max
        mid = math.sqrt(EDGES[idx - 1] * EDGES[idx])
        # Clamp to the exact extremes so a near-empty histogram never
        # reports a percentile outside the observed range.
        return min(max(mid, self.min), self.max)

    def to_dict(self) -> dict:
        """JSON-safe sparse form, percentiles precomputed for readers."""
        d: dict = {
            "schema": SCHEMA,
            "count": self.count,
            "total": round(self.total, 9),
        }
        if self.count:
            d["min"] = self.min
            d["max"] = self.max
            d["mean"] = round(self.mean, 9)
            d["p50"] = self.percentile(0.50)
            d["p95"] = self.percentile(0.95)
            d["p99"] = self.percentile(0.99)
            d["buckets"] = {str(i): n for i, n in sorted(self.buckets.items())}
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        """Inverse of ``to_dict`` — the precomputed percentiles are
        recomputable from the buckets and are ignored on load."""
        h = cls()
        h.count = int(d.get("count", 0))
        h.total = float(d.get("total", 0.0))
        if h.count:
            h.min = float(d["min"])
            h.max = float(d["max"])
            h.buckets = {int(i): int(n) for i, n in (d.get("buckets") or {}).items()}
        return h

    def summary(self, scale: float = 1.0, digits: int = 6) -> dict:
        """Compact {count, mean, p50, p95, p99, max} view, values × scale
        (e.g. scale=1e3 renders second-valued samples in ms)."""
        if self.count == 0:
            return {"count": 0}
        return {
            "count": self.count,
            "mean": round(self.mean * scale, digits),
            "p50": round((self.percentile(0.50) or 0.0) * scale, digits),
            "p95": round((self.percentile(0.95) or 0.0) * scale, digits),
            "p99": round((self.percentile(0.99) or 0.0) * scale, digits),
            "max": round(self.max * scale, digits),
        }

    def __len__(self) -> int:
        return self.count

    def __repr__(self) -> str:
        if not self.count:
            return "Histogram(empty)"
        return (
            f"Histogram(n={self.count}, p50={self.percentile(0.5):.4g}, "
            f"p99={self.percentile(0.99):.4g}, max={self.max:.4g})"
        )
