"""Fleet profiler (ISSUE 16): the cross-job, per-worker busy/idle
timeline and its derived numbers — fleet utilization, barrier-bubble
seconds, and per-job pipelining opportunity.

Every observability plane before this one is a single-job view. This
module JOINS the artifacts the existing planes already leave on disk —

- ``{service-root}/service.journal``: the job-lifecycle rows (submit /
  start / done / cancel) whose ``t`` stamps live on the service-uptime
  axis. The LAST ``start`` row of a job is its per-job report's epoch
  (the Coordinator — and its JobReport clock — is created at admission),
  so job-local event times rebase onto the service axis by adding it.
- ``{service-root}/job-*/job_report.json``: each job's ordered
  control-plane event log (grant / expire / finish / late_finish /
  revoke, with ``t``/``phase``/``tid``/``attempt``/``wid``) plus — new
  in ISSUE 16 — the per-reduce-partition readiness table fed by the
  map finish reports' trailing ``part_bytes`` vector.
- a single-job workdir's ``job_report.json``, when pointed at one.

and computes, per worker: busy intervals (grant → finish), **dead**
intervals (grant → lease expiry with no finish — the SIGKILLed attempt's
window, excluded from the idle denominator instead of counted as idle),
idle = presence − busy − dead; and fleet-wide: ``util_frac``,
``idle_frac``, ``bubble_frac`` (idle worker-seconds that overlap a
*bubble window* — any span where a job sat queued, or a running job's
reduce work existed but was blocked behind the global map barrier), and
``pipelining_opportunity_s`` = Σ_r max(reduce-r first grant −
readiness-r, 0) per job — the headroom a phase-pipelining scheduler
(ROADMAP item 1) could reclaim, measured before that scheduler exists.

Crash-tolerant by construction: torn journal tails are skipped, a
missing/partial ``job_report.json`` degrades that job to a
journal-only row instead of failing the report, and every such
degradation is listed under ``errors``. No jax import anywhere — the
profiler is an offline control-plane tool (``python -m
mapreduce_rust_tpu fleet``) and must start in milliseconds.
"""

from __future__ import annotations

import glob
import json
import os

__all__ = [
    "build_fleet_report",
    "fleet_history_row",
    "format_fleet_report",
    "run_cli",
]


# ---------------------------------------------------------------------------
# Interval arithmetic (closed-open [t0, t1) spans)
# ---------------------------------------------------------------------------

def _merge(intervals: list) -> list:
    """Sorted union of [t0, t1) spans."""
    out: list = []
    for t0, t1 in sorted(intervals):
        if t1 <= t0:
            continue
        if out and t0 <= out[-1][1]:
            out[-1][1] = max(out[-1][1], t1)
        else:
            out.append([t0, t1])
    return [(a, b) for a, b in out]

def _total(intervals: list) -> float:
    return sum(t1 - t0 for t0, t1 in intervals)

def _subtract(base: list, holes: list) -> list:
    """base − holes, both merged-sorted span lists."""
    out: list = []
    holes = list(holes)
    for t0, t1 in base:
        cur = t0
        for h0, h1 in holes:
            if h1 <= cur or h0 >= t1:
                continue
            if h0 > cur:
                out.append((cur, min(h0, t1)))
            cur = max(cur, h1)
            if cur >= t1:
                break
        if cur < t1:
            out.append((cur, t1))
    return out

def _intersect(a: list, b: list) -> list:
    out: list = []
    for x0, x1 in a:
        for y0, y1 in b:
            lo, hi = max(x0, y0), min(x1, y1)
            if hi > lo:
                out.append((lo, hi))
    return _merge(out)


# ---------------------------------------------------------------------------
# Artifact loading (crash-tolerant: every failure degrades, none raise)
# ---------------------------------------------------------------------------

def _load_service_journal(path: str, errors: list) -> list:
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        errors.append(f"service.journal unreadable: {e}")
        return []
    rows: list = []
    for line in raw.splitlines():
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail from a crashed append
        if isinstance(row, dict) and row.get("job") and "op" in row:
            rows.append(row)
    return rows

def _load_job_report(path: str, errors: list) -> "dict | None":
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError:
        return None  # never flushed (job mid-flight at crash): caller
        # degrades to a journal-only row — absence is not an error here
    except json.JSONDecodeError as e:
        errors.append(f"{path}: torn/partial report ({e}) — skipped")
        return None
    if not isinstance(doc, dict):
        errors.append(f"{path}: not a report object — skipped")
        return None
    rep = doc.get("report", doc)
    return rep if isinstance(rep, dict) else None


# ---------------------------------------------------------------------------
# Timeline construction
# ---------------------------------------------------------------------------

def _job_intervals(jid: "str | None", events: list, base: float,
                   end_hint: float) -> tuple:
    """One job's event log → (timeline rows, last event t). Busy rows
    span grant → finish/late_finish/revoke of the same (wid, phase,
    tid); a grant settled only by a lease ``expire`` — or never settled
    at all — becomes a **dead** row (the attempt's worker stopped
    reporting: crash, SIGKILL, or wedge), which the caller excludes
    from that worker's idle denominator. Times are rebased onto the
    caller's axis by ``base``."""
    open_grants: dict = {}   # (phase, tid) → [t, attempt, wid]
    rows: list = []
    t_max = 0.0

    def _row(t0: float, t1: float, state: str, phase, tid, wid) -> None:
        if t1 <= t0 or wid is None:
            return
        rows.append({
            "wid": wid, "t0": round(base + t0, 6), "t1": round(base + t1, 6),
            "state": state, "job": jid, "phase": phase, "tid": tid,
        })

    for ev in events:
        if not isinstance(ev, dict):
            continue
        t = ev.get("t")
        kind = ev.get("ev")
        if not isinstance(t, (int, float)) or not isinstance(kind, str):
            continue
        t_max = max(t_max, t)
        phase, tid, wid = ev.get("phase"), ev.get("tid"), ev.get("wid")
        key = (phase, tid)
        if kind == "grant":
            prev = open_grants.pop(key, None)
            if prev is not None:
                # Re-grant over a still-open attempt (expiry row raced or
                # was dropped at the event cap): the old attempt is dead.
                _row(prev[0], t, "dead", phase, tid, prev[2])
            open_grants[key] = [t, ev.get("attempt"), wid]
        elif kind in ("finish", "late_finish", "revoke"):
            g = open_grants.pop(key, None)
            if g is not None:
                # Revoked losers still COMPUTED until the revocation —
                # the worker was busy, just uselessly so.
                _row(g[0], t, "busy", phase, tid,
                     wid if wid is not None else g[2])
        elif kind == "expire":
            g = open_grants.pop(key, None)
            if g is not None:
                _row(g[0], t, "dead", phase, tid, g[2])
    for (phase, tid), g in open_grants.items():
        # Open at end of log: the job (or the service) went down with the
        # attempt in flight.
        _row(g[0], max(end_hint - base, t_max), "dead", phase, tid, g[2])
    return rows, t_max


def _job_pipelining(report: dict) -> tuple:
    """(pipelining_opportunity_s, per-partition detail) from one job's
    readiness table + its reduce grant events. Job-local axis — both
    sides share the report epoch, no rebase needed."""
    parts = report.get("partitions")
    if not isinstance(parts, dict) or not parts:
        return 0.0, {}
    first_reduce_grant: dict = {}
    for ev in report.get("events") or []:
        if (isinstance(ev, dict) and ev.get("ev") == "grant"
                and ev.get("phase") == "reduce"
                and isinstance(ev.get("t"), (int, float))
                and ev.get("tid") is not None):
            first_reduce_grant.setdefault(ev["tid"], ev["t"])
    total = 0.0
    detail: dict = {}
    for r_key, slot in parts.items():
        if not isinstance(slot, dict):
            continue
        ready = slot.get("ready_s")
        try:
            r = int(r_key)
        except (TypeError, ValueError):
            continue
        start = first_reduce_grant.get(r)
        if ready is None or start is None:
            continue
        gap = max(start - ready, 0.0)
        total += gap
        detail[str(r)] = {
            "ready_s": round(ready, 6),
            "reduce_start_s": round(start, 6),
            "gap_s": round(gap, 6),
            "bytes": slot.get("bytes", 0),
        }
    return round(total, 6), detail


# ---------------------------------------------------------------------------
# The report
# ---------------------------------------------------------------------------

def build_fleet_report(target: str) -> dict:
    """Join the artifacts under ``target`` (a service root or a
    single-job workdir) into one fleet report dict. Crash-tolerant:
    partial artifacts degrade into ``errors`` entries, never exceptions
    (short of the target simply not existing)."""
    errors: list = []
    journal_path = os.path.join(target, "service.journal")
    job_dirs = sorted(glob.glob(os.path.join(target, "job-*")))
    service_mode = os.path.isfile(journal_path) or bool(job_dirs)

    jobs: dict = {}          # jid → job row (lifecycle + metrics)
    timeline: list = []
    end = 0.0

    if service_mode:
        for row in _load_service_journal(journal_path, errors):
            jid, op, t = row["job"], row["op"], row.get("t")
            if not isinstance(t, (int, float)):
                continue
            end = max(end, t)
            j = jobs.setdefault(jid, {"state": "unknown"})
            if op == "submit":
                j["submit_t"] = t
                j["priority"] = row.get("priority", 0)
                spec = row.get("spec")
                if isinstance(spec, dict):
                    j["app"] = spec.get("app")
            elif op == "start":
                j["start_t"] = t   # LAST start wins: restart re-admission
                j["state"] = "running"
            elif op == "done":
                j["done_t"] = t
                j["state"] = row.get("state", "done")
                if row.get("cached"):
                    j["cached"] = True
            elif op == "cancel":
                j.setdefault("done_t", t)
                j["state"] = "cancelled"
        report_dirs = {os.path.basename(d)[len("job-"):]: d
                       for d in job_dirs if os.path.isdir(d)}
    else:
        report_dirs = {None: target}

    for jid, d in sorted(report_dirs.items(), key=lambda kv: str(kv[0])):
        rep = _load_job_report(os.path.join(d, "job_report.json"), errors)
        if not service_mode:
            # Single-job mode: name the row after the report's own job
            # id (None for the classic coordinator — render as "job").
            jid = (rep or {}).get("job") or "job"
        j = jobs.setdefault(jid, {"state": "unknown"})
        if rep is None:
            j["partial"] = True
            errors.append(
                f"job {jid or os.path.basename(d)}: no readable "
                "job_report.json — journal-only row"
            )
            continue
        base = j.get("start_t", j.get("submit_t", 0.0)) if service_mode \
            else 0.0
        rows, t_max = _job_intervals(jid, rep.get("events") or [],
                                     base, end)
        timeline.extend(rows)
        end = max(end, base + t_max)
        opp, parts = _job_pipelining(rep)
        j["pipelining_opportunity_s"] = opp
        if parts:
            j["partitions"] = parts
        # Barrier window (job-local → rebased): from the first map finish
        # (reduce work EXISTS from here) to the last map finish (the
        # barrier opens). Map-only jobs have no reduce phase — no window.
        map_fin = [ev["t"] for ev in rep.get("events") or []
                   if isinstance(ev, dict)
                   and ev.get("ev") in ("finish", "late_finish")
                   and ev.get("phase") == "map"
                   and isinstance(ev.get("t"), (int, float))]
        has_reduce = "reduce" in (rep.get("totals") or {})
        if rep.get("sched") == "pipeline":
            # The scheduler dissolved the barrier (ISSUE 17): reduce
            # tasks were grantable per partition throughout the map
            # window, so idle inside it is plain idle, not a structural
            # bubble — no barrier_window, and the sched stamp rides the
            # job row so readers can tell why it's absent.
            j["sched"] = "pipeline"
        elif len(map_fin) > 1 and has_reduce:
            j["barrier_window"] = (round(base + min(map_fin), 6),
                                   round(base + max(map_fin), 6))

    # --- bubble windows on the shared axis ---
    bubble_windows: list = []
    for jid, j in jobs.items():
        sub = j.get("submit_t")
        if sub is not None and not j.get("cached"):
            start = j.get("start_t")
            t1 = start if start is not None else j.get("done_t", end)
            if t1 is not None and t1 > sub:
                bubble_windows.append((sub, t1))      # job sat queued
        bw = j.get("barrier_window")
        if bw:
            bubble_windows.append(bw)                 # map-barrier tail
        if sub is not None:
            q = (j.get("start_t") if j.get("start_t") is not None
                 else j.get("done_t", end)) or 0.0
            j["queue_wait_s"] = round(max(q - sub, 0.0), 6)
    bubble_windows = _merge(bubble_windows)

    # --- per-worker accounting ---
    by_wid: dict = {}
    for row in timeline:
        by_wid.setdefault(row["wid"], []).append(row)
    workers: dict = {}
    tot = {"busy_ws": 0.0, "idle_ws": 0.0, "dead_ws": 0.0,
           "bubble_ws": 0.0, "active_ws": 0.0}
    for wid, rows in sorted(by_wid.items(), key=lambda kv: str(kv[0])):
        first = min(r["t0"] for r in rows)
        busy = _merge([(r["t0"], r["t1"]) for r in rows
                       if r["state"] == "busy"])
        dead = _merge([(r["t0"], r["t1"]) for r in rows
                       if r["state"] == "dead"])
        dead = _subtract(dead, busy)  # overlap reads as busy: the worker
        # demonstrably worked there (speculation twins share (phase,tid))
        present = [(first, max(end, first))]
        idle = _subtract(_subtract(present, busy), dead)
        bubble = _intersect(idle, bubble_windows)
        busy_s, dead_s = _total(busy), _total(dead)
        idle_s, bubble_s = _total(idle), _total(bubble)
        active_s = _total(present) - dead_s  # crash windows leave the
        # denominator: a dead worker can't be "wasted idle"
        workers[str(wid)] = {
            "present_s": round(_total(present), 3),
            "busy_s": round(busy_s, 3),
            "idle_s": round(idle_s, 3),
            "dead_s": round(dead_s, 3),
            "bubble_s": round(bubble_s, 3),
            "util_frac": round(busy_s / active_s, 4) if active_s > 0
            else 0.0,
        }
        tot["busy_ws"] += busy_s
        tot["idle_ws"] += idle_s
        tot["dead_ws"] += dead_s
        tot["bubble_ws"] += bubble_s
        tot["active_ws"] += max(active_s, 0.0)

    active = tot["active_ws"]
    opp_total = sum(j.get("pipelining_opportunity_s", 0.0)
                    for j in jobs.values())
    fleet = {
        "workers": len(workers),
        "jobs": len(jobs),
        **{k: round(v, 3) for k, v in tot.items()},
        "util_frac": round(tot["busy_ws"] / active, 4) if active > 0
        else 0.0,
        "idle_frac": round(tot["idle_ws"] / active, 4) if active > 0
        else 0.0,
        "bubble_frac": round(tot["bubble_ws"] / active, 4) if active > 0
        else 0.0,
        "pipelining_opportunity_s": round(opp_total, 6),
    }
    out = {
        "kind": "fleet_report",
        "mode": "service" if service_mode else "job",
        "target": os.path.abspath(target),
        "window_s": round(end, 3),
        "fleet": fleet,
        "workers": workers,
        "jobs": {str(k): v for k, v in sorted(jobs.items(),
                                              key=lambda kv: str(kv[0]))},
        "bubble_windows": [(round(a, 3), round(b, 3))
                           for a, b in bubble_windows],
        "timeline": sorted(timeline,
                           key=lambda r: (str(r["wid"]), r["t0"])),
    }
    if errors:
        out["errors"] = errors
    return out


def fleet_history_row(report: dict) -> dict:
    """The three trend-watched series the bench history records — one
    place, so bench.py and any future caller agree on the names doctor
    trend follows."""
    f = report.get("fleet") or {}
    return {
        "fleet_bubble_frac": f.get("bubble_frac", 0.0),
        "fleet_util_frac": f.get("util_frac", 0.0),
        "pipelining_opportunity_s": f.get("pipelining_opportunity_s", 0.0),
    }


# ---------------------------------------------------------------------------
# Rendering + CLI
# ---------------------------------------------------------------------------

def format_fleet_report(report: dict, verbose: bool = False) -> str:
    f = report["fleet"]
    lines = [
        f"fleet: {f['workers']} worker(s) · {f['jobs']} job(s) · window "
        f"{report['window_s']:.1f}s [{report['mode']}]",
        f"  util {f['util_frac']:.1%} · idle {f['idle_frac']:.1%} · "
        f"bubble {f['bubble_frac']:.1%} ({f['bubble_ws']:.1f} "
        f"worker-s) · dead {f['dead_ws']:.1f} worker-s",
        f"  pipelining opportunity: {f['pipelining_opportunity_s']:.2f}s "
        "(Σ reduce-start − partition-readiness)",
    ]
    if report["workers"]:
        lines.append("  WID   BUSY      IDLE      BUBBLE    DEAD      UTIL")
        for wid, w in report["workers"].items():
            lines.append(
                f"  w{wid:<4} {w['busy_s']:<9.2f} {w['idle_s']:<9.2f} "
                f"{w['bubble_s']:<9.2f} {w['dead_s']:<9.2f} "
                f"{w['util_frac']:.1%}"
            )
    for jid, j in report["jobs"].items():
        bits = [f"  job {jid}: {j.get('state', '?')}"]
        if j.get("app"):
            bits.append(j["app"])
        if "queue_wait_s" in j:
            bits.append(f"wait {j['queue_wait_s']:.2f}s")
        if j.get("pipelining_opportunity_s"):
            bits.append(f"pipelining {j['pipelining_opportunity_s']:.2f}s")
        if j.get("cached"):
            bits.append("cached")
        if j.get("partial"):
            bits.append("PARTIAL (no report artifact)")
        lines.append(" · ".join(bits))
    dead_rows = [r for r in report["timeline"] if r["state"] == "dead"]
    if dead_rows:
        lines.append(f"  {len(dead_rows)} dead interval(s) — lease-expired"
                     " / crashed attempts, excluded from idle:")
        for r in dead_rows:
            lines.append(
                f"    w{r['wid']} {r['t0']:.2f}–{r['t1']:.2f}s "
                f"{(r['job'] + ':') if r['job'] else ''}"
                f"{r['phase']}:{r['tid']}"
            )
    if verbose:
        lines.append("  timeline:")
        for r in report["timeline"]:
            lines.append(
                f"    w{r['wid']} {r['t0']:8.3f}–{r['t1']:8.3f}  "
                f"{r['state']:<5} "
                f"{(r['job'] + ':') if r['job'] else ''}"
                f"{r['phase']}:{r['tid']}"
            )
    for e in report.get("errors") or []:
        lines.append(f"  warning: {e}")
    return "\n".join(lines)


def compare_baseline(report: dict, baseline: dict) -> dict:
    """Regression check against a prior fleet report: bubble_frac is the
    watched series (bad = up), with the doctor-trend style guard band —
    2 points absolute plus 10% relative."""
    cur = report["fleet"].get("bubble_frac", 0.0)
    base = (baseline.get("fleet") or {}).get("bubble_frac", 0.0)
    regressed = cur > base + 0.02 + 0.10 * abs(base)
    return {
        "bubble_frac": cur,
        "baseline_bubble_frac": base,
        "delta": round(cur - base, 4),
        "regressed": regressed,
    }


def run_cli(args) -> int:
    target = args.target
    if not os.path.isdir(target):
        print(f"fleet: {target!r} is not a directory")
        return 2
    report = build_fleet_report(target)
    rc = 0
    if getattr(args, "baseline", None):
        errors: list = []
        base = _load_job_report(args.baseline, errors) \
            if os.path.isfile(args.baseline) else None
        # _load_job_report unwraps {"report": ...}; a fleet report has no
        # such envelope, so it comes back verbatim.
        if base is None or base.get("kind") != "fleet_report":
            print(f"fleet: baseline {args.baseline!r} is not a fleet "
                  "report")
            return 2
        report["baseline"] = compare_baseline(report, base)
        if report["baseline"]["regressed"]:
            rc = 1
    if getattr(args, "format", "text") == "json":
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_fleet_report(report,
                                  verbose=getattr(args, "verbose", False)))
        if "baseline" in report:
            b = report["baseline"]
            print(f"  baseline: bubble {b['baseline_bubble_frac']:.1%} → "
                  f"{b['bubble_frac']:.1%} "
                  f"({'REGRESSED' if b['regressed'] else 'ok'})")
    return rc
