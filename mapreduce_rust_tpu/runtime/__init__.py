"""Runtime tier: host-side ingest, streaming driver, dictionary, metrics."""

from mapreduce_rust_tpu.runtime.chunker import Chunk, chunk_document, chunk_stream, iter_chunks, list_inputs  # noqa: F401
from mapreduce_rust_tpu.runtime.dictionary import Dictionary, extract_words  # noqa: F401
from mapreduce_rust_tpu.runtime.driver import JobResult, merge_outputs, run_job  # noqa: F401
from mapreduce_rust_tpu.runtime.metrics import JobStats  # noqa: F401
