"""Runtime tier: host-side ingest, streaming driver, dictionary, metrics,
trace/telemetry.

Re-exports are LAZY (PEP 562): importing a light submodule — say
``runtime.telemetry`` from the coordinator's control-plane process — must
not execute this package body eagerly pulling in ``runtime.driver`` and
with it jax + an XLA backend. ``from mapreduce_rust_tpu.runtime import
run_job`` still works; it just imports driver at attribute access time.
"""

_LAZY = {
    "Chunk": "chunker", "chunk_document": "chunker", "chunk_stream": "chunker",
    "iter_chunks": "chunker", "list_inputs": "chunker",
    "parse_input_spec": "chunker", "resolve_corpora": "chunker",
    "derive_splitters": "splitter", "prepare_app": "splitter",
    "splitters_for_job": "splitter",
    "Dictionary": "dictionary", "extract_words": "dictionary",
    "JobResult": "driver", "merge_outputs": "driver", "run_job": "driver",
    "JobStats": "metrics",
    "JobReport": "telemetry", "build_manifest": "telemetry",
    "diff_manifests": "telemetry", "load_manifest": "telemetry",
    "write_manifest": "telemetry",
    "Tracer": "trace", "trace_span": "trace", "validate_events": "trace",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    submodule = _LAZY.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(
        importlib.import_module(f"{__name__}.{submodule}"), name
    )
