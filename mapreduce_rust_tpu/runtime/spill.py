"""Binary async spill plane (ISSUE 11): native sorted-run format,
double-buffered background writers, and the batched k-way merge that
replaces the per-key text-line heap interleave at egress.

Three layers, one module:

- **Run format** — a spill run is a small header (magic, schema version,
  run token, key count) + a SORTED packed-uint64 key column + LEB128
  varint word lengths + the concatenated word bytes. Columnar on purpose:
  the k-way merge memory-maps the key column and never touches word
  bytes until a key actually matches the fold. Replaces the
  ``'k1 k2 word'`` text lines whose per-line ``%d``-format on write and
  ``split()``-parse on read were the spill-engaged Zipf leg's wall
  (``Dictionary._flush_words`` / ``iter_sorted``). Varint encode/decode
  are fully vectorized (numpy group arithmetic) — no per-word Python on
  either side.

- **AsyncSpillWriter** — one bounded background writer thread per
  spilling tier (each dictionary shard, the host accumulator), depth-2
  double buffering: the fold/consumer thread freezes a snapshot, enqueues
  it and keeps scanning while the writer sorts/packs/writes. Teardown
  reuses the PR 9 fold-plane pattern: a dead writer keeps DRAINING its
  queue so the bounded ``submit`` can never deadlock, the original error
  re-raises on the owner thread, and ``close(abort=True)`` forces the
  sentinel past a full queue. ``MR_SPILL_SYNC=1`` (or
  ``Config.spill_async=False``) runs every task inline at submit — the
  legacy synchronous plane, kept for debugging and for the chaos leg that
  measures what the async writer hides.

- **k-way merge** — ``merge_sources`` yields (keys, src, idx) BLOCKS
  globally sorted by packed key over any number of key-disjoint sorted
  sources (disk runs, RAM tiers, all shards at once): a native loser-tree
  kernel (``loader.cpp mr_merge_runs``, O(block) memory over the
  memory-mapped key columns) with a vectorized argsort fallback. The
  egress merge-join and ``Dictionary.iter_sorted`` are both built on it.

The array-redistribution framing (arXiv:2112.01075, PAPERS.md) applies to
disk exactly as to ICI: O(chunk) double buffers, transfer overlapped with
compute. No jax import here — spill runs are a host-side artifact and the
scavenger must be callable from any process.
"""

from __future__ import annotations

import os
import queue
import re
import threading
import time

import numpy as np

from mapreduce_rust_tpu.runtime.histogram import Histogram

#: Run-format identity: the header magic + schema version every reader
#: checks before trusting a byte, and the name history rows record so a
#: bench trajectory says which plane produced each number.
RUN_MAGIC = b"MRSP"
RUN_VERSION = 1
RUN_FORMAT = f"binary-v{RUN_VERSION}"
_HEADER_BYTES = 40

#: Merge block size: large enough that the per-block Python overhead
#: (searchsorted + mask) amortizes, small enough that a block's scratch
#: stays cache-resident. The merge is O(block) memory regardless of the
#: total key count.
DEFAULT_BLOCK = 1 << 16

_TRUTHY = ("1", "true", "on", "yes")


def sync_spill_forced() -> bool:
    """``MR_SPILL_SYNC`` — process-tree opt-out of the async writer (the
    MR_SANITIZE enablement pattern): the bench's slow-disk chaos pair runs
    the same job sync-vs-async to measure exactly what the overlap hides."""
    return os.environ.get("MR_SPILL_SYNC", "").strip().lower() in _TRUTHY


# ---------------------------------------------------------------------------
# Vectorized LEB128 varints (word lengths; save-container collision records)
# ---------------------------------------------------------------------------

def encode_varints(values) -> bytes:
    """LEB128-encode an array of unsigned ints — vectorized over GROUPS
    (≤10 rounds for uint64), never over values: word lengths are almost
    always single-byte, so round 1 handles the whole array at once."""
    v = np.ascontiguousarray(np.asarray(values, dtype=np.uint64))
    n = len(v)
    if n == 0:
        return b""
    ngroups = np.ones(n, dtype=np.int64)
    x = v >> np.uint64(7)
    while x.any():
        ngroups += x > 0
        x >>= np.uint64(7)
    ends = np.cumsum(ngroups)
    starts = ends - ngroups
    out = np.zeros(int(ends[-1]), dtype=np.uint8)
    rem = v.copy()
    active = np.arange(n)
    g = 0
    while len(active):
        byte = (rem[active] & np.uint64(0x7F)).astype(np.uint8)
        more = ngroups[active] > (g + 1)
        out[starts[active] + g] = byte | (more.astype(np.uint8) << 7)
        rem[active] >>= np.uint64(7)
        active = active[more]
        g += 1
    return out.tobytes()


def decode_varints(buf, count: int) -> np.ndarray:
    """Decode exactly ``count`` LEB128 varints from ``buf`` — vectorized:
    terminator bytes (MSB clear) delimit groups, per-byte shifts come from
    the group starts, and ``np.add.reduceat`` folds the 7-bit limbs (the
    limbs are bit-disjoint, so add == or). Raises ValueError on a
    truncated or miscounted section — a torn run must fail loudly."""
    data = np.frombuffer(buf, dtype=np.uint8) if not isinstance(
        buf, np.ndarray) else buf.astype(np.uint8, copy=False)
    if count == 0:
        if len(data):
            raise ValueError("varint section: trailing bytes after 0 values")
        return np.zeros(0, dtype=np.uint64)
    term = (data & 0x80) == 0
    term_pos = np.nonzero(term)[0]
    if len(term_pos) != count or (len(data) and not term[-1]):
        raise ValueError(
            f"varint section: {len(term_pos)} terminators for {count} values"
        )
    group_start = np.empty(count, dtype=np.int64)
    group_start[0] = 0
    group_start[1:] = term_pos[:-1] + 1
    gid = np.zeros(len(data), dtype=np.int64)
    gid[1:] = np.cumsum(term[:-1])
    shift = ((np.arange(len(data)) - group_start[gid]) * 7).astype(np.uint64)
    contrib = (data.astype(np.uint64) & np.uint64(0x7F)) << shift
    return np.add.reduceat(contrib, group_start)


# ---------------------------------------------------------------------------
# Run files
# ---------------------------------------------------------------------------

class RunSource:
    """One sorted key-disjoint merge source: a memory-mapped disk run or a
    packed RAM tier. ``keys`` is the sorted packed-uint64 column, ``ends``
    the exclusive word-byte end offsets, ``data`` the concatenated word
    bytes (bytes for RAM tiers, a memmap slice for disk runs — sliced
    lazily, only for keys the join actually matches)."""

    __slots__ = ("keys", "ends", "data", "path", "collisions")

    def __init__(self, keys, ends, data, path=None, collisions=()):
        self.keys = keys
        self.ends = ends
        self.data = data
        self.path = path
        self.collisions = list(collisions)

    def __len__(self) -> int:
        return len(self.keys)

    def word(self, i: int) -> bytes:
        s = int(self.ends[i - 1]) if i else 0
        w = self.data[s:int(self.ends[i])]
        return w if isinstance(w, bytes) else bytes(w)


def pack_word_map(word_of: dict) -> tuple:
    """(sorted packed keys uint64[n], ends int64[n], word bytes) of a
    ``{(k1, k2): word}`` map — the vectorized ``np.argsort`` that replaces
    the Python ``sorted()`` over dict items in the flush path. Shared by
    the run writer and the RAM-tier merge source, so the on-disk order and
    the in-RAM order can never disagree."""
    n = len(word_of)
    if n == 0:
        return (np.empty(0, dtype=np.uint64), np.empty(0, dtype=np.int64), b"")
    packed = np.fromiter(
        ((k1 << 32) | k2 for (k1, k2) in word_of.keys()),
        dtype=np.uint64, count=n,
    )
    lens = np.fromiter((len(w) for w in word_of.values()),
                       dtype=np.int64, count=n)
    order = np.argsort(packed, kind="stable")
    words = list(word_of.values())
    buf = b"".join(words[i] for i in order.tolist())
    return packed[order], np.cumsum(lens[order]), buf


def _pack_header(token: str, n: int, lens_bytes: int, n_collisions: int) -> bytes:
    head = np.zeros(_HEADER_BYTES, dtype=np.uint8)
    head[0:4] = np.frombuffer(RUN_MAGIC, dtype=np.uint8)
    head[4:6] = np.frombuffer(
        np.uint16(RUN_VERSION).tobytes(), dtype=np.uint8)
    tok = token.encode()[:8].ljust(8, b"\0")
    head[8:16] = np.frombuffer(tok, dtype=np.uint8)
    head[16:40] = np.frombuffer(
        np.asarray([n, lens_bytes, n_collisions], dtype="<u8").tobytes(),
        dtype=np.uint8,
    )
    return head.tobytes()


def pack_header_for_save(token: str, n: int, lens_bytes: int,
                         n_collisions: int) -> bytes:
    """The container header for streaming writers (Dictionary.save pipes
    the sections itself so word bytes never materialize whole)."""
    return _pack_header(token, n, lens_bytes, n_collisions)


def write_run_container(f, token: str, keys, ends, buf: bytes,
                        collisions=()) -> int:
    """Write one run/save container to an open binary file; returns bytes
    written. ``keys`` must already be sorted ascending (pack_word_map's
    contract); collision records ride only in save containers — spill runs
    keep theirs in RAM (the flush never clears ``Dictionary.collisions``)."""
    keys = np.ascontiguousarray(keys, dtype="<u8")
    n = len(keys)
    lens = np.diff(np.asarray(ends, dtype=np.int64), prepend=np.int64(0))
    lens_b = encode_varints(lens)
    coll_parts = []
    for kept, rejected in collisions:
        coll_parts.append(encode_varints(np.asarray([len(kept)])))
        coll_parts.append(kept)
        coll_parts.append(encode_varints(np.asarray([len(rejected)])))
        coll_parts.append(rejected)
    written = 0
    for part in (_pack_header(token, n, len(lens_b), len(collisions)),
                 keys.tobytes(), lens_b, buf, *coll_parts):
        f.write(part)
        written += len(part)
    return written


def write_run_file(path: str, token: str, keys, ends, buf: bytes,
                   run_index: int = 0, collisions=()) -> int:
    """Atomic (tmp + rename) run write — the writer-thread task body.
    Returns bytes written. The seeded ``slow_disk`` chaos site fires here:
    one injection point covers every spill tier."""
    _chaos_slow_disk(run_index)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        written = write_run_container(f, token, keys, ends, buf, collisions)
    os.replace(tmp, path)
    return written


def write_npy_run(path: str, rows: np.ndarray, run_index: int = 0) -> int:
    """Atomic accumulator-run write (sorted deduped [n,3] rows, .npy) —
    the accumulator writer's task body, behind the same chaos site."""
    _chaos_slow_disk(run_index)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.save(f, rows)
    size = os.path.getsize(tmp)
    os.replace(tmp, path)
    return size


def read_run_header(mm) -> tuple:
    """(n, lens_bytes, n_collisions) after validating magic + version.
    An unknown version is a LOUD exit path, never a silent misparse —
    the schema field exists so a future format can migrate instead."""
    if len(mm) < _HEADER_BYTES:
        raise ValueError("spill run: truncated header")
    if bytes(mm[0:4]) != RUN_MAGIC:
        raise ValueError("spill run: bad magic (not a binary spill run)")
    version = int(np.frombuffer(mm, dtype="<u2", count=1, offset=4)[0])
    if version != RUN_VERSION:
        raise ValueError(
            f"spill run: unsupported schema version {version} "
            f"(this build reads v{RUN_VERSION})"
        )
    n, lens_bytes, n_coll = np.frombuffer(
        mm, dtype="<u8", count=3, offset=16).tolist()
    return int(n), int(lens_bytes), int(n_coll)


def read_run_file(path: str) -> RunSource:
    """Memory-map one binary run: the key column and word bytes stay on
    disk (the OS pages them); only the varint lengths decode eagerly into
    the offsets the merge needs."""
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    n, lens_bytes, n_coll = read_run_header(mm)
    keys_off = _HEADER_BYTES
    lens_off = keys_off + 8 * n
    words_off = lens_off + lens_bytes
    keys = np.frombuffer(mm, dtype="<u8", count=n, offset=keys_off)
    lens = decode_varints(
        np.frombuffer(mm, dtype=np.uint8, count=lens_bytes, offset=lens_off),
        n,
    )
    ends = np.cumsum(lens.astype(np.int64))
    total = int(ends[-1]) if n else 0
    data = mm[words_off:words_off + total]
    collisions = []
    if n_coll:
        pos = words_off + total
        raw = bytes(mm[pos:])
        o = 0
        for _ in range(n_coll):
            ln, o = _read_one_varint(raw, o)
            kept = raw[o:o + ln]
            o += ln
            ln, o = _read_one_varint(raw, o)
            rejected = raw[o:o + ln]
            o += ln
            collisions.append((kept, rejected))
    return RunSource(keys, ends, data, path=path, collisions=collisions)


def _read_one_varint(raw: bytes, o: int) -> tuple[int, int]:
    v = 0
    shift = 0
    while True:
        b = raw[o]
        o += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, o
        shift += 7


# ---------------------------------------------------------------------------
# k-way merge over key-disjoint sorted sources
# ---------------------------------------------------------------------------

def merge_sources(sources, block: int = DEFAULT_BLOCK):
    """Yield ``(keys uint64[b], src int32[b], idx int64[b])`` blocks,
    globally sorted by packed key, over key-disjoint sorted sources.
    ``src``/``idx`` index into the CALLER's sources list — empty sources
    keep their slot so the indices never shift. Native loser tree when the
    toolchain is present (O(block) memory over memory-mapped key columns);
    vectorized argsort fallback otherwise (O(total keys) scratch — the
    same order of memory the dictionary's membership arrays already hold)."""
    key_arrays = [np.ascontiguousarray(s.keys, dtype=np.uint64)
                  for s in sources]
    total = sum(len(a) for a in key_arrays)
    if total == 0:
        return
    live = [i for i, a in enumerate(key_arrays) if len(a)]
    if len(live) == 1:
        i = live[0]
        a = key_arrays[i]
        for start in range(0, len(a), block):
            end = min(start + block, len(a))
            yield (a[start:end].copy(),
                   np.full(end - start, i, dtype=np.int32),
                   np.arange(start, end, dtype=np.int64))
        return
    from mapreduce_rust_tpu.native.host import merge_runs_stream

    native = merge_runs_stream(key_arrays, block)
    if native is not None:
        yield from native
        return
    # Fallback: one vectorized argsort over the concatenated columns.
    all_keys = np.concatenate(key_arrays)
    src = np.concatenate([
        np.full(len(a), i, dtype=np.int32) for i, a in enumerate(key_arrays)
    ])
    idx = np.concatenate([
        np.arange(len(a), dtype=np.int64) for a in key_arrays
    ])
    order = np.argsort(all_keys, kind="stable")
    for start in range(0, total, block):
        sel = order[start:start + block]
        yield all_keys[sel], src[sel], idx[sel]


def slice_block_words(sources, src, idx) -> list:
    """Word bytes for one merged block's (src, idx) rows, in row order —
    the batched slicer shared by the egress join and the streaming save:
    per source, the byte ranges come out of ONE vectorized offsets pass
    (and one contiguous bytes() copy for memory-mapped runs, legal
    because idx is ascending per source within a block) instead of a
    method call + numpy scalar indexing per word."""
    words: list = [None] * len(src)
    for s in np.unique(src).tolist():
        sel = np.nonzero(src == s)[0]
        source = sources[s]
        ii = idx[sel]
        ends_arr = source.ends
        starts = np.where(ii > 0, ends_arr[ii - 1], 0)
        ends_i = ends_arr[ii]
        data = source.data
        base = 0
        if not isinstance(data, bytes) and len(ii):
            base = int(starts[0])
            data = bytes(memoryview(data[base:int(ends_i[-1])]))
        for o, s0, e0 in zip(sel.tolist(), (starts - base).tolist(),
                             (ends_i - base).tolist()):
            words[o] = data[s0:e0]
    return words


def iter_sources_sorted(sources, block: int = DEFAULT_BLOCK):
    """(packed, k1, k2, word) tuples in ascending packed-key order — the
    legacy ``iter_sorted`` surface, generated from the block merge so the
    per-tuple and the batched consumers can never disagree on order."""
    for keys, src, idx in merge_sources(sources, block):
        for packed, s, i in zip(keys.tolist(), src.tolist(), idx.tolist()):
            yield (packed, packed >> 32, packed & 0xFFFFFFFF,
                   sources[s].word(i))


# ---------------------------------------------------------------------------
# Async writer
# ---------------------------------------------------------------------------

class SpillWriterError(RuntimeError):
    """Re-raise wrapper is NOT used — the original exception surfaces
    verbatim on the owner thread (fold-plane doctrine); this type exists
    only for the poisoned-without-error impossibility."""


class AsyncSpillWriter:
    """One background thread writing spill runs off the fold/consumer hot
    path, double-buffered: at most ``depth`` frozen snapshots in flight,
    so memory stays O(depth × budget) while the owner keeps scanning.

    Failure containment (the PR 9 fold-plane pattern): a task that raises
    records its error, flips the poison flag and the loop keeps DRAINING
    the queue — the owner's bounded ``submit`` can therefore never
    deadlock against a dead writer; the recorded error re-raises on the
    owner thread at the next ``submit``/``drain``. ``close(abort=True)``
    (exception-path teardown) forces the sentinel past a full queue by
    displacing entries and never blocks forever.

    ``sync=True`` (or ``MR_SPILL_SYNC=1``) executes every task inline at
    submit — the legacy synchronous plane, same accounting, no thread.
    """

    _SENTINEL = object()

    def __init__(self, name: str = "mr/spill", depth: int = 2,
                 sync: bool = False) -> None:
        self.sync = bool(sync) or sync_spill_forced()
        self.write_s = 0.0        # writer-thread seconds inside tasks
        self.stall_s = 0.0        # owner-thread seconds blocked on submit
        self.bytes_written = 0
        self.runs_written = 0
        self.hist = Histogram()   # per-run write_s distribution
        self.error: "BaseException | None" = None
        self._poisoned = threading.Event()
        self._closed = False
        if self.sync:
            self._q = None
            self._thread = None
            return
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    # ---- writer thread ----

    def _run_task(self, task) -> None:
        t0 = time.perf_counter()
        written = task()
        dt = time.perf_counter() - t0
        self.write_s += dt
        self.hist.add(dt)
        self.bytes_written += int(written or 0)
        self.runs_written += 1

    def _loop(self) -> None:
        q = self._q
        while True:
            task = q.get()
            try:
                if task is self._SENTINEL:
                    return
                if not self._poisoned.is_set():
                    try:
                        self._run_task(task)
                    except BaseException as e:
                        # Error recorded BEFORE task_done (the finally):
                        # drain()'s q.join() may wake at that task_done,
                        # and it must observe the error — a failed final
                        # run that drained "clean" would surface later as
                        # a FileNotFoundError instead of the real cause.
                        # Poisoned, the loop keeps consuming (discarding)
                        # until the sentinel, so the owner's bounded put
                        # can never deadlock against a dead writer.
                        self.error = e
                        self._poisoned.set()
            finally:
                q.task_done()

    # ---- owner side ----

    def _raise_error(self) -> None:
        if self.error is not None:
            raise self.error
        raise SpillWriterError("spill writer poisoned without an error")

    def submit(self, task) -> None:
        """Hand one frozen snapshot task (callable → bytes written) to the
        writer. Blocked = spill backpressure, timed into ``stall_s`` — the
        wall-clock 'the disk is the ceiling' signal, exactly as
        fold_stall_s is for the fold."""
        if self._poisoned.is_set():
            self._raise_error()
        if self.sync:
            if self._closed:
                raise RuntimeError("spill writer already closed")
            try:
                self._run_task(task)
            except BaseException as e:
                self.error = e
                self._poisoned.set()
                raise
            return
        try:
            self._q.put_nowait(task)
            return
        except queue.Full:
            pass
        t0 = time.perf_counter()
        try:
            while True:
                if self._poisoned.is_set():
                    self._raise_error()
                try:
                    self._q.put(task, timeout=0.05)
                    return
                except queue.Full:
                    continue
        finally:
            self.stall_s += time.perf_counter() - t0

    def drain(self) -> None:
        """Block until every submitted run is on disk; re-raise a recorded
        writer error. The barrier before any read of the runs (egress
        merge, iter_sorted, fold_arrays) and before final accounting."""
        if not self.sync and not self._closed:
            # task_done fires for every entry — poisoned loops included —
            # so join() cannot deadlock.
            self._q.join()
        if self.error is not None:
            raise self.error

    def stats_dict(self, runs: int) -> dict:
        """Final accounting shape shared by every spilling tier (collect
        AFTER drain/close — the counters are writer-thread cells)."""
        return {"write_s": self.write_s, "stall_s": self.stall_s,
                "bytes": self.bytes_written, "runs": runs,
                "hist": self.hist}

    def snapshot(self) -> tuple:
        """(write_s, stall_s, bytes) right now — benign-stale reads for
        the live metrics ring (exact finals come from stats_dict)."""
        return (self.write_s, self.stall_s, self.bytes_written)

    def close(self, abort: bool = False) -> None:
        """Stop the writer thread. ``abort=True`` poisons first (pending
        snapshots are discarded — the caller is deleting the run files
        anyway) and forces the sentinel past a full queue. Idempotent,
        never raises, never blocks forever."""
        if self.sync or self._closed:
            self._closed = True
            return
        self._closed = True
        if abort:
            self._poisoned.set()
            while True:
                try:
                    self._q.put_nowait(self._SENTINEL)
                    break
                except queue.Full:
                    try:
                        self._q.get_nowait()
                        self._q.task_done()
                    except queue.Empty:
                        pass
        else:
            self._q.put(self._SENTINEL)
        self._thread.join(timeout=30)


def ensure_writer(current: "AsyncSpillWriter | None", name: str,
                  sync: bool) -> AsyncSpillWriter:
    """Lazy writer slot shared by every spilling tier: create on first
    flush; replace a CLOSED writer (remove_runs already ran — a fresh
    spill after job-end cleanup, test-only in practice, must not enqueue
    into a thread that already exited)."""
    if current is None or current._closed:
        return AsyncSpillWriter(name=name, sync=sync)
    return current


def tier_spill_stats(writer: "AsyncSpillWriter | None", runs: int) -> dict:
    """stats_dict with the never-spilled zeros — one shape for both
    tiers, so _collect_spill_stats can't drift between them."""
    if writer is None:
        return {"write_s": 0.0, "stall_s": 0.0, "bytes": 0, "runs": runs,
                "hist": None}
    return writer.stats_dict(runs)


def tier_spill_snapshot(writer: "AsyncSpillWriter | None"):
    return None if writer is None else writer.snapshot()


# ---------------------------------------------------------------------------
# Chaos: seeded per-spill write delay (slow_disk)
# ---------------------------------------------------------------------------

_chaos_cache: dict = {}


def _chaos_slow_disk(run_index: int) -> None:
    """The ``slow_disk`` injection checkpoint: one site covers every spill
    tier (dictionary runs, accumulator runs, shard or not). Seeded p=
    sampling keys on the run index, so reruns delay the same runs. Cached
    per spec string — tests flip MR_CHAOS between jobs."""
    spec = os.environ.get("MR_CHAOS")
    if not spec:
        return
    plan = _chaos_cache.get(spec)
    if plan is None:
        try:
            from mapreduce_rust_tpu.analysis.chaos import ChaosPlan

            plan = ChaosPlan.parse(spec)
        except Exception:
            plan = False  # a bad ambient spec must not fail spill writes
        _chaos_cache[spec] = plan
    if not plan:
        return
    f = plan.pick("slow_disk", tid=run_index)
    if f is not None and f.seconds > 0:
        time.sleep(f.seconds)


def chaos_fired(spec: str) -> list:
    """Fired slow_disk events for ``spec`` (test/bench introspection)."""
    plan = _chaos_cache.get(spec)
    return plan.fired() if plan else []


# ---------------------------------------------------------------------------
# Crash-safe run scavenging
# ---------------------------------------------------------------------------

#: accrun-*/dictrun-* naming policy: kind, host tag (``h`` + 8-hex hash
#: of the hostname — pid liveness is only checkable on the writer's own
#: machine, and shared-filesystem work dirs are a supported deployment),
#: the writer's pid, the per-instance token (dictionary.new_run_token),
#: the run index, the tier's extension — plus the atomic-write .tmp
#: suffix a SIGKILL can strand. The host tag group is optional so
#: pre-tag leftovers still parse (they scavenge under the legacy
#: same-host assumption).
_RUN_NAME_RE = re.compile(
    r"^(dictrun|accrun)-(?:h([0-9a-f]{8})-)?(\d+)-([0-9a-f]{8})-\d+"
    r"\.(bin|txt|npy)(\.tmp)?$"
)

_host_tag_cache: "str | None" = None


def host_tag() -> str:
    """``h`` + 8-hex hash of this machine's hostname — the run-name
    fragment that scopes scavenging to files THIS host's pids wrote."""
    global _host_tag_cache
    if _host_tag_cache is None:
        import hashlib
        import socket

        _host_tag_cache = "h" + hashlib.sha256(
            socket.gethostname().encode()
        ).hexdigest()[:8]
    return _host_tag_cache


def run_file_name(kind: str, token: str, run_index: int, ext: str) -> str:
    """THE spill-run naming policy, one definition for both tiers and the
    scavenger's parser."""
    return f"{kind}-{host_tag()}-{os.getpid()}-{token}-{run_index}.{ext}"

#: Files younger than this are never scavenged even when their writer pid
#: is gone — belt and braces against pid-recycling races around process
#: startup. A leaked run is reclaimed on the NEXT job in the work dir,
#: which is exactly when the space matters.
SCAVENGE_MIN_AGE_S = 60.0


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except OSError:
        return True  # exists but not ours (EPERM) / unknown: keep the file


def scavenge_stale_runs(spill_dir: str, live_tokens=(),
                        min_age_s: float = SCAVENGE_MIN_AGE_S,
                        logger=None) -> list[str]:
    """Delete orphaned spill runs a SIGKILLed job left behind (ISSUE 11
    satellite: ``remove_run_files`` only runs at clean job end, so a
    killed run leaked ``dictrun-*``/``accrun-*`` forever). Guarded four
    ways so a CONCURRENT job's live runs are never touched: the file must
    match the run naming policy exactly, its host tag must be THIS
    machine's (pid liveness means nothing for a peer host on a shared
    filesystem — foreign-host files are never touched), its embedded
    token must not be one of ours (``live_tokens``), and its writer pid
    must be gone — a pid that still answers ``kill(pid, 0)`` may be a
    live job sharing the work dir, so its files stay. Age is the
    pid-recycling backstop. Best-effort by contract: returns the removed
    names, never raises."""
    removed: list[str] = []
    try:
        names = os.listdir(spill_dir)
    except OSError:
        return removed
    now = time.time()
    own = os.getpid()
    tokens = set(live_tokens)
    tag = host_tag()[1:]
    for name in names:
        m = _RUN_NAME_RE.match(name)
        if m is None:
            continue
        host, pid, token = m.group(2), int(m.group(3)), m.group(4)
        if host is not None and host != tag:
            continue  # another host's file: its liveness is unknowable here
        if token in tokens or pid == own or _pid_alive(pid):
            continue
        path = os.path.join(spill_dir, name)
        try:
            if now - os.path.getmtime(path) < min_age_s:
                continue
            os.unlink(path)
            removed.append(name)
        except OSError:
            continue
    if removed and logger is not None:
        logger.info(
            "scavenged %d stale spill run(s) from %s (dead writers)",
            len(removed), spill_dir,
        )
    return removed
