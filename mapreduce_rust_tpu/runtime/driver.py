"""Chunked streaming driver — the end-to-end engine (single-chip and mesh).

This is the TPU-native replacement for the reference's whole worker
execution path (src/mr/worker.rs:65-193): instead of per-task files and
per-record writes, a single host loop streams whitespace-aligned chunks
(runtime/chunker.py) through a compiled per-chunk step and keeps running
distinct-key state on device:

    chunk bytes ──device_put──▶ tokenize_and_hash ─▶ app.device_map
        ─▶ count_unique (map-side combiner)  ─▶ merge into state
                                                   │
         evicted tail (rare) ◀─────────────────────┘
              └─▶ host spill accumulator (exact, nothing dropped)

With ``cfg.mesh_shape > 1`` the same loop feeds groups of D chunks to the
mesh pipeline (parallel/shuffle.py): per-chip combine → bucket scatter →
``lax.all_to_all`` over ICI → per-chip merge into a hash-class-sharded
state. That collective IS the reference's mr-{m}-{r}.txt file shuffle
(src/mr/worker.rs:117-140), lowered to the interconnect.

The loop is pipelined: JAX dispatch is async, so while the device works on
chunk k the host normalizes/chunks k+1 and feeds the egress dictionary
(runtime/dictionary.py). Overflow/spill counters come back via async
device→host copies issued at dispatch and read ``Config.pipeline_depth``
steps later, so the host never blocks a round trip per chunk — essential
when the chip sits behind a tunnel where one blocking scalar read costs
~80 ms against sub-ms step compute.

Capacity faults are handled, not asserted (VERDICT r1 weak 3):
- per-chunk distinct keys > partial_capacity → the chunk/group is
  *replayed* through a lazily-compiled wider tier (counted, exact);
- mesh bucket skew > bucket capacity → same replay, tier sized so bucket
  overflow is impossible (bucket_cap = whole update);
- merged distinct keys > merge_capacity → the evicted tail spills whole
  to the host accumulator (ops/groupby.merge_batches; counted, exact).

At egress the final table joins the hash→word dictionary and each app
formats its partitions (apps/base.py), written as mr-{r}.txt like the
reference (src/mr/worker.rs:167,180-183) — including every partition's
last key, which the reference drops (worker.rs:169-184).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import os
import time
from typing import Sequence
from zipfile import BadZipFile

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_rust_tpu.apps.base import App
from mapreduce_rust_tpu.apps.word_count import WordCount
from mapreduce_rust_tpu.config import (
    Config,
    lineage_forced,
    profile_forced,
    sync_dispatch_forced,
)
from mapreduce_rust_tpu.core.kv import KVBatch
from mapreduce_rust_tpu.ops.groupby import (
    clamp_batch,
    compact_front,
    compaction_cap,
    count_unique,
    merge_batches,
)
from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash
from mapreduce_rust_tpu.runtime.chunker import chunk_stream, resolve_corpora
from mapreduce_rust_tpu.runtime.dictionary import (
    Dictionary,
    ShardedDictionary,
    new_run_token,
    remove_run_files,
)
from mapreduce_rust_tpu.runtime.histogram import Histogram
from mapreduce_rust_tpu.runtime.metrics import (
    JobStats,
    jobstats_collector,
    log,
    metrics_tick,
    start_metrics,
    stop_metrics,
)
from mapreduce_rust_tpu.runtime.trace import (
    active_tracer,
    maybe_snapshot,
    partial_path,
    start_tracing,
    stop_tracing,
    trace_counter,
    trace_span,
)

_cc_enabled = False


# ---------------------------------------------------------------------------
# XLA compile instrumentation (ISSUE 5 tentpole: the trace layer never saw
# device-side compiles — a cold run's dominant cost was invisible)
# ---------------------------------------------------------------------------

#: Every backend compile jax reported via its monitoring events since the
#: listener was installed: {"dur_s", "cache": "hit"|"miss"|"uncached"}.
#: run_job slices [n0:] around its own interval, so the log never needs
#: clearing (concurrent run_jobs in one process are already unsupported —
#: same contract as the tracer).
_COMPILE_LOG: list[dict] = []
_COMPILE_TRACK_TID = -2  # synthetic trace track: compile intervals are
# measured by jax's wall clock, not ours — on their own track they can
# never partially overlap this thread's call-structured spans
_compile_listener_installed = False
_compile_cache_state: list[str] = []  # hit/miss events awaiting their compile


def _install_compile_listener() -> None:
    """Idempotently hook jax.monitoring: one record (and one ``xla.compile``
    trace span, when tracing) per backend compile, with persistent-cache
    hit/miss status. Listener registration is append-only in jax, hence the
    once-per-process guard."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    _compile_listener_installed = True
    import jax.monitoring as monitoring

    def on_event(event: str, **_kw) -> None:
        # Cache events fire inside compile_or_get_cached, strictly before
        # the duration event that closes the same compile: a hit on the
        # read path, a miss when the fresh result is written back. A
        # compile with neither (cache disabled, or entry below the
        # min-compile-time/min-size write thresholds) is "uncached".
        if event.endswith("/compilation_cache/cache_hits"):
            _compile_cache_state.append("hit")
        elif event.endswith("/compilation_cache/cache_misses"):
            _compile_cache_state.append("miss")

    def on_duration(event: str, duration: float, **_kw) -> None:
        if event != "/jax/core/compile/backend_compile_duration":
            return
        cache = _compile_cache_state.pop() if _compile_cache_state else "uncached"
        _compile_cache_state.clear()  # never let a stale event cross compiles
        _COMPILE_LOG.append({"dur_s": duration, "cache": cache})
        tr = active_tracer()
        if tr is not None:
            t1 = time.perf_counter()
            tr.add_span(
                "xla.compile", t1 - duration, t1,
                {"cache": cache, "seconds": round(duration, 3)},
                tid=_COMPILE_TRACK_TID,
            )

    monitoring.register_event_listener(on_event)
    monitoring.register_event_duration_secs_listener(on_duration)


_MEM_SAMPLE_PERIOD_S = 0.5
_mem_last_sample = [0.0]


def _sample_device_memory(stats) -> None:
    """Device-memory gauge, fed from the existing drain/consume loops
    (never per record): Chrome "C" counter samples per local device when
    tracing, plus a manifest high-water mark. Backends without
    ``memory_stats`` (CPU) simply contribute nothing. Throttled so a
    fast drain loop doesn't turn the gauge into the hot path."""
    now = time.monotonic()
    if now - _mem_last_sample[0] < _MEM_SAMPLE_PERIOD_S:
        return
    _mem_last_sample[0] = now
    try:
        from jax._src import xla_bridge

        if not xla_bridge._backends:
            # No backend initialized in this process: local_devices()
            # would CREATE one — a ~minutes metadata probe against an
            # absent accelerator (the PR 6 worker wedge). The gauge is
            # guarded at the source now, not just at one caller, so every
            # present and future call site inherits the safety
            # (mrlint: backend-init-in-probe).
            return
        for i, dev in enumerate(jax.local_devices()):
            ms = dev.memory_stats()
            if not ms:
                continue
            in_use = ms.get("bytes_in_use")
            if in_use is None:
                continue
            trace_counter(f"device.mem.d{i}", bytes_in_use=int(in_use))
            if in_use > stats.device_mem_high_bytes:
                stats.device_mem_high_bytes = int(in_use)
    except Exception:  # a telemetry probe must never fail the run
        pass


def enable_compilation_cache(path: str | None = "auto") -> None:
    """Point XLA's persistent compilation cache at a shared directory.

    Idempotent (first caller wins). The step-fn compiles below are tens of
    seconds each on TPU; with this cache a *process* pays them at most once
    ever per (shape, backend) instead of once per run — the difference
    between a bench that times out and one that measures steady state.
    "auto" resolves to <repo>/.jax_cache next to the package.
    """
    global _cc_enabled
    if _cc_enabled or not path:
        return
    if path == "auto":
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
        )
        # Scope by host fingerprint — "auto" only: XLA's CPU cache key does
        # NOT cover the host's instruction-set features, so an entry
        # AOT-compiled on another machine image loads with a "could lead to
        # SIGILL" warning and may do exactly that; the per-(jax, arch,
        # cpu-flags) subdir turns cross-machine reuse into a clean cold
        # compile. An EXPLICIT caller path is used verbatim — a caller
        # pointing at a prepared/shared cache dir must actually hit it.
        path = os.path.join(path, _host_fingerprint())
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    _cc_enabled = True


def _host_fingerprint() -> str:
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as f:
            # x86 spells it "flags", aarch64 "Features" — either carries the
            # ISA extensions whose mismatch makes a foreign AOT result crash.
            flags = next(
                (l for l in f if l.startswith(("flags", "Features"))), ""
            )
    except OSError:
        flags = ""
    # JAX_PLATFORMS joins the key: a pure-CPU process and an
    # accelerator-plugin process on the SAME machine compile CPU entries
    # with different XLA target pseudo-features (prefer-no-scatter/gather),
    # and loading across that line warns "could lead to SIGILL".
    h = hashlib.sha256(
        f"{jax.__version__}:{platform.machine()}:{flags}:"
        f"{os.environ.get('JAX_PLATFORMS', '')}".encode()
    ).hexdigest()[:12]
    return f"{platform.machine()}-{h}"


def select_device(kind: str = "auto"):
    """cfg.device → a jax.Device. "auto" prefers the accelerator backend."""
    if kind == "auto":
        return jax.devices()[0]
    devs = jax.devices(kind)
    if not devs:
        raise RuntimeError(f"no {kind} devices available")
    return devs[0]


_STEP_FNS: dict = {}  # (app, u_cap, use_pallas) → (map_combine, merge)


def make_step_fns(app: App, u_cap: int, use_pallas: bool = False):
    """(map_combine, merge) jitted for one app + update capacity.

    map_combine: chunk bytes → compacted per-chunk partial + overflow count.
    merge: fold the partial into the running state, returning the evicted
    tail and its record count (donates the old state's buffers).
    use_pallas: target is a TPU — tokenize with the fused Mosaic kernel.

    Cached at module level: apps are frozen dataclasses, so the key is a
    value key and every run_job in a process shares one set of jitted
    closures — a second run hits jax.jit's in-process executable cache
    instead of recompiling (the round-3 bench killer: warm == cold because
    fresh closures were built per call).
    """
    key = (app, u_cap, use_pallas)
    fns = _STEP_FNS.get(key)
    if fns is None:
        fns = _STEP_FNS[key] = _build_step_fns(app, u_cap, use_pallas)
    return fns


def _build_step_fns(app: App, u_cap: int, use_pallas: bool = False):
    op = app.combine_op

    @jax.jit
    def map_combine(chunk: jnp.ndarray, doc_id: jnp.ndarray):
        kv = tokenize_and_hash(chunk, use_pallas=use_pallas)
        # Compact before sorting: count_unique pays for tokens, not bytes
        # (~6x fewer sort slots on text); ops/groupby.compaction_cap is the
        # shared sizing policy. NOTE: the overflow flag below therefore
        # covers BOTH distinct keys > u_cap AND raw tokens > cap_c — either
        # replays the chunk through the full-width tier.
        kv, c_ovf = compact_front(kv, compaction_cap(u_cap, chunk.shape[0]))
        kv = app.device_map(kv, doc_id)
        partial = count_unique(kv, op=op)
        update = partial.take_front(u_cap)
        ovf = jnp.sum(partial.valid[u_cap:].astype(jnp.int32)) + c_ovf
        # An overflowing chunk contributes NOTHING (update clamps to empty,
        # keys included — ops/groupby.clamp_batch keeps the merged state
        # sorted): the driver replays it full-width later. This makes the
        # merge safe to dispatch before the overflow flag ever reaches the
        # host, which is what lets the stream loop batch its readbacks (one
        # device→host round trip per pipeline window, not per chunk).
        update = clamp_batch(update, ovf == 0)
        return update, ovf

    @functools.partial(jax.jit, donate_argnums=(0,))
    def merge(state: KVBatch, update: KVBatch):
        # update is a count_unique output — already key-sorted, so the
        # rank-merge inserts it without any sort at all.
        new_state, evicted = merge_batches(state, update, op=op, update_sorted=True)
        ev_count = jnp.sum(evicted.valid.astype(jnp.int32))
        return new_state, evicted, ev_count

    return map_combine, merge


def _pack_key_cols(keys: np.ndarray) -> np.ndarray:
    """[n, 2] (k1, k2) int64 columns (uint32-ranged by construction: they
    are the device hash lanes) → one uint64 packed column. Packing turns
    every key fold below into a 1-D sort/unique — np.unique(axis=0)'s
    row-structured sort was the measured finalize wall of the spill-heavy
    Zipf leg (ISSUE 11: ~4x slower than the 1-D path at 5M rows), and
    packed order == (k1, k2) lexicographic order, so the fold's output
    ordering is bit-identical."""
    return (keys[:, 0].astype(np.uint64) << np.uint64(32)) | keys[:, 1].astype(
        np.uint64
    )


def _unpack_rows(packed: np.ndarray, vals: np.ndarray) -> np.ndarray:
    return np.column_stack([
        (packed >> np.uint64(32)).astype(np.int64),
        (packed & np.uint64(0xFFFFFFFF)).astype(np.int64),
        vals.astype(np.int64),
    ])


def _combine_rows(op: str, keys: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """The shared fold kernel: (keys [n,2], vals [n]) → sorted deduped
    rows [m, 3], value-keyed for "distinct", else folded per key. All key
    work happens on the packed 1-D column (see _pack_key_cols)."""
    packed = _pack_key_cols(keys)
    if op == "distinct":
        # Sort by (key, value) then mask repeats — same output order as
        # np.unique over (k1, k2, value) rows, minus the structured sort.
        order = np.lexsort((vals, packed))
        p_s, v_s = packed[order], vals[order]
        if len(p_s):
            keep = np.empty(len(p_s), dtype=bool)
            keep[0] = True
            keep[1:] = (p_s[1:] != p_s[:-1]) | (v_s[1:] != v_s[:-1])
            p_s, v_s = p_s[keep], v_s[keep]
        return _unpack_rows(p_s, v_s)
    uniq, inv = np.unique(packed, return_inverse=True)
    inv = inv.reshape(-1)
    if op == "sum":
        folded = np.zeros(len(uniq), dtype=np.int64)
        np.add.at(folded, inv, vals)
    elif op == "max":
        folded = np.full(len(uniq), np.iinfo(np.int64).min)
        np.maximum.at(folded, inv, vals)
    else:
        folded = np.full(len(uniq), np.iinfo(np.int64).max)
        np.minimum.at(folded, inv, vals)
    return _unpack_rows(uniq, folded)


def _combine_pending(op: str, keys_list, vals_list) -> np.ndarray:
    """Combine pending (keys, vals) batches into sorted deduped rows
    [n, 3] (k1, k2, value) — value-keyed for "distinct", else folded.
    Module-level and pure so the async spill writer can run it against a
    frozen snapshot off the consumer thread (ISSUE 11)."""
    return _combine_rows(op, np.concatenate(keys_list),
                         np.concatenate(vals_list))


class HostAccumulator:
    """Exact host-side fold of device spills + the final state, per op.

    Adds are O(1) array appends; the fold is deferred and vectorized
    (np.unique over the concatenated batches + ufunc.at), so a spill-heavy
    run costs one sort at egress instead of per-record Python per spill.
    The per-key Python dict is built exactly once, when .table is read.

    Bounded-memory tier (VERDICT r4 missing 3): with ``budget_bytes`` set,
    pending arrays above the budget are combined into a SORTED, deduped run
    on disk (``spill_dir/accrun-*.npy``) and dropped from RAM, so a
    spill-heavy high-cardinality job holds O(budget + distinct) bytes
    instead of every spilled record — the tier the reference lacks (one
    ``Vec`` per partition holds the whole partition,
    /root/reference/src/mr/worker.rs:82-108). The combine+write of each
    run happens on a background :class:`AsyncSpillWriter` against frozen
    pending arrays (ISSUE 11), so the consumer keeps draining the device
    while the disk works; ``fold_arrays()`` drains the writer and merges
    the runs back exactly at finalize; ``.table`` (the Python-dict view)
    stays for the in-RAM paths, while the streaming egress reads the
    arrays.
    """

    def __init__(self, op: str, budget_bytes: int | None = None,
                 spill_dir: str | None = None,
                 async_spill: bool = True) -> None:
        if budget_bytes is not None and not spill_dir:
            raise ValueError("budget_bytes needs a spill_dir")
        self.op = op
        self.budget_bytes = budget_bytes
        self.spill_dir = spill_dir
        self.async_spill = async_spill
        self._keys: list[np.ndarray] = []   # each [N, 2] int64
        self._vals: list[np.ndarray] = []   # each [N] int64
        self._pending_bytes = 0
        self._runs: list[str] = []          # sorted, deduped [n,3] .npy files
        self._table: dict | None = None
        self._run_token = new_run_token()
        self._writer = None

    def add(self, keys: np.ndarray, vals: np.ndarray) -> None:
        keys = np.asarray(keys, dtype=np.int64).reshape(-1, 2)
        if len(keys):
            vals = np.asarray(vals, dtype=np.int64).reshape(-1)
            self._keys.append(keys)
            self._vals.append(vals)
            self._pending_bytes += keys.nbytes + vals.nbytes
            self._table = None  # late add after a read: refold lazily
            if self.budget_bytes is not None and self._pending_bytes > self.budget_bytes:
                self._flush_run()

    def add_batch(self, batch: KVBatch) -> None:
        keys, vals = batch.to_host()
        self.add(keys, vals)

    @property
    def has_runs(self) -> bool:
        return bool(self._runs)

    @property
    def run_count(self) -> int:
        return len(self._runs)

    def _pending_rows(self) -> np.ndarray:
        """Combine the in-RAM pending batches into sorted deduped rows
        [n, 3] (k1, k2, value) — value-keyed for "distinct", else folded."""
        return _combine_pending(self.op, self._keys, self._vals)

    def _clear_pending(self) -> None:
        self._keys.clear()
        self._vals.clear()
        self._pending_bytes = 0

    def _ensure_writer(self):
        from mapreduce_rust_tpu.runtime.spill import ensure_writer

        self._writer = ensure_writer(
            self._writer, f"mr/spill-acc-{self._run_token}",
            sync=not self.async_spill,
        )
        return self._writer

    def _flush_run(self) -> None:
        """Freeze the pending batches and hand the combine + write to the
        background writer (ISSUE 11): the np.unique fold AND the .npy
        write run off the consumer thread; this thread only swaps in
        fresh lists and enqueues."""
        from mapreduce_rust_tpu.runtime.spill import (
            run_file_name,
            write_npy_run,
        )

        keys, vals = self._keys, self._vals
        self._keys, self._vals = [], []
        self._pending_bytes = 0
        os.makedirs(self.spill_dir, exist_ok=True)
        run_index = len(self._runs)
        path = os.path.join(
            self.spill_dir,
            run_file_name("accrun", self._run_token, run_index, "npy"),
        )
        self._runs.append(path)
        op = self.op

        def task() -> int:
            with trace_span("accumulator.flush_run", run=run_index):
                rows = _combine_pending(op, keys, vals)
                written = write_npy_run(path, rows, run_index=run_index)
            log.info("host accumulator: spilled run %d (%d rows)",
                     run_index + 1, len(rows))
            return written

        self._ensure_writer().submit(task)

    def drain_spills(self) -> None:
        """Barrier before any read of the run files (fold_arrays) or the
        final accounting; re-raises a recorded writer error."""
        if self._writer is not None:
            self._writer.drain()

    def close_spills(self, abort: bool = True) -> None:
        if self._writer is not None:
            self._writer.close(abort=abort)

    def spill_stats(self) -> dict:
        from mapreduce_rust_tpu.runtime.spill import tier_spill_stats

        return tier_spill_stats(self._writer, len(self._runs))

    def spill_snapshot(self) -> "tuple[float, float, int] | None":
        from mapreduce_rust_tpu.runtime.spill import tier_spill_snapshot

        return tier_spill_snapshot(self._writer)

    def remove_runs(self) -> None:
        """Job-end cleanup of this accumulator's spill run files (the
        driver owns the lifecycle — see dictionary.remove_run_files).
        Closes the writer first so no run lands after its unlink."""
        self.close_spills(abort=True)
        remove_run_files(self._runs)

    def _combine_sorted(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """Merge two sorted deduped [n,3] row arrays into one (the same
        packed-column kernel as the pending fold — one implementation, so
        the run merge and the pending combine cannot order differently)."""
        rows = np.concatenate([a, b])
        return _combine_rows(self.op, rows[:, :2], rows[:, 2])

    def fold_arrays(self) -> np.ndarray:
        """The exact fold as sorted rows [n, 3] (k1, k2, value) — one row
        per distinct key (scalar ops) or per distinct (key, value) pair
        ("distinct"). Runs merge through a binary-counter tree (LSM-style:
        equal-size partials merge first), so a K-run fold costs
        O(total log K) combine work instead of re-combining the full
        accumulated result once per run; peak memory stays O(result)."""
        self.drain_spills()  # every enqueued run must be on disk first
        stack: list[tuple[int, np.ndarray]] = []  # (level, rows)

        def push(rows: np.ndarray) -> None:
            level = 0
            while stack and stack[-1][0] == level:
                _, prev = stack.pop()
                rows = self._combine_sorted(prev, rows)
                level += 1
            stack.append((level, rows))

        for path in self._runs:
            push(np.load(path))
        if self._keys:
            push(self._pending_rows())
        rows = np.empty((0, 3), np.int64)
        for _, r in stack:
            rows = self._combine_sorted(rows, r)
        return rows

    def snapshot(self) -> tuple[np.ndarray, np.ndarray]:
        """(keys [n,2], vals [n]) of everything accumulated so far — for
        the driver checkpoint. Folded form, which resumes exactly (every
        op is associative). Only valid before .table is first read."""
        if not self._keys and not self._runs:
            return np.empty((0, 2), np.int64), np.empty(0, np.int64)
        rows = self.fold_arrays()
        return rows[:, :2], rows[:, 2]

    @property
    def table(self) -> dict:
        if self._table is None:
            self._table = self._fold()
        return self._table

    def _fold(self) -> dict:
        if not self._keys and not self._runs:
            return {}
        rows = self.fold_arrays()
        if self.op == "distinct":
            t: dict = collections.defaultdict(set)
            for a, b, v in rows.tolist():
                t[(a, b)].add(v)
            return t
        return {
            (a, b): v
            for a, b, v in zip(
                rows[:, 0].tolist(), rows[:, 1].tolist(), rows[:, 2].tolist()
            )
        }


@dataclasses.dataclass
class JobResult:
    stats: JobStats
    table: dict            # word bytes → final value (int or sorted doc list)
    output_files: list[str]


def _scan_payload(payload: bytes):
    """Tagged scan result of one chunk — runs on the ingest pool. The
    native C pass releases the GIL, so scans of consecutive chunks overlap
    each other, the chunker thread, and device dispatch."""
    from mapreduce_rust_tpu.native.host import scan_unique_raw

    res = scan_unique_raw(payload)
    if res is not None:
        return ("raw", *res)
    from mapreduce_rust_tpu.core.hashing import hash_words
    from mapreduce_rust_tpu.runtime.dictionary import extract_words

    seen: set = set()
    words = [w for w in extract_words(payload) if not (w in seen or seen.add(w))]
    return ("list", words, hash_words(words))


def _slice_words(raw: bytes, ends: np.ndarray, idx) -> list[bytes]:
    """Materialize words idx (ascending indices) of a concatenated
    (raw, ends) scan result."""
    ends_l = ends.tolist()
    return [raw[(ends_l[i - 1] if i else 0): ends_l[i]] for i in idx]


def scan_keys(kind, parts) -> np.ndarray:
    """The hash-pair array of a tagged scan result."""
    return parts[2] if kind == "raw" else parts[1]


def _routed_parts(keys, mask, reduce_n: int, range_mode: bool = False):
    """Reduce partitions one chunk's (masked) keys route to — the
    provenance ledger's chunk→partition edge (ISSUE 20). Hash apps route
    k1 % reduce_n, so one vectorized unique over the scan's key column
    answers it exactly; range apps route through sampler-derived
    splitters on the WORD, which the scan result no longer carries — a
    range chunk claims every partition (conservative: the blast radius
    can only over-approximate, never miss a dependent partition)."""
    if range_mode:
        return list(range(reduce_n))
    k1 = keys[:, 0] if getattr(keys, "ndim", 1) > 1 else keys
    if mask is not None:
        k1 = k1[mask]
    n = len(k1)
    if n == 0:
        return []
    # Exact answer, sampled fast path: a strided sample that already
    # shows every partition proves the full set (an observed residue is
    # definitely present; more than reduce_n is impossible) without
    # touching the other keys — for any non-degenerate chunk with
    # reduce_n in the single digits this is the ~always branch, and it
    # keeps the ledger's per-byte tax inside the ≤2% bench contract.
    # Only a skewed chunk that genuinely misses partitions pays the full
    # bincount pass.
    if n > 4096:
        sample = np.asarray(k1[:: n // 2048], dtype=np.int64) % reduce_n
        if len(np.unique(sample)) == reduce_n:
            return list(range(reduce_n))
    hits = np.bincount(
        (np.asarray(k1, dtype=np.int64) % reduce_n).astype(np.intp),
        minlength=reduce_n,
    )
    return [int(r) for r in np.flatnonzero(hits)]


def fold_scan_into_dictionary(dictionary: Dictionary, mask, kind, parts) -> None:
    """Fold one tagged scan result — ("raw", raw, ends, keys[, ...]) or
    ("list", words, keys[, ...]) — into the egress dictionary, restricted
    to the keys a filtering app keeps. mask is the PRECOMPUTED
    App.host_mask(scan_keys(...)) result (callers that also filter their
    merge stream reuse it — the [n, Q] compare is per-window hot-path
    work), or None for keep-everything, which folds via the fast paths.
    For grep-style apps the dictionary then scales with the QUERY, not the
    corpus vocabulary — non-query words are never materialized."""
    if kind == "raw":
        raw, ends, keys = parts[0], parts[1], parts[2]
        if mask is None:
            dictionary.add_scanned_raw(raw, ends, keys)
            return
        idx = np.nonzero(mask)[0].tolist()
        if idx:
            dictionary.add_scanned(_slice_words(raw, ends, idx), keys[idx])
    else:
        words, keys = parts[0], parts[1]
        if mask is not None:
            idx = np.nonzero(mask)[0].tolist()
            if not idx:
                return
            words = [words[i] for i in idx]
            keys = keys[idx]
        dictionary.add_scanned(words, keys)


_SENTINEL = object()


@contextlib.contextmanager
def _a2a_span(stats, **span_args):
    """One mesh.all_to_all block: the trace span PLUS a wall-clock
    accumulation into stats.all_to_all_s, so the manifest's ICI-vs-compute
    split exists even for untraced runs (the tracer's per-round summary
    rides along only when tracing is on). Covers tokenize + bucket scatter
    + collective + merge dispatch of the round — on an async backend this
    is dispatch-side time; the blocking tail lands in device_wait_s."""
    t0 = time.perf_counter()
    try:
        with trace_span("mesh.all_to_all", **span_args):
            yield
    finally:
        dt = time.perf_counter() - t0
        stats.all_to_all_s += dt
        # Per-round distribution beside the aggregate: the manifest then
        # carries a2a p50/p95/p99 even for untraced runs (ISSUE 5).
        stats.record_hist("a2a.round_s", dt)
        wb = span_args.get("wire_bytes")
        if wb:
            stats.record_hist("a2a.wire_bytes", wb)


class _IngestStream:
    """Shared ingest: a prefetch thread runs read→normalize→chunk ahead of
    the consumer (bounded queue), and a thread pool runs the dictionary
    scans; scan results fold into the Dictionary only on the consumer
    thread. doc_id = position in inputs + doc_id_offset (a worker's map
    task passes its task id so inverted_index doc ids stay global)."""

    def __init__(self, cfg: Config, inputs: Sequence[str], stats: JobStats,
                 dictionary: Dictionary, doc_id_offset: int = 0,
                 skip_chunks: int = 0,
                 doc_ids: "Sequence[int] | None" = None,
                 host_mask=None, lineage_range: bool = False) -> None:
        import queue
        import threading
        from concurrent.futures import ThreadPoolExecutor

        from mapreduce_rust_tpu.runtime.lineage import active_ledger

        self.cfg = cfg
        self.stats = stats
        # Provenance (ISSUE 20): digests computed on the scan pool (the
        # payload is hot there), recorded in chunk order by _fold_done on
        # the consumer thread. None when the ledger is off — zero work.
        self._ledger = active_ledger()
        self._lineage_range = lineage_range
        # Chunks below a resumed checkpoint: read (the chunker must stay
        # positionally deterministic) but neither dictionary-scanned nor
        # yielded — their words and counts are already in the checkpoint.
        self.skip_chunks = skip_chunks
        self.dictionary = dictionary
        # Filtering apps (App.host_mask) restrict dictionary growth to
        # their query keys; the default keep-all mask folds via fast paths.
        self.host_mask = host_mask if host_mask is not None else (lambda keys: None)
        self.workers = max(cfg.ingest_threads, 1)
        self.pool = ThreadPoolExecutor(max_workers=self.workers,
                                       thread_name_prefix="mr/ingest-io")
        self.scans: collections.deque = collections.deque()
        self.q: "queue.Queue" = queue.Queue(maxsize=max(cfg.prefetch_chunks, 1))
        self.err: BaseException | None = None
        self._stop = False
        self._doc_ids = list(doc_ids) if doc_ids is not None else None
        self._thread = threading.Thread(
            target=self._produce, args=(list(inputs), stats, doc_id_offset),
            name="mr/ingest", daemon=True
        )
        self._thread.start()

    def _put(self, item) -> bool:
        import queue

        while True:
            try:
                self.q.put(item, timeout=0.2)
                return True
            except queue.Full:
                if self._stop:
                    return False

    def _produce(self, inputs, stats, doc_id_offset) -> None:
        # This producer thread legitimately owns bytes_in/chunks/forced_cuts
        # (disjoint from the consumer's fields); under the sanitizer it must
        # say so, or its first write raises. No-op otherwise.
        stats.register_writer()
        try:
            for i, path in enumerate(inputs):
                doc = self._doc_ids[i] if self._doc_ids else doc_id_offset + i
                stats.bytes_in += os.path.getsize(path)
                with open(path, "rb") as f:
                    for chunk in chunk_stream(f, doc, self.cfg.chunk_bytes):
                        stats.chunks += 1
                        stats.forced_cuts += int(chunk.forced_cut)
                        if not self._put(chunk):
                            return
        except BaseException as e:  # re-raised on the consumer thread
            self.err = e
        finally:
            self._put(_SENTINEL)

    def _scan_lineage(self, payload: bytes):
        """_scan_payload plus the chunk's content digest, both on the pool
        thread where the payload is hot — the scan result grows a (dg,
        nbytes) prefix that _fold_done strips and records in FIFO order."""
        from mapreduce_rust_tpu.runtime.lineage import chunk_digest

        return (chunk_digest(payload), len(payload), *_scan_payload(payload))

    def _fold_done(self, block: bool = False) -> None:
        while self.scans and (block or self.scans[0][0].done()):
            fut, doc_id = self.scans.popleft()
            res = fut.result()
            if self._ledger is not None:
                dg, nb, kind, *rest = res
            else:
                kind, *rest = res
            keys = scan_keys(kind, rest)
            mask = self.host_mask(keys)
            fold_scan_into_dictionary(self.dictionary, mask, kind, rest)
            if self._ledger is not None:
                self._ledger.record_chunk(
                    doc_id, nb, dg,
                    parts=_routed_parts(keys, mask, self.cfg.reduce_n,
                                        self._lineage_range),
                )
            block = False  # blocking drain pops exactly one

    def __iter__(self):
        scan = self._scan_lineage if self._ledger is not None else _scan_payload
        while True:
            t0 = time.perf_counter()
            with trace_span("ingest.wait"):
                chunk = self.q.get()
            dt = time.perf_counter() - t0
            self.stats.ingest_wait_s += dt
            self.stats.record_hist("ingest.wait_s", dt)
            if chunk is _SENTINEL:
                if self.err is not None:
                    raise self.err
                return
            if self.skip_chunks > 0:
                self.skip_chunks -= 1
                continue
            self.scans.append(
                (self.pool.submit(scan, bytes(chunk.data[: chunk.nbytes])),
                 chunk.doc_id)
            )
            # Backpressure: each pending future pins a chunk-sized payload;
            # fold the oldest (blocking) once the backlog exceeds the pool.
            self._fold_done(block=len(self.scans) > 2 * self.workers + 4)
            maybe_snapshot()  # flight-recorder tick: per chunk, off-hot-path
            metrics_tick()    # live-metrics sampler, same piggyback contract
            yield chunk

    def close(self, abort: bool = False) -> None:
        """Fold remaining scans and release threads. abort=True (exception
        path) skips folding and just unblocks + reaps the producer."""
        self._stop = True
        if abort:
            try:
                while True:
                    self.q.get_nowait()
            except Exception:
                pass
            for f, _doc in self.scans:
                f.cancel()
            self.scans.clear()
        else:
            while self.scans:
                self._fold_done(block=True)
        # cancel_futures + wait: queued scans cancel, the (bounded) running
        # ones finish and are reaped — an abandoned scan must not outlive
        # the stream holding its chunk payload (same contract as the
        # host-map engine's teardown).
        self.pool.shutdown(wait=True, cancel_futures=True)
        self._thread.join(timeout=5)


def _stream_single(cfg: Config, app: App, inputs, stats, acc, dictionary,
                   doc_id_offset: int = 0) -> None:
    enable_compilation_cache(cfg.compilation_cache_dir)
    device = select_device(cfg.device)
    use_pallas = device.platform == "tpu"
    u_cap = cfg.effective_partial_capacity()
    depth = max(cfg.pipeline_depth, 1)
    map_combine, merge = make_step_fns(app, u_cap, use_pallas)
    slow_fns = None  # full-width replay path, compiled only if ever needed

    state = jax.device_put(KVBatch.empty(cfg.merge_capacity), device)
    pending: collections.deque = collections.deque()  # (ovf, ev_count, evicted, chunk_host, did)

    def replay_chunk(chunk_host: np.ndarray, doc_id) -> None:
        # More distinct keys in the chunk than partial_capacity: the fast
        # path clamped its update to empty (make_step_fns), so re-run the
        # whole chunk at full width. Exact, never silent (VERDICT r1 weak 3).
        nonlocal state, slow_fns
        stats.partial_overflow_replays += 1
        if slow_fns is None:
            slow_fns = make_step_fns(app, cfg.chunk_bytes, use_pallas)
        with trace_span("chunk.replay"):
            update, _ = slow_fns[0](jax.device_put(chunk_host, device), doc_id)
            state, evicted, ev_count = slow_fns[1](state, update)
            if int(ev_count) > 0:
                stats.spill_events += 1
                stats.spilled_keys += int(ev_count)
                acc.add_batch(evicted)

    def drain(n: int) -> None:
        # Resolve the oldest n pipeline steps with ONE batched readback:
        # through a tunneled TPU every device→host read costs a ~80 ms
        # round trip no matter its size, so per-chunk scalar reads cap the
        # stream at ~12 chunks/s. One device_get for the whole window pays
        # that latency once per `pipeline_depth` chunks.
        if n <= 0:
            return
        batch = [pending.popleft() for _ in range(n)]
        t0 = time.perf_counter()
        with trace_span("device.drain", steps=n):
            flat = jax.device_get([x for (ovf, evc, *_rest) in batch for x in (ovf, evc)])
        dt = time.perf_counter() - t0
        stats.device_wait_s += dt
        stats.record_hist("device.drain_s", dt)
        _sample_device_memory(stats)
        for (ovf, evc, evicted, chunk_host, did), ovf_n, ev_n in zip(
            batch, flat[::2], flat[1::2]
        ):
            if int(ev_n) > 0:
                stats.spill_events += 1
                stats.spilled_keys += int(ev_n)
                with trace_span("spill", keys=int(ev_n)):
                    acc.add_batch(evicted)
            if int(ovf_n) > 0:
                replay_chunk(chunk_host, did)

    ingest = _IngestStream(cfg, inputs, stats, dictionary, doc_id_offset,
                           host_mask=app.host_mask,
                           lineage_range=app.partition_mode == "range")
    try:
        for chunk in ingest:
            with trace_span("chunk.dispatch"):
                chunk_dev = jax.device_put(chunk.data, device)
                did = jax.device_put(np.int32(chunk.doc_id), device)
                update, ovf = map_combine(chunk_dev, did)
                # Merge dispatches immediately — an overflowed update is
                # empty on device, so merging before the flag reaches the
                # host is safe.
                state, evicted, ev_count = merge(state, update)
                pending.append((ovf, ev_count, evicted, chunk.data, did))
            # Keep one window in flight while draining the previous one, so
            # the batched readback's round trip overlaps dispatched work.
            if len(pending) >= 2 * depth:
                drain(depth)
        drain(len(pending))
    except BaseException:
        ingest.close(abort=True)
        raise
    ingest.close()
    acc.add_batch(state)


#: (app, cap) → merge_packed, LRU-bounded (ISSUE 13 satellite): the old
#: plain dict grew one compiled merge per (app, cap) FOREVER — a
#: long-lived multi-job process (ROADMAP item 2) leaked jit executables it
#: could never drop. Bounded, back-to-back same-config runs still hit the
#: warm entry (the round-3 "warm == cold" bench killer stays fixed), while
#: a churn of distinct apps/caps evicts oldest-first.
_PACKED_FNS: "collections.OrderedDict" = collections.OrderedDict()
_PACKED_FNS_MAX = 8


def clear_packed_fns() -> None:
    """Explicit clear hook for the packed-merge jit cache: drop every
    cached closure (their XLA executables free once the last reference
    dies). For embedders that KNOW no further host-engine run is coming —
    run_job's own teardown calls :func:`trim_packed_fns` instead, which
    keeps the warm path for repeated jobs."""
    _PACKED_FNS.clear()


def trim_packed_fns(limit: int = _PACKED_FNS_MAX) -> None:
    """Evict least-recently-used packed-merge closures beyond ``limit`` —
    wired into run_job teardown so a multi-job process holds a bounded
    working set instead of one executable per (app, cap) ever seen."""
    while len(_PACKED_FNS) > max(int(limit), 0):
        _PACKED_FNS.popitem(last=False)


def make_packed_merge_fn(app: App, cap: int):
    """Merge one host-mapped update, shipped as ONE flat uint32 array
    (host→device transfers through a tunneled chip pay a big fixed round
    trip, so the four KVBatch leaves must not be four transfers):

        flat[0]           n — number of real records
        flat[1 : 1+cap]   k1 (SENTINEL-padded so padding sorts last)
        flat[1+cap : 1+2cap]  k2
        flat[1+2cap : 1+3cap] value (uint32 bit-pattern of the int32)

    Returns (new_state, evicted, evicted_count), donating the old state —
    the host-engine twin of _build_step_fns.merge.
    """
    key = (app, cap)
    fn = _PACKED_FNS.get(key)
    if fn is not None:
        _PACKED_FNS.move_to_end(key)  # LRU: reuse refreshes recency
        return fn
    op = app.combine_op

    @functools.partial(jax.jit, donate_argnums=(0,))
    def merge_packed(state: KVBatch, flat: jnp.ndarray):
        n = flat[0].astype(jnp.int32)
        update = KVBatch(
            k1=flat[1 : 1 + cap],
            k2=flat[1 + cap : 1 + 2 * cap],
            value=flat[1 + 2 * cap : 1 + 3 * cap].astype(jnp.int32),
            valid=jnp.arange(cap, dtype=jnp.int32) < n,
        )
        new_state, evicted = merge_batches(state, update, op=op)
        ev_count = jnp.sum(evicted.valid.astype(jnp.int32))
        return new_state, evicted, ev_count

    _PACKED_FNS[key] = merge_packed
    trim_packed_fns()  # the bound holds at every insert, not only job end
    return merge_packed


def _merge_cost_analysis(app: App, cfg: Config) -> "dict | None":
    """``jax.stages`` cost analysis of the jitted packed-merge fn
    (ISSUE 19): flops + bytes accessed PER DISPATCH — the
    operational-intensity input the roofline attribution uses for the
    device-merge stage. Abstract lowering (ShapeDtypeStructs, the shapes
    the run just used) — no device buffers; the executable cache makes
    the ``compile()`` a lookup, not a second compile."""
    cap = cfg.host_update_cap
    n = cfg.merge_capacity
    state = KVBatch(
        k1=jax.ShapeDtypeStruct((n,), jnp.uint32),
        k2=jax.ShapeDtypeStruct((n,), jnp.uint32),
        value=jax.ShapeDtypeStruct((n,), jnp.int32),
        valid=jax.ShapeDtypeStruct((n,), jnp.bool_),
    )
    flat = jax.ShapeDtypeStruct((1 + 3 * cap,), jnp.uint32)
    lowered = make_packed_merge_fn(app, cap).lower(state, flat)
    try:
        ca = lowered.compile().cost_analysis()
    except Exception:
        ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for key_name in ("flops", "bytes accessed", "transcendentals"):
        v = ca.get(key_name)
        if isinstance(v, (int, float)):
            out[key_name.replace(" ", "_")] = float(v)
    return out or None


def _pack_update(keys: np.ndarray, values: np.ndarray, cap: int) -> np.ndarray:
    """Lay one window's (keys uint32[n,2], values) into the flat layout
    make_packed_merge_fn expects. The reference packer: allocates (and
    memsets) a fresh buffer per call — the dispatch plane's _PackStager
    produces byte-identical output from a persistent buffer (the test
    suite holds the two equal)."""
    n = len(keys)
    flat = np.full(1 + 3 * cap, 0xFFFFFFFF, dtype=np.uint32)  # SENTINEL pad
    flat[0] = n
    flat[1 : 1 + n] = keys[:, 0]
    flat[1 + cap : 1 + cap + n] = keys[:, 1]
    flat[1 + 2 * cap : 1 + 2 * cap + n] = np.asarray(values, dtype=np.uint32)
    return flat


class _PackStager:
    """Zero-memset packed-update staging (ISSUE 13 tentpole b): ONE
    persistent ``1 + 3·cap`` uint32 buffer reused across dispatches,
    re-sentineling only the previously-dirty prefix beyond the new fill.
    The old per-dispatch ``np.full`` was a ~786 KB allocate+memset at the
    default cap even for a 100-word tail window; here a small window
    touches O(n) bytes plus whatever the LAST window dirtied — by
    construction byte-identical to :func:`_pack_update`'s output.

    Reuse safety: ``jax.device_put`` COPIES the host buffer on the CPU
    backend (measured on this image — mutate-after-put does not alter the
    device array), so the buffer is free the moment the put returns. On
    accelerator backends the host→device transfer may be asynchronous
    w.r.t. the source buffer; ``needs_barrier`` tells the dispatch plane
    to wait for the put (``block_until_ready`` on the INPUT array — a
    dispatch-thread-local sync the router never sees) before this buffer
    is dirtied again."""

    SENTINEL = np.uint32(0xFFFFFFFF)

    def __init__(self, cap: int, device) -> None:
        self.cap = cap
        self.flat = np.full(1 + 3 * cap, self.SENTINEL, dtype=np.uint32)
        self.dirty = 0  # records of the previous pack still in the buffer
        self.needs_barrier = getattr(device, "platform", "cpu") != "cpu"

    def pack(self, k1: np.ndarray, k2: np.ndarray,
             vals: np.ndarray) -> np.ndarray:
        n = len(k1)
        cap, flat, dirty = self.cap, self.flat, self.dirty
        if dirty > n:  # re-sentinel ONLY the stale tail of each section
            flat[1 + n : 1 + dirty] = self.SENTINEL
            flat[1 + cap + n : 1 + cap + dirty] = self.SENTINEL
            flat[1 + 2 * cap + n : 1 + 2 * cap + dirty] = self.SENTINEL
        flat[0] = n
        flat[1 : 1 + n] = k1
        flat[1 + cap : 1 + cap + n] = k2
        flat[1 + 2 * cap : 1 + 2 * cap + n] = vals
        self.dirty = n
        return flat


def _coalesce_updates_py(a_keys, a_vals, m, b_keys, b_vals):
    """Vectorized numpy fallback for ``mr_coalesce_updates`` (no native
    toolchain): merge two sorted unique-key columns, summing counts on
    duplicate keys. Same output, one concatenate+argsort instead of the
    linear walk."""
    keys = np.concatenate([a_keys[:m], b_keys])
    vals = np.concatenate([a_vals[:m], b_vals])
    order = np.argsort(keys, kind="stable")
    ks, vs = keys[order], vals[order]
    if not len(ks):
        return ks, vs
    first = np.empty(len(ks), dtype=bool)
    first[0] = True
    first[1:] = ks[1:] != ks[:-1]
    idx = np.nonzero(first)[0]
    return ks[idx], np.add.reduceat(vs, idx)


# ---------------------------------------------------------------------------
# slow_dispatch chaos checkpoint (ISSUE 13 satellite) — the spill plane's
# slow_disk pattern: seeded per-merge-dispatch delay, MR_CHAOS only (the
# env form rides a whole process tree), cached per spec string.
# ---------------------------------------------------------------------------

_dispatch_chaos_cache: dict = {}


def _chaos_slow_dispatch(dispatch_index: int) -> None:
    """The ``slow_dispatch`` injection checkpoint: ONE site in the dispatch
    plane (fires per merge dispatch, so ``p=`` samples by dispatch index
    and reruns delay the same dispatches). The async plane hides the delay
    on the dispatch thread; the sync plane eats it on the router's wall —
    the pair bench.py measures."""
    spec = os.environ.get("MR_CHAOS")
    if not spec:
        return
    plan = _dispatch_chaos_cache.get(spec)
    if plan is None:
        try:
            from mapreduce_rust_tpu.analysis.chaos import ChaosPlan

            plan = ChaosPlan.parse(spec)
        except Exception:
            plan = False  # a bad ambient spec must not fail dispatches
        _dispatch_chaos_cache[spec] = plan
    if not plan:
        return
    f = plan.pick("slow_dispatch", tid=dispatch_index)
    if f is not None and f.seconds > 0:
        time.sleep(f.seconds)


def dispatch_chaos_fired(spec: str) -> list:
    """Fired slow_dispatch events for ``spec`` (test/bench introspection)."""
    plan = _dispatch_chaos_cache.get(spec)
    return plan.fired() if plan else []


# (sync_dispatch_forced is imported from config at the top of this module:
# the fold-shard auto heuristic reads the SAME check — one definition, so
# the plane and the heuristic can never disagree on what counts as async.)


class _DispatchPlane:
    """The device-merge dispatch plane (ISSUE 13 tentpole): scan-order
    scatter-back, update pack, ``device_put`` and the compiled packed
    merge — the per-window host→device hop that PR 10's doctor measured
    as ~13 s of host-glue on the Zipf leg — run on ONE dedicated
    depth-bounded dispatch thread. The router hands off O(1) per window
    (a tuple of already-materialized scan arrays) and goes back to
    routing; glue stops booking device hops.

    Three costs die here:

    - **cross-window coalescing** (``Config.dispatch_coalesce``, "sum"
      apps only — pre-summing any other op would be wrong): successive
      windows' (packed-key, count) columns merge into a staging combine
      buffer (``mr_coalesce_updates``: sorted linear merge, duplicate
      keys sum), and a device merge dispatches only when fill crosses
      ``dispatch_fill_frac·cap`` or the stream ends — under a Zipf
      vocabulary most of a window's keys already sit in staging, so far
      fewer records ship;
    - **zero-memset staging** (:class:`_PackStager`): the per-dispatch
      ``np.full(1 + 3·cap)`` becomes a persistent buffer that
      re-sentinels only the previously-dirty prefix;
    - **serialized dispatch**: the jit call and its drain readbacks run
      off the router thread entirely (``--sync-dispatch`` /
      ``MR_DISPATCH_SYNC=1`` keeps the inline path for A/B).

    Exactness: the dispatch stream is a pure function of the window
    sequence (which the router consumes in window order) and the dispatch
    config — never of (host_map_workers, fold_shards) — so outputs stay
    bit-identical across the whole (W, S) matrix at a fixed dispatch
    config; with coalescing OFF the stream is exactly PR 10's, sync or
    async. Coalescing changes WHICH merges the device sees (sorted,
    pre-summed), not what they sum to: oracle-exact by associativity.

    Failure containment is the PR 9/10 plane pattern verbatim: a dispatch
    error poisons the plane, the dead thread keeps DRAINING its queue so
    the router's bounded ``submit`` can never deadlock, the original
    error re-raises on the router at the next submit or at ``finish``,
    and ``abort`` forces the sentinel past a full queue.
    """

    _SENTINEL = object()
    _QUEUE_DEPTH = 8  # windows in flight router→dispatch; each pins one
    # window's scan arrays (shared read-only with the fold plane's slices)

    def __init__(self, cfg: Config, app: App, stats: JobStats, acc,
                 dictionary, device) -> None:
        import queue
        import threading

        self.app = app
        self.stats = stats
        self.acc = acc
        self.dictionary = dictionary
        self.device = device
        self.cap = cfg.host_update_cap
        self.depth = max(cfg.pipeline_depth, 1)
        self.sync = (not cfg.dispatch_async) or sync_dispatch_forced()
        self.coalesce = bool(cfg.dispatch_coalesce) \
            and app.combine_op == "sum"
        self.stage_cap = cfg.effective_dispatch_stage_cap()
        self.fill_threshold = max(
            1, min(self.stage_cap,
                   int(round(cfg.dispatch_fill_frac * self.stage_cap)))
        )
        self.merge_packed = make_packed_merge_fn(app, self.cap)
        self.state = jax.device_put(KVBatch.empty(cfg.merge_capacity), device)
        self.pending: collections.deque = collections.deque()  # (ev, evicted)
        self._stager = _PackStager(self.cap, device)
        if self.coalesce:
            # Ping-pong staging pair, sized stage_cap (SEVERAL windows of
            # distinct keys — a cap-sized buffer would never coalesce a
            # high-cardinality window; see Config.dispatch_stage_cap):
            # the native merge writes into the OTHER buffer (inputs must
            # not alias outputs), then the roles swap — no allocation per
            # window.
            self._skeys = [
                np.empty(self.stage_cap, np.uint64) for _ in range(2)
            ]
            self._svals = [
                np.empty(self.stage_cap, np.int64) for _ in range(2)
            ]
            self._scur = 0
            self._sn = 0
        # Plane-local tallies (the fold-plane doctrine): the dispatch
        # thread owns these cells; the router publishes benign-stale
        # copies per window (publish_live) and collect() writes the exact
        # finals after the join.
        self.dispatch_s = 0.0        # thread seconds in scatter/pack/put/jit
        self.stall_s = 0.0           # router blocked on a full queue + join
        self.idle_s = 0.0            # thread seconds waiting for windows
        self.device_wait_s = 0.0
        self.spill_events = 0
        self.spilled_keys = 0
        self.merge_dispatches = 0
        self.records_shipped = 0
        self.submit_hist = Histogram()   # per-dispatch pack+put+jit seconds
        self.drain_hist = Histogram()    # per-drain blocking readback
        self.error: "BaseException | None" = None
        self.poisoned = threading.Event()
        self._finished = False
        stats.dispatch_mode = ("sync" if self.sync else "async") \
            + ("+coalesce" if self.coalesce else "")
        if self.sync:
            self._q = None
            self._thread = None
            return
        self._q: "queue.Queue" = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._thread = threading.Thread(
            target=self._loop, name="mr/dispatch", daemon=True
        )
        self._thread.start()

    # ---- dispatch thread ----

    def _loop(self) -> None:
        # Sanitizer registration: this thread legitimately writes
        # device_mem_high_bytes (via _sample_device_memory) — every other
        # tally is plane-local until collect().
        self.stats.register_writer()
        q = self._q
        saw_sentinel = False
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                self.idle_s += time.perf_counter() - t0
                if item is self._SENTINEL:
                    saw_sentinel = True
                    if not self.poisoned.is_set():
                        self._finalize()
                    return
                if self.poisoned.is_set():
                    continue  # poisoned: drain, don't dispatch
                self._handle(item)
        except BaseException as e:
            self.error = e
            self.poisoned.set()
            if not saw_sentinel:
                # Keep consuming (discarding) until the sentinel: the
                # router's bounded put must never deadlock against a dead
                # dispatch thread.
                while q.get() is not self._SENTINEL:
                    pass

    def _handle(self, item) -> None:
        """One window: scatter back to exact scan order, apply the
        filtering app's mask, stamp values, then coalesce-or-dispatch."""
        doc_id, kind, keys, counts, pos, mask = item
        t0 = time.perf_counter()
        with trace_span("dispatch.window", doc=doc_id, n=len(keys)):
            if kind == "sharded":
                # Grouped scan result: scatter keys/counts (and the mask,
                # computed on grouped rows) back to EXACT scan order so
                # the merge stream matches the unsharded engine's.
                keys_d = np.empty_like(keys)
                keys_d[pos] = keys
                counts_d = np.empty_like(counts)
                counts_d[pos] = counts
                if mask is not None:  # filtering app: query keys only
                    mask_d = np.empty(len(pos), dtype=bool)
                    mask_d[pos] = mask
                    keys_d, counts_d = keys_d[mask_d], counts_d[mask_d]
                keys, counts = keys_d, counts_d
            elif mask is not None:  # filtering app: query keys only
                keys, counts = keys[mask], counts[mask]
            values = self.app.host_values(counts, doc_id)
            if self.coalesce:
                self._coalesce_window(keys, values)
            else:
                # PR 10 stream verbatim: scan order, split at cap.
                cap = self.cap
                for start in range(0, len(keys), cap):
                    ks = keys[start : start + cap]
                    self._dispatch(
                        ks[:, 0], ks[:, 1],
                        np.asarray(values[start : start + cap],
                                   dtype=np.uint32),
                    )
        self.dispatch_s += time.perf_counter() - t0

    def _coalesce_window(self, keys: np.ndarray, values) -> None:
        from mapreduce_rust_tpu.native.host import coalesce_updates_into

        packed = (keys[:, 0].astype(np.uint64) << np.uint64(32)) \
            | keys[:, 1].astype(np.uint64)
        order = np.argsort(packed, kind="stable")
        pk = np.ascontiguousarray(packed[order])
        pv = np.ascontiguousarray(
            np.asarray(values, dtype=np.int64)[order]
        )
        n = len(pk)
        if self._sn + n > self.stage_cap:
            # The merged result may not fit: flush first. Conservative
            # (duplicates could have made it fit), but deterministic and
            # cheap — and fill_threshold <= stage_cap means staging
            # flushes well before this bound matters under normal shapes.
            self._flush_staging()
        if n >= self.stage_cap:
            # A window wider than the whole staging buffer ships
            # directly, in sorted cap-sized slices — never through
            # staging (with the auto 64x stage cap this is the
            # degenerate single-giant-window shape only).
            for start in range(0, n, self.cap):
                self._dispatch_packed(pk[start : start + self.cap],
                                      pv[start : start + self.cap])
            return
        cur, nxt = self._scur, 1 - self._scur
        m = coalesce_updates_into(
            self._skeys[cur], self._svals[cur], self._sn, pk, pv,
            self._skeys[nxt], self._svals[nxt],
        )
        if m is None:  # no native lib: vectorized numpy merge
            ks, vs = _coalesce_updates_py(
                self._skeys[cur], self._svals[cur], self._sn, pk, pv
            )
            m = len(ks)
            self._skeys[nxt][:m] = ks
            self._svals[nxt][:m] = vs
        self._scur, self._sn = nxt, int(m)
        if self._sn >= self.fill_threshold:
            self._flush_staging()

    def _flush_staging(self) -> None:
        """Ship the staging combine buffer as cap-sized packed merges
        (sorted, pre-summed): every chunk but the tail goes out 100%
        full — the record-count reduction IS the coalesce factor."""
        if not self.coalesce or self._sn == 0:
            return
        cur, n = self._scur, self._sn
        self._sn = 0
        for start in range(0, n, self.cap):
            # Clip the tail chunk at the FILL, not the buffer: a bare
            # [start : start+cap] slice clips at stage_cap and would ship
            # stale staging slots beyond n as real records.
            end = min(start + self.cap, n)
            self._dispatch_packed(self._skeys[cur][start:end],
                                  self._svals[cur][start:end])

    def _dispatch_packed(self, pk: np.ndarray, pv: np.ndarray) -> None:
        # int64 staging counts → the uint32 bit pattern the packed layout
        # carries (the device accumulates in int32 two's complement, so
        # pre-summing mod 2^32 is bit-exact against per-window merges).
        self._dispatch(
            (pk >> np.uint64(32)).astype(np.uint32),
            (pk & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            pv.astype(np.uint32),
        )

    def _dispatch(self, k1: np.ndarray, k2: np.ndarray,
                  vals: np.ndarray) -> None:
        t0 = time.perf_counter()
        _chaos_slow_dispatch(self.merge_dispatches)
        flat = self._stager.pack(k1, k2, vals)
        with trace_span("dispatch.submit", n=len(k1)):
            flat_dev = jax.device_put(flat, self.device)
            if self._stager.needs_barrier:
                # Accelerator backends: the put may read the host buffer
                # asynchronously — wait before the stager dirties it again
                # (CPU copies eagerly; see _PackStager).
                flat_dev.block_until_ready()
            self.state, evicted, ev_count = self.merge_packed(
                self.state, flat_dev
            )
        self.pending.append((ev_count, evicted))
        self.merge_dispatches += 1
        self.records_shipped += len(k1)
        self.submit_hist.add(time.perf_counter() - t0)
        if len(self.pending) >= 2 * self.depth:
            self._drain(self.depth)

    def _drain(self, n: int) -> None:
        # One batched readback per window batch — see _stream_single.drain.
        if n <= 0:
            return
        batch = [self.pending.popleft() for _ in range(n)]
        t0 = time.perf_counter()
        with trace_span("device.drain", steps=n):
            counts = jax.device_get([ev for ev, _ in batch])
        dt = time.perf_counter() - t0
        self.device_wait_s += dt
        self.drain_hist.add(dt)
        _sample_device_memory(self.stats)
        for (ev, evicted), ev_n in zip(batch, counts):
            if int(ev_n) > 0:
                self.spill_events += 1
                self.spilled_keys += int(ev_n)
                with trace_span("spill", keys=int(ev_n)):
                    self.acc.add_batch(evicted)

    def _finalize(self) -> None:
        """End-of-stream: flush the staging combine buffer, resolve every
        pending merge. Runs on the dispatch thread (async) or the router
        (sync) — after it, ``self.state`` is the complete device fold."""
        self._flush_staging()
        self._drain(len(self.pending))

    # ---- router side ----

    def _raise_error(self) -> None:
        if self.error is not None:
            raise self.error
        raise RuntimeError("dispatch plane poisoned without a recorded error")

    def submit(self, item) -> None:
        """Hand one window to the plane — O(1) for the router (sync mode
        runs the dispatch inline, the PR 10 path). Blocked = dispatch
        backpressure, timed into ``stall_s`` — the wall-clock "the
        dispatch is the ceiling" signal, exactly as fold_stall_s is for
        the fold."""
        import queue as _queue

        if self.sync:
            self._handle(item)
            return
        if self.poisoned.is_set():
            self._raise_error()
        try:
            self._q.put_nowait(item)
            return
        except _queue.Full:
            pass
        t0 = time.perf_counter()
        try:
            with trace_span("host_map.dispatch_stall"):
                while True:
                    if self.poisoned.is_set():
                        self._raise_error()
                    try:
                        self._q.put(item, timeout=0.05)
                        return
                    except _queue.Full:
                        continue
        finally:
            self.stall_s += time.perf_counter() - t0

    def finish(self) -> None:
        """Clean end-of-stream: sentinel, join, surface any dispatch
        error — called AFTER the last window was submitted. The join wall
        (the plane catching up on its backlog + the final drain) counts
        as dispatch stall, mirroring the fold plane's accounting."""
        if self._finished:
            return
        self._finished = True
        if self.sync:
            self._finalize()
            return
        t0 = time.perf_counter()
        self._q.put(self._SENTINEL)
        self._thread.join()
        self.stall_s += time.perf_counter() - t0
        if self.poisoned.is_set():
            self._raise_error()

    def abort(self) -> None:
        """Exception-path teardown: poison (the thread discards its
        backlog), force a sentinel past a full queue by displacing one
        item, reap the thread. Idempotent, never raises, never blocks
        forever."""
        import queue as _queue

        self.poisoned.set()
        if self._finished:
            return
        self._finished = True
        if self.sync:
            return
        while True:
            try:
                self._q.put_nowait(self._SENTINEL)
                break
            except _queue.Full:
                try:
                    self._q.get_nowait()
                except _queue.Empty:
                    pass
        self._thread.join(timeout=10)

    def mean_fill_frac(self) -> float:
        if not self.merge_dispatches:
            return 0.0
        return self.records_shipped / (self.merge_dispatches * self.cap)

    def publish_live(self, stats: JobStats) -> None:
        """Per-window live publication (router thread): the plane's cells
        are benign-stale at worst — the live ring, the fleet view and the
        streaming doctor must see a dispatch-bound job DURING the run
        (the PR 9 fold_s pattern). collect() writes the exact finals."""
        stats.dispatch_s = self.dispatch_s
        stats.dispatch_stall_s = self.stall_s
        stats.merge_dispatches = self.merge_dispatches
        stats.merge_fill_frac = round(self.mean_fill_frac(), 6)
        stats.device_wait_s = self.device_wait_s
        stats.spill_events = self.spill_events
        stats.spilled_keys = self.spilled_keys

    def collect(self, stats: JobStats) -> None:
        """Fold the plane's tallies into JobStats — router thread only,
        after finish/abort joined the thread (the fold-plane collect
        doctrine)."""
        stats.dispatch_s = self.dispatch_s
        stats.dispatch_stall_s = self.stall_s
        stats.merge_dispatches = self.merge_dispatches
        stats.merge_fill_frac = round(self.mean_fill_frac(), 6)
        stats.device_wait_s = self.device_wait_s
        stats.spill_events = self.spill_events
        stats.spilled_keys = self.spilled_keys
        for name, h in (("dispatch.submit_s", self.submit_hist),
                        ("device.drain_s", self.drain_hist)):
            if h.count:
                agg = stats.hists.get(name)
                if agg is None:
                    agg = stats.hists[name] = Histogram()
                agg.merge(h)


class _FoldShardPlane:
    """The sharded egress fold (ISSUE 9): S fold threads, each the SOLE
    owner of one key-hash-disjoint dictionary shard
    (runtime/dictionary.ShardedDictionary), fed per-window per-shard
    slices by the host-map router over bounded queues.

    Ownership discipline — the refactor the PR 3 sanitizer makes
    mechanically checkable: the router thread never touches shard state
    (it only slices read-only scan results and enqueues); a fold thread
    never touches another shard's queue or dictionary; each shard
    dictionary's owner is handed to its fold thread at start
    (``set_owner``), so under ``MR_SANITIZE=1`` a fold from the wrong
    thread raises at the write site and a mis-ROUTED key fails the
    vectorized ``check_shard_route`` assert before it can split a key's
    dedup state across shards.

    Failure containment: a fold thread that raises records its error,
    flips the shared poison flag and keeps DRAINING its queue (discarding)
    until the sentinel — the router's bounded ``put`` can therefore never
    deadlock against a dead consumer; the router surfaces the recorded
    error at its next route or at ``finish``. ``abort`` (exception-path
    teardown) poisons every shard, forces sentinels past full queues and
    reaps the threads without ever blocking forever.
    """

    _SENTINEL = object()

    def __init__(self, cfg: Config, stats: JobStats, shards) -> None:
        import queue
        import threading

        from mapreduce_rust_tpu.analysis.sanitize import sanitize_enabled

        self.n = len(shards)
        self.stats = stats
        self.shards = shards
        self._sanitize = sanitize_enabled(cfg)
        # Bounded per-shard queues: each entry pins one window's grouped
        # scan arrays (shared read-only across shards — slices are views),
        # so fold-plane memory stays O(depth × window result), never
        # O(corpus) — the same flat-memory contract as the scan budget.
        self.queues = [queue.Queue(maxsize=8) for _ in range(self.n)]
        self.errors: list = [None] * self.n
        self.poisoned = threading.Event()
        self.fold_s = [0.0] * self.n
        self.idle_s = [0.0] * self.n
        self.hists = [Histogram() for _ in range(self.n)]
        self.stall_s = 0.0  # router side: blocked puts + end-of-stream join
        self._finished = False
        self.threads = [
            threading.Thread(target=self._loop, args=(s,),
                             name=f"mr/fold-{s}", daemon=True)
            for s in range(self.n)
        ]
        for t in self.threads:
            t.start()

    # ---- fold threads ----

    def _loop(self, s: int) -> None:
        shard = self.shards[s]
        # Sanitizer registration (ISSUE 9 satellite): this thread becomes
        # the shard dictionary's owner and a registered stats writer —
        # no-ops unsanitized, asserts armed under MR_SANITIZE=1.
        self.stats.register_writer()
        set_owner = getattr(shard, "set_owner", None)
        if set_owner is not None:
            set_owner()
        q = self.queues[s]
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                self.idle_s[s] += time.perf_counter() - t0
                if item is self._SENTINEL:
                    return
                if self.poisoned.is_set():
                    continue  # another shard failed: drain, don't fold
                t0 = time.perf_counter()
                with trace_span("host_map.fold", shard=s):
                    self._fold_one(s, shard, item)
                dt = time.perf_counter() - t0
                self.fold_s[s] += dt
                self.hists[s].add(dt)
        except BaseException as e:
            self.errors[s] = e
            self.poisoned.set()
            # Keep consuming (discarding) until the sentinel: the router's
            # bounded put must never deadlock against a dead fold thread.
            while q.get() is not self._SENTINEL:
                pass

    def _fold_one(self, s: int, shard, item) -> None:
        kind = item[0]
        if kind == "raw":
            # Pre-partitioned native scan: rows [lo, hi) and one
            # contiguous word-bytes span belong to this shard.
            _, raw, ends, keys, lo, hi, mask = item
            if hi <= lo:
                return
            base = int(ends[lo - 1]) if lo else 0
            raw_s = raw[base:int(ends[hi - 1])]
            ends_s = ends[lo:hi] - base
            keys_s = keys[lo:hi]
            if self._sanitize:
                from mapreduce_rust_tpu.analysis.sanitize import (
                    check_shard_route,
                )

                check_shard_route(keys_s, self.n, s)
            mask_s = mask[lo:hi] if mask is not None else None
            fold_scan_into_dictionary(shard, mask_s, "raw",
                                      (raw_s, ends_s, keys_s))
        else:
            # Python-fallback scan: no pre-partitioning, so every shard
            # thread selects its own keys from the shared result — the
            # per-word slicing cost parallelizes across shards exactly
            # like the fold it feeds.
            _, words, keys, mask = item
            from mapreduce_rust_tpu.runtime.dictionary import (
                shard_ids_of_packed,
            )

            packed = (
                keys[:, 0].astype(np.uint64) << np.uint64(32)
            ) | keys[:, 1].astype(np.uint64)
            sel = shard_ids_of_packed(packed, self.n) == np.uint64(s)
            if mask is not None:
                sel &= mask
            idx = np.nonzero(sel)[0].tolist()
            if idx:
                shard.add_scanned([words[i] for i in idx], keys[idx])

    # ---- router side ----

    def _raise_error(self) -> None:
        for e in self.errors:
            if e is not None:
                raise e
        raise RuntimeError("fold plane poisoned without a recorded error")

    def _put(self, s: int, item) -> None:
        import queue as _queue

        if self.poisoned.is_set():
            self._raise_error()
        q = self.queues[s]
        try:
            q.put_nowait(item)
            return
        except _queue.Full:
            pass
        # Blocked = fold backpressure: timed separately from glue so the
        # bottleneck attribution can say "the fold is the ceiling".
        t0 = time.perf_counter()
        try:
            with trace_span("host_map.fold_stall", shard=s):
                while True:
                    if self.poisoned.is_set():
                        self._raise_error()
                    try:
                        q.put(item, timeout=0.05)
                        return
                    except _queue.Full:
                        continue
        finally:
            self.stall_s += time.perf_counter() - t0

    def route_raw(self, raw, ends, keys, shard_counts, mask) -> None:
        """Hand each shard its slice of one pre-partitioned scan result.
        O(shards) router work per window — the per-word routing loop this
        PR deletes lives in the native kernel now."""
        cum = 0
        for s, c in enumerate(shard_counts.tolist()):
            lo, hi = cum, cum + c
            cum = hi
            if c:
                self._put(s, ("raw", raw, ends, keys, lo, hi, mask))

    def route_list(self, words, keys, mask) -> None:
        for s in range(self.n):
            self._put(s, ("list", words, keys, mask))

    def finish(self) -> None:
        """Clean end-of-stream: sentinel every queue, join every thread,
        surface any fold error — called AFTER the last scan result was
        routed, so the teardown order is router → fold threads → (the
        caller's) device merge drain."""
        if self._finished:
            return
        self._finished = True
        t0 = time.perf_counter()
        for q in self.queues:
            q.put(self._SENTINEL)
        for t in self.threads:
            t.join()
        self.stall_s += time.perf_counter() - t0
        if self.poisoned.is_set():
            self._raise_error()

    def abort(self) -> None:
        """Exception-path teardown: poison (fold threads discard their
        backlog), force a sentinel past a full queue by displacing one
        item, reap the threads. Idempotent, never raises, never blocks
        forever."""
        import queue as _queue

        self.poisoned.set()
        if self._finished:
            return  # finish() already joined the threads
        self._finished = True
        for q in self.queues:
            while True:
                try:
                    q.put_nowait(self._SENTINEL)
                    break
                except _queue.Full:
                    try:
                        q.get_nowait()
                    except _queue.Empty:
                        pass
        for t in self.threads:
            t.join(timeout=10)

    def collect(self, stats: JobStats) -> None:
        """Fold the per-thread tallies into JobStats — router thread only,
        after finish/abort joined the threads, so no write races exist
        (and the sanitizer's single-writer contract holds)."""
        stats.fold_s = sum(self.fold_s)
        stats.fold_stall_s = self.stall_s
        stats.fold_shard_s = [round(v, 6) for v in self.fold_s]
        stats.fold_shard_idle_s = [round(v, 6) for v in self.idle_s]
        agg = stats.hists.get("host_map.fold_s")
        if agg is None:
            agg = stats.hists["host_map.fold_s"] = Histogram()
        for h in self.hists:
            if h.count:
                agg.merge(h)


_CUT_PROBE = 1 << 16  # how far back a window cut searches for whitespace


def _iter_windows(cfg: Config, inputs, stats):
    """(doc_id, raw window view) stream — ZERO-COPY uint8 views over each
    memory-mapped input, cut at ASCII whitespace (safe before
    normalization — normalize never alters ASCII bytes). Only the last
    _CUT_PROBE bytes of a window are materialized to find the cut; a
    window whose final 64 KB contains no whitespace is force-cut at a
    UTF-8 sequence boundary and counted in stats.forced_cuts (the device
    chunker's policy; note its force threshold is a whole chunk, but any
    token past _CUT_PROBE already exceeds the tokenizer's max_word_len by
    three orders of magnitude). No read-ahead thread: the page-faulting
    sequential read happens inside the GIL-free native scan, which the
    engine already overlaps with the Python glue."""
    from mapreduce_rust_tpu.runtime.chunker import _ws_cut, utf8_safe_cut

    for doc_id, path in enumerate(inputs):
        size = os.path.getsize(path)
        stats.bytes_in += size
        if size == 0:
            continue
        mm = np.memmap(path, dtype=np.uint8, mode="r")
        try:  # sequential readahead: fault whole extents, not page by page
            import mmap as _mmap

            mm._mmap.madvise(_mmap.MADV_SEQUENTIAL)
        except (AttributeError, OSError, ValueError):
            pass
        start = 0
        while start < size:
            end = min(start + cfg.host_window_bytes, size)
            if end < size:
                probe_at = max(start, end - _CUT_PROBE)
                tail = mm[probe_at:end].tobytes()
                off, forced = _ws_cut(tail, 0, len(tail))
                if forced:
                    stats.forced_cuts += 1
                    off = utf8_safe_cut(tail, off)
                cut = probe_at + off
            else:
                cut = end
            yield doc_id, mm[start:cut]
            start = cut


def _py_scan_count(window: bytes):
    """Pure-Python fallback for scan_count_raw (no native toolchain):
    exact, an order of magnitude slower. The window is RAW bytes, so it
    normalizes first — the fused C pass does both in one sweep."""
    from mapreduce_rust_tpu.core.hashing import hash_words
    from mapreduce_rust_tpu.core.normalize import normalize_unicode
    from mapreduce_rust_tpu.runtime.dictionary import extract_words

    counter = collections.Counter(extract_words(normalize_unicode(bytes(window))))
    words = list(counter.keys())
    keys = hash_words(words)
    counts = np.asarray([counter[w] for w in words], dtype=np.uint32)
    return words, keys, counts


def _stream_host_map(cfg: Config, app: App, inputs, stats, acc, dictionary,
                     doc_id_offset: int = 0) -> None:
    """The host-map engine: one fused native pass per window tokenizes,
    dedupes, hashes and counts on the host — the very scan that feeds the
    egress dictionary — and the device merges the compacted updates. The
    map lives where the reference's map lives (the worker CPU,
    src/app/wc.rs:6-13); the framework's added value is the device-side
    combine/merge/shuffle state machine behind it. End-to-end this beats
    the device-tokenize engine whenever host→device bandwidth, not
    compute, is the ceiling (measured: a tunneled v5e moves ~60 MB/s of
    chunk bytes but >100 MB/s of text through the host scan, whose updates
    are 10-30× smaller than the text).

    The scan fans out (ISSUE 2 tentpole): ``cfg.host_map_workers`` threads
    (auto = usable cores, one reserved for this consumer) run the GIL-releasing native scan concurrently —
    per-thread scratch arenas already isolate them (native/host._buffers)
    — while THIS thread, the single consumer, folds results into the
    dictionary and dispatches packed merges strictly IN WINDOW ORDER, so
    outputs are bit-identical for any worker count. In-flight scans are
    bounded (a small multiple of the worker count), so host memory stays
    flat: O(workers) arenas + O(budget) scanned updates + O(depth) device
    buffers, never O(corpus). The scan workers are PURE functions of their
    window — all shared state (stats, dictionary, device stream) is
    touched only here, which is also what makes teardown safe: an orphaned
    scan can finish into the void without racing the unwound stream.

    The FOLD fans out too (ISSUE 9 tentpole): with a ShardedDictionary the
    consumer becomes a ROUTER — the native scan returns each window
    pre-partitioned by key-hash shard (one contiguous slice per shard),
    the router hands shard s its slice over a bounded queue, and S fold
    threads (each the sole owner of one shard dictionary) fold in window
    order. The device merge stream is scattered back to EXACT scan order
    first, so merges, evictions and therefore outputs and spill totals are
    bit-identical for every (host_map_workers, fold_shards) combination —
    the same contract the scan fan-out holds for worker counts."""
    from mapreduce_rust_tpu.native import host as native_host
    from mapreduce_rust_tpu.native.host import (
        scan_count_raw,
        scan_count_sharded_raw,
    )

    enable_compilation_cache(cfg.compilation_cache_dir)
    device = select_device(cfg.device)
    workers = cfg.effective_host_map_workers()
    stats.host_map_workers = workers
    fold_n = (
        dictionary.n_shards if isinstance(dictionary, ShardedDictionary) else 1
    )
    stats.fold_shards = fold_n
    fold: "_FoldShardPlane | None" = None  # started right before the
    # stream loop's try block — device setup below can raise, and fold
    # threads started earlier would leak, blocked forever on q.get()
    # The dispatch plane (ISSUE 13) owns the device state, the pending
    # merges and their drain: the router below never books a device hop.
    dispatch = _DispatchPlane(cfg, app, stats, acc, dictionary, device)
    # Provenance (ISSUE 20): digest each window on ITS scan thread (the
    # bytes are hot there), record on the consumer — in window order, so
    # the ledger is identical for any (workers, shards) combination.
    from mapreduce_rust_tpu.runtime.lineage import active_ledger, chunk_digest

    ledger = active_ledger()
    lineage_range = app.partition_mode == "range"

    def lineage_record(doc_id, lin, keys, mask) -> None:
        if lin is None:
            return
        dg, nb = lin
        ledger.record_chunk(
            doc_id, nb, dg,
            parts=_routed_parts(keys, mask, cfg.reduce_n, lineage_range),
        )

    def scan_window(item):
        # PURE: reads its window, returns its result + its own duration.
        # No shared-state writes off the consumer thread — N of these run
        # concurrently, and an abandoned one (exception teardown) cannot
        # mutate stats after the stream has unwound.
        doc_id, window = item
        t0 = time.perf_counter()
        with trace_span("host_map.scan", doc=doc_id, bytes=int(window.size)):
            if fold is not None:
                # Sharded fold: the native kernel pre-partitions the scan
                # result by key-hash shard in the same fused pass.
                res = scan_count_sharded_raw(window, fold.n)
                out = (
                    (doc_id, "raw_sharded", res) if res is not None
                    else (doc_id, "py", _py_scan_count(window))
                )
            else:
                res = scan_count_raw(window)
                out = (
                    (doc_id, "raw", res) if res is not None
                    else (doc_id, "py", _py_scan_count(window))
                )
            # Digest AFTER the scan: the scan just faulted every window
            # page in, so the sampled blake2b reads hot memory instead of
            # paying the memmap's cold-page latency itself.
            lin = (
                (chunk_digest(window), int(window.size))
                if ledger is not None else None
            )
        return (*out, lin, time.perf_counter() - t0)

    def consume(result) -> None:
        doc_id, kind, res, lin, scan_s = result
        stats.host_map_s += scan_s  # aggregate scan seconds across workers
        # Per-window scan distribution: a high-cardinality window shows up
        # as a p99 tail here long before it moves the aggregate (ISSUE 5).
        stats.record_hist("host_map.scan_s", scan_s)
        t_glue = time.perf_counter()
        stall0 = fold.stall_s if fold is not None else 0.0
        dstall0 = dispatch.stall_s
        dwait0 = dispatch.device_wait_s
        with trace_span("host_glue"):
            stats.chunks += 1
            if kind == "raw_sharded":
                # Sharded fold (ISSUE 9): route each shard its
                # pre-partitioned slice — O(shards) router work, the fold
                # threads do the word-level folding. The scan-order
                # scatter-back for the device merge moved to the dispatch
                # plane (ISSUE 13): the router hands the grouped arrays +
                # permutation over and is done in O(1).
                raw, ends, keys, counts, pos, shard_counts = res
                mask = app.host_mask(keys)  # grouped rows; per-row exact
                lineage_record(doc_id_offset + doc_id, lin, keys, mask)
                fold.route_raw(raw, ends, keys, shard_counts, mask)
                dispatch.submit(
                    (doc_id_offset + doc_id, "sharded", keys, counts, pos,
                     mask)
                )
            elif kind == "raw":
                raw, ends, keys, counts = res
                mask = app.host_mask(keys)
                lineage_record(doc_id_offset + doc_id, lin, keys, mask)
                fold_scan_into_dictionary(dictionary, mask, "raw", (raw, ends, keys))
                dispatch.submit(
                    (doc_id_offset + doc_id, "flat", keys, counts, None,
                     mask)
                )
            else:
                words, keys, counts = res
                mask = app.host_mask(keys)
                lineage_record(doc_id_offset + doc_id, lin, keys, mask)
                if fold is not None:
                    # Python-fallback scan has no pre-partitioning: the
                    # whole (read-only) result fans out and each shard
                    # thread selects its own keys.
                    fold.route_list(words, keys, mask)
                else:
                    fold_scan_into_dictionary(dictionary, mask, "list", (words, keys))
                dispatch.submit(
                    (doc_id_offset + doc_id, "flat", keys, counts, None,
                     mask)
                )
        # Glue accounting: time the router spent BLOCKED on full shard or
        # dispatch queues is backpressure (fold_stall_s /
        # dispatch_stall_s), not glue — subtracted so glue keeps meaning
        # "router's own work". In SYNC dispatch mode the inline dispatch
        # runs inside the glue span exactly as PR 10 booked it (that is
        # the A/B: sync shows the device hops in glue, async doesn't) —
        # only the drain's blocking readback is subtracted, which
        # device_wait_s already owns.
        glue_dt = time.perf_counter() - t_glue
        if fold is not None:
            glue_dt = max(glue_dt - (fold.stall_s - stall0), 0.0)
        if dispatch.sync:
            glue_dt = max(
                glue_dt - (dispatch.device_wait_s - dwait0), 0.0
            )
        else:
            glue_dt = max(glue_dt - (dispatch.stall_s - dstall0), 0.0)
        stats.host_glue_s += glue_dt
        stats.record_hist("host_map.glue_s", glue_dt)
        if fold is not None:
            # Publish the running fold totals per window (router thread):
            # the plane's tallies are plane-local until collect(), and the
            # live ring / renewal-envelope / streaming-doctor series would
            # otherwise read 0 for the whole run — a fold-bound job must
            # name host-fold LIVE, not just post-mortem. Reading the fold
            # threads' float cells is benign (slightly stale at worst);
            # collect() writes the exact finals at teardown.
            stats.fold_s = sum(fold.fold_s)
            stats.fold_stall_s = fold.stall_s
        # Running dispatch totals, same contract (ISSUE 13): a
        # dispatch-bound job must name merge-dispatch in the live ring.
        dispatch.publish_live(stats)
        # Running spill totals, same live-publication contract as fold_s:
        # a spill-bound job must name "spill" in the live ring, not just
        # in the post-mortem manifest (ISSUE 11).
        _publish_spill_live(stats, dictionary, acc)
        maybe_snapshot()  # flight-recorder tick: per window, consumer thread
        metrics_tick()    # live-metrics sampler, same piggyback contract

    from concurrent.futures import ThreadPoolExecutor

    # In-flight budget: each submitted-but-unconsumed scan pins one memmap
    # window plus (once done) its compacted result, so 2×workers + 2 keeps
    # every worker busy while the consumer works through the ordered head —
    # deep enough to ride out a slow (high-cardinality) window, shallow
    # enough that memory stays flat.
    inflight: collections.deque = collections.deque()
    budget = 2 * workers + 2

    def next_result():
        fut = inflight.popleft()
        t0 = time.perf_counter()
        with trace_span("host_map.stall"):
            res = fut.result()
        dt = time.perf_counter() - t0
        stats.scan_wait_s += dt
        stats.record_hist("host_map.stall_s", dt)
        trace_counter("host_map.inflight", scans=len(inflight),
                      merges=len(dispatch.pending))  # benign-stale len read
        return res

    pool = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="mr/scan")
    if fold_n > 1:
        # Started HERE, not at function entry: everything that can raise
        # during setup (device selection/state allocation, pool creation)
        # is behind us, and the very next statement is the try whose
        # except/finally owns the plane's teardown — no window where an
        # exception strands S fold threads on q.get(). The dispatch plane
        # started earlier (its ctor allocates device state), so a fold
        # ctor failure must unwind it.
        try:
            fold = _FoldShardPlane(cfg, stats, dictionary.shards)
        except BaseException:
            dispatch.abort()
            raise
    try:
        for item in _iter_windows(cfg, inputs, stats):
            inflight.append(pool.submit(scan_window, item))
            if len(inflight) >= budget:
                consume(next_result())
        while inflight:
            consume(next_result())
        stats.host_arena_bytes = native_host.arena_bytes()
        if fold is not None:
            # Teardown ORDER (ISSUE 9 satellite): the router is fully
            # drained (every scan result routed above), THEN the fold
            # threads flush and join, THEN the dispatch plane flushes its
            # staging buffer + drains the device merges — each stage's
            # producers are gone before it stops. A fold error recorded
            # mid-stream surfaces here (or at the route that first
            # observed the poison).
            fold.finish()
        dispatch.finish()
    except BaseException:
        if fold is not None:
            fold.abort()
        dispatch.abort()
        raise
    finally:
        if fold is not None:
            fold.collect(stats)  # threads joined by finish()/abort()
        dispatch.collect(stats)  # same doctrine: joined before collect
        # cancel_futures + wait (the old wait=False shutdown abandoned an
        # in-flight scan on exception: the orphaned future kept its memmap
        # window alive past the stream's unwind — ISSUE 2 satellite).
        # Queued futures cancel; the ≤ workers running scans finish their
        # pure work and are reaped before the stream frame exits.
        pool.shutdown(wait=True, cancel_futures=True)
    acc.add_batch(dispatch.state)


def _ckpt_paths(cfg: Config) -> tuple[str, str]:
    return (
        os.path.join(cfg.work_dir, "driver.ckpt.npz"),
        os.path.join(cfg.work_dir, "driver.ckpt.dict"),
    )


def _job_fingerprint(cfg: Config, app: App, inputs, d: int) -> str:
    """Ties a checkpoint to (inputs, app, every shape-determining knob): a
    mismatch on resume is silently ignored, never trusted."""
    import hashlib

    h = hashlib.sha256()
    for p in inputs:
        st = os.stat(p)
        h.update(f"{p}:{st.st_size}:{st.st_mtime_ns};".encode())
    # state-v2: merge_batches now REQUIRES a sorted state (rank-merge); a
    # checkpoint from the validity-only-clamp era can hold mid-array
    # SENTINEL holes, which would silently mis-merge — reject it.
    h.update(
        f"state-v2:{app.name}:{app.combine_op}:{cfg.chunk_bytes}:{d}:"
        f"{cfg.effective_partial_capacity()}:{cfg.merge_capacity}".encode()
    )
    return h.hexdigest()


def _write_ckpt(cfg: Config, fingerprint: str, state: KVBatch, groups_done: int,
                acc, dictionary, stats) -> None:
    """Atomic driver checkpoint: device state + host spill accumulator +
    progress in one npz (the commit point), dictionary beside it. The
    dictionary file renames FIRST: its content only ever grows, so a
    newer-than-npz dictionary is a superset — safe — while the npz commit
    guarantees a complete dictionary exists. This is the single-process
    mesh driver's equivalent of the control plane's spill-file checkpoints
    + fingerprinted journal (coordinator/server.py, worker/runtime.py)."""
    npz_path, dict_path = _ckpt_paths(cfg)
    os.makedirs(cfg.work_dir, exist_ok=True)
    tmp_d = dict_path + f".{os.getpid()}.tmp"
    dictionary.save(tmp_d)
    os.replace(tmp_d, dict_path)
    k1, k2, value, valid = (np.asarray(x) for x in jax.device_get(tuple(state)))
    acc_keys, acc_vals = acc.snapshot()
    tmp_n = npz_path + f".{os.getpid()}.tmp"
    with open(tmp_n, "wb") as f:
        np.savez(
            f,
            fingerprint=np.frombuffer(fingerprint.encode(), dtype=np.uint8),
            k1=k1, k2=k2, value=value, valid=valid,
            groups_done=np.int64(groups_done),
            acc_keys=acc_keys, acc_vals=acc_vals,
            spill_events=np.int64(stats.spill_events),
            spilled_keys=np.int64(stats.spilled_keys),
        )
    os.replace(tmp_n, npz_path)
    log.info("checkpoint: %d groups done", groups_done)


def _load_ckpt(cfg: Config, fingerprint: str):
    """(state_arrays, groups_done, acc_keys, acc_vals, spill_events,
    spilled_keys, dict_path) or None (absent / torn / different job)."""
    npz_path, dict_path = _ckpt_paths(cfg)
    if not (os.path.exists(npz_path) and os.path.exists(dict_path)):
        return None
    try:
        with np.load(npz_path) as z:
            if bytes(z["fingerprint"]).decode() != fingerprint:
                log.warning("checkpoint fingerprint mismatch — starting fresh")
                return None
            return (
                KVBatch(z["k1"], z["k2"], z["value"], z["valid"]),
                int(z["groups_done"]),
                z["acc_keys"], z["acc_vals"],
                int(z["spill_events"]), int(z["spilled_keys"]),
                dict_path,
            )
    except (OSError, ValueError, KeyError, BadZipFile) as e:
        log.warning("unreadable checkpoint (%s) — starting fresh", e)
        return None


def _stream_multihost(cfg: Config, app: App, inputs, stats, acc, dictionary) -> None:
    """The mesh pipeline over a MULTI-PROCESS (jax.distributed) cluster —
    SURVEY.md §5's comm-backend row closed end-to-end: control stays on the
    coordinator's RPC plane, data rides XLA collectives over ICI/DCN, and
    the shared filesystem carries only egress artifacts (dictionaries and
    partition files), exactly the role it plays for the reference
    (src/mr/worker.rs:117-140) and for this framework's worker spills.

    Per process: ingest ONLY the inputs assigned to it (round-robin by
    global doc id), feed its local chips' rows of each global group via
    make_array_from_process_local_data, and run the same SPMD step programs
    every other process runs. Per-group decisions (replay? continue?) come
    back as psum-REPLICATED flags so every process agrees without any host
    being able to see the whole array. Rounds are lockstep: a process whose
    inputs are exhausted keeps contributing space-padded groups until the
    replicated have-data count reaches zero. At the end each process folds
    only its ADDRESSABLE state/spill shards (its hash classes), publishes
    its dictionary shard, and merges everyone's — so any process can print
    words whose bytes were only ever read by another host."""
    from mapreduce_rust_tpu.parallel.shuffle import (
        AXIS,
        default_bucket_cap,
        local_batch,
        local_rows,
        make_mesh,
        make_mh_shuffle_step_fns,
        make_round_fn,
        sharded_empty_state,
        wire_bytes_per_round,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    if cfg.checkpoint_every_groups or cfg.resume or cfg.sharded_stream:
        raise ValueError(
            "checkpoint/resume and sharded_stream are single-process features"
        )
    enable_compilation_cache(cfg.compilation_cache_dir)
    pid, nproc = jax.process_index(), jax.process_count()
    backend = None if cfg.device == "auto" else cfg.device
    mesh = make_mesh(cfg.mesh_shape, backend)
    d = mesh.devices.size
    d_local = len([dev for dev in mesh.devices.ravel() if dev.process_index == pid])
    if d_local == 0:
        raise RuntimeError("this process owns no devices of the mesh")
    u_cap = cfg.effective_partial_capacity()
    bucket_cap = default_bucket_cap(u_cap, d, cfg.bucket_capacity_factor)
    fast = make_mh_shuffle_step_fns(app, u_cap, bucket_cap, mesh)
    round_fn = make_round_fn(mesh)
    tiers: dict[str, tuple] = {}

    state = sharded_empty_state(mesh, max(cfg.merge_capacity // d, 16))
    in_shard = NamedSharding(mesh, P(AXIS))
    flag_shard = NamedSharding(mesh, P(AXIS))

    # Inputs round-robin by GLOBAL doc id, so inverted_index doc ids match
    # a single-process run over the same sorted listing.
    my_inputs = [(i, p) for i, p in enumerate(inputs) if i % nproc == pid]
    ingest = _IngestStream(
        cfg, [p for _i, p in my_inputs], stats, dictionary,
        doc_ids=[i for i, _p in my_inputs], host_mask=app.host_mask,
        lineage_range=app.partition_mode == "range",
    )

    def to_global(local_np: np.ndarray, global_shape):
        return jax.make_array_from_process_local_data(
            in_shard, local_np, global_shape=global_shape
        )

    def fold_local_spill(ev_local: np.ndarray, evicted) -> None:
        n = int(ev_local.sum())
        if n > 0:
            stats.spill_events += 1
            stats.spilled_keys += n
            acc.add_batch(local_batch(evicted))

    def run_round(chunks_np: np.ndarray, docs_np: np.ndarray, have: int) -> bool:
        nonlocal state
        chunks_g = to_global(chunks_np, (d, cfg.chunk_bytes))
        docs_g = jax.make_array_from_process_local_data(
            flag_shard, docs_np, global_shape=(d,)
        )
        stats.mesh_rounds += 1
        stats.shuffle_wire_bytes += wire_bytes_per_round(d, bucket_cap)
        with _a2a_span(stats, round=stats.mesh_rounds, tier="fast",
                       wire_bytes=wire_bytes_per_round(d, bucket_cap)):
            local, bad_p, bad_b = fast[0](chunks_g, docs_g)
            state, evicted, ev_counts = fast[1](state, local)
            flags = round_fn(
                jax.make_array_from_process_local_data(
                    flag_shard, np.full(d_local, have, dtype=np.int32), global_shape=(d,)
                )
            )
        # ONE batched fetch per round: the replicated flags (any local
        # shard holds the global value) AND this process's spill counts —
        # every separate blocking read is a full round trip.
        t0 = time.perf_counter()
        with trace_span("device.drain", steps=1):
            got = jax.device_get(
                [x.addressable_shards[0].data for x in (bad_p, bad_b, flags)]
                + [s.data for s in ev_counts.addressable_shards]
            )
        dt = time.perf_counter() - t0
        stats.device_wait_s += dt
        stats.record_hist("device.drain_s", dt)
        bad_p_l, bad_b_l, flags_l = got[:3]
        ev_local = np.concatenate([np.asarray(x).reshape(-1) for x in got[3:]])
        bad_p_n = int(np.asarray(bad_p_l)[0])
        bad_b_n = int(np.asarray(bad_b_l)[0])
        if bad_p_n > 0 or bad_b_n > 0:
            if bad_p_n > 0:
                stats.partial_overflow_replays += 1
                if "full" not in tiers:
                    tiers["full"] = make_mh_shuffle_step_fns(
                        app, cfg.chunk_bytes, cfg.chunk_bytes, mesh
                    )
                fns, tier_cap = tiers["full"], cfg.chunk_bytes
            else:
                stats.bucket_skew_replays += 1
                if "skew" not in tiers:
                    tiers["skew"] = make_mh_shuffle_step_fns(app, u_cap, u_cap, mesh)
                fns, tier_cap = tiers["skew"], u_cap
            stats.mesh_rounds += 1
            stats.shuffle_wire_bytes += wire_bytes_per_round(d, tier_cap)
            with _a2a_span(stats, round=stats.mesh_rounds, tier="replay",
                           wire_bytes=wire_bytes_per_round(d, tier_cap)):
                local, _p, _b = fns[0](chunks_g, docs_g)
                state, evicted2, ev2 = fns[1](state, local)
            # Fetch + fold outside the a2a block (rare: own fetch) — the
            # blocking shard read must not inflate all_to_all_s.
            t0 = time.perf_counter()
            with trace_span("device.drain", steps=1):
                ev2_local = local_rows(ev2)
            stats.device_wait_s += time.perf_counter() - t0
            fold_local_spill(ev2_local, evicted2)
        fold_local_spill(ev_local, evicted)
        return int(np.asarray(flags_l)[0]) > 0

    it = iter(ingest)
    exhausted = False
    try:
        while True:
            rows: list[np.ndarray] = []
            docs: list[int] = []
            while not exhausted and len(rows) < d_local:
                try:
                    chunk = next(it)
                    rows.append(chunk.data)
                    docs.append(chunk.doc_id)
                except StopIteration:
                    exhausted = True
            have = 1 if rows else 0
            while len(rows) < d_local:  # pad my contribution with spaces
                rows.append(np.full(cfg.chunk_bytes, 0x20, dtype=np.uint8))
                docs.append(0)
            any_data = run_round(
                np.stack(rows), np.asarray(docs, dtype=np.int32), have
            )
            if not any_data:
                break
    except BaseException:
        ingest.close(abort=True)
        raise
    ingest.close()
    acc.add_batch(local_batch(state))

    # Dictionary exchange over the shared work dir: each process publishes
    # its shard + a done marker, then merges everyone's (a chip may own
    # keys whose word bytes were only read by another process). Filenames
    # embed the job fingerprint so a leftover marker from a DIFFERENT job
    # in the same work dir can never satisfy — or break — the barrier;
    # a leftover from the SAME job is the same corpus, hence the same
    # shard content. (`clean` removes dict-* including markers.)
    # nproc is part of the name: same inputs + same d under a different
    # process split produce different shards, and stale ones must not
    # satisfy (or poison) the barrier.
    fp = f"{_job_fingerprint(cfg, app, inputs, d)[:16]}-n{nproc}"

    def shard_path(proc: int) -> str:
        return os.path.join(cfg.work_dir, f"dict-proc-{proc}-{fp}.txt")

    os.makedirs(cfg.work_dir, exist_ok=True)
    tmp = shard_path(pid) + ".tmp"
    dictionary.save(tmp)
    os.replace(tmp, shard_path(pid))
    open(shard_path(pid) + ".done", "w").close()
    _await_shard_files(shard_path, nproc, cfg.multihost_barrier_timeout_s)
    for other in range(nproc):
        if other != pid:
            dictionary.merge(Dictionary.load(shard_path(other)))


def _await_shard_files(shard_path, nproc: int, timeout_s: float) -> None:
    """The multihost dictionary-exchange barrier: wait for every process's
    published shard + done marker. A peer that died before publishing
    cannot be waited out — its chips' hash classes died with it — so the
    only honest outcome is a loud, prompt failure naming every missing
    rank (the timeout is a knob: slow shared filesystems legitimately
    need more than the default)."""
    deadline = time.monotonic() + timeout_s  # immune to wall-clock steps
    waiting = set(range(nproc))
    while waiting:
        waiting -= {
            other for other in waiting
            if os.path.exists(shard_path(other) + ".done")
            and os.path.exists(shard_path(other))
        }
        if not waiting:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"dictionary shards from process(es) {sorted(waiting)} never "
                f"arrived within {timeout_s:.0f}s (multihost_barrier_timeout_s)"
                " — peer death or a stalled shared work dir; results would be"
                " missing those hash classes, so the job fails instead."
                " Re-run the job."
            )
        time.sleep(0.05)


def _finish_mesh_state(app: App, mesh, state, stats, acc) -> None:
    """Fold the final sharded state into the host accumulator. Top-k apps
    fetch only per-chip candidates over ICI (parallel/topk.py) when that
    is provably exact: no spills (a spilled key's device value is partial)
    and no value tie at any chip's k boundary (the word tie-break needs
    bytes the device doesn't have)."""
    from mapreduce_rust_tpu.parallel.shuffle import shard_fill_counts

    try:
        # Per-chip final distinct-key counts: the hash-class skew signal
        # the doctor scores (a hot shard here means one chip's merge and
        # egress carry the job). One readback at finalize, off the stream.
        stats.mesh_shard_rows = shard_fill_counts(state)
    except Exception:
        pass  # telemetry stays best-effort
    k = app.device_select_k
    if k and stats.spill_events == 0:
        from mapreduce_rust_tpu.parallel.topk import topk_candidates

        res = topk_candidates(mesh, state, k)
        if res is not None:
            keys, vals = res
            acc.add(keys, vals)
            log.info("device top-%d selection: %d candidates fetched", k, len(vals))
            return
        log.info("device top-%d selection ambiguous (value tie at boundary) "
                 "— falling back to full state fetch", k)
    acc.add_batch(state)


def _stream_sharded(cfg: Config, app: App, inputs, stats, acc, dictionary) -> None:
    """Sequence-parallel mesh ingestion: each normalized window rides the
    mesh as ONE contiguous byte stream cut at arbitrary — mid-word, even
    mid-UTF-8-sequence — equal offsets, one shard per chip. A ppermute
    halo exchange (parallel/halo.py) hashes straddling tokens exactly once
    (owned by the chip where the token ENDS), then the records take the
    standard combine → bucket scatter → all_to_all → merge pipeline. This
    is SURVEY.md §5's long-context row made end-to-end: the reference's
    sequence ceiling is one whole file in one String per task
    (src/mr/worker.rs:65-77); here no chip ever needs a token-aligned —
    or even character-aligned — view of the stream.

    Tokens longer than the halo (cfg.max_word_len) may hash truncated;
    they are DETECTED on device and counted in stats.halo_truncations,
    this framework's standard posture for capacity faults."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mapreduce_rust_tpu.core.normalize import normalize_unicode
    from mapreduce_rust_tpu.native.host import normalize_native
    from mapreduce_rust_tpu.parallel.halo import make_sharded_tokenizer, shard_stream
    from mapreduce_rust_tpu.parallel.shuffle import (
        AXIS,
        default_bucket_cap,
        make_kv_shuffle_step_fns,
        make_mesh,
        make_shuffle_step_fns,
        sharded_empty_state,
        wire_bytes_per_round,
    )

    if cfg.checkpoint_every_groups or cfg.resume:
        raise ValueError(
            "checkpoint/resume is not supported in sharded-stream mode "
            "(use the chunked mesh path, or run without sharded_stream)"
        )
    enable_compilation_cache(cfg.compilation_cache_dir)
    backend = None if cfg.device == "auto" else cfg.device
    mesh = make_mesh(cfg.mesh_shape, backend)
    d = mesh.devices.size
    u_cap = cfg.effective_partial_capacity()
    bucket_cap = default_bucket_cap(u_cap, d, cfg.bucket_capacity_factor)
    tokenize = make_sharded_tokenizer(mesh, halo=cfg.max_word_len)
    kv_shuffle = make_kv_shuffle_step_fns(app, u_cap, bucket_cap, mesh)
    merge = make_shuffle_step_fns(app, u_cap, bucket_cap, mesh)[1]
    wide: dict = {}  # lazily-compiled full-width replay tier

    state = sharded_empty_state(mesh, max(cfg.merge_capacity // d, 16))
    in_shard = NamedSharding(mesh, P(AXIS))
    rep = NamedSharding(mesh, P(AXIS))
    depth = max(max(cfg.pipeline_depth, 1) // d, 4)
    pending: collections.deque = collections.deque()
    shard_bytes = max(cfg.chunk_bytes, 2 * cfg.max_word_len + 8)

    def replay_group(group_bytes: bytes, doc_id: int, p_n: int) -> None:
        # The fast path clamped the whole group to empty on device, so
        # re-run it through the full-width tier (u_cap = the whole token
        # window, bucket_cap = u_cap — overflow structurally impossible)
        # and merge that. Exact, never silent, like every capacity fault.
        nonlocal state
        stats.partial_overflow_replays += int(p_n > 0)
        stats.bucket_skew_replays += int(p_n == 0)
        if not wide:
            w_cap = cfg.max_word_len + shard_bytes + 1  # the full window
            wide["fns"] = make_kv_shuffle_step_fns(app, w_cap, w_cap, mesh)
            wide["merge"] = make_shuffle_step_fns(app, w_cap, w_cap, mesh)[1]
        shards = jax.device_put(shard_stream(group_bytes, mesh, pad=shard_bytes), in_shard)
        docs = jax.device_put(np.full(d, doc_id, dtype=np.int32), rep)
        stats.mesh_rounds += 1
        stats.shuffle_wire_bytes += wire_bytes_per_round(
            d, cfg.max_word_len + shard_bytes + 1
        )
        with _a2a_span(stats, round=stats.mesh_rounds, tier="replay",
                       wire_bytes=wire_bytes_per_round(
                           d, cfg.max_word_len + shard_bytes + 1)):
            kv, _trunc = tokenize(shards)
            local, _p, _b = wide["fns"](kv, docs)
            state, evicted, ev_counts = wide["merge"](state, local)
        # Readback + spill fold outside the a2a block — see _stream_mesh
        # replay_group: all_to_all_s must stay interconnect-attributable.
        t0 = time.perf_counter()
        with trace_span("device.drain", steps=1):
            ev_n = int(np.asarray(jax.device_get(ev_counts)).sum())
        stats.device_wait_s += time.perf_counter() - t0
        if ev_n > 0:
            stats.spill_events += 1
            stats.spilled_keys += ev_n
            acc.add_batch(evicted)

    def drain(n: int) -> None:
        if n <= 0:
            return
        batch = [pending.popleft() for _ in range(n)]
        t0 = time.perf_counter()
        with trace_span("device.drain", steps=n):
            flat = jax.device_get([x for row in batch for x in row[:4]])
        dt = time.perf_counter() - t0
        stats.device_wait_s += dt
        stats.record_hist("device.drain_s", dt)
        _sample_device_memory(stats)
        for row, trunc, p_ovf, b_ovf, ev in zip(
            batch, flat[::4], flat[1::4], flat[2::4], flat[3::4]
        ):
            stats.halo_truncations += int(np.asarray(trunc).sum())
            ev_n = int(np.asarray(ev).sum())
            if ev_n > 0:
                stats.spill_events += 1
                stats.spilled_keys += ev_n
                with trace_span("spill", keys=ev_n):
                    acc.add_batch(row[4])
            p_n = int(np.asarray(p_ovf).sum())
            b_n = int(np.asarray(b_ovf).sum())
            if p_n or b_n:
                replay_group(row[5], row[6], p_n)

    from mapreduce_rust_tpu.runtime.lineage import active_ledger, chunk_digest

    ledger = active_ledger()
    lineage_range = app.partition_mode == "range"
    for doc_id, window in _iter_windows(cfg, inputs, stats):
        stats.chunks += 1
        raw = bytes(window)
        norm = normalize_native(raw)
        if norm is None:
            norm = normalize_unicode(raw)
        kind, *scan = _scan_payload(norm)
        keys = scan_keys(kind, scan)
        mask = app.host_mask(keys)
        fold_scan_into_dictionary(dictionary, mask, kind, scan)
        if ledger is not None:
            # Digest the RAW window (pre-normalization) — same bytes the
            # other engines hash, so corpus digests agree across engines.
            ledger.record_chunk(
                doc_id, len(raw), chunk_digest(raw),
                parts=_routed_parts(keys, mask, cfg.reduce_n, lineage_range),
            )
        # Group seams are host-side cuts like window seams, so they align
        # to whitespace — a token split THERE would fragment into keys no
        # dictionary entry matches. The arbitrary (mid-word) cuts this
        # mode demonstrates are the D-1 chip seams inside each group,
        # which the halo exchange repairs on device.
        from mapreduce_rust_tpu.runtime.chunker import _ws_cut

        off = 0
        while off < len(norm):
            end = min(off + d * shard_bytes, len(norm))
            if end < len(norm):
                probe = norm[max(off, end - cfg.max_word_len - 1) : end]
                o, forced = _ws_cut(probe, 0, len(probe))
                if forced:
                    stats.forced_cuts += 1
                else:
                    end -= len(probe) - o
            group = norm[off:end]
            off = end
            stats.mesh_rounds += 1
            stats.shuffle_wire_bytes += wire_bytes_per_round(d, bucket_cap)
            with _a2a_span(stats, round=stats.mesh_rounds, tier="fast",
                           wire_bytes=wire_bytes_per_round(d, bucket_cap)):
                shards = jax.device_put(
                    shard_stream(group, mesh, pad=shard_bytes), in_shard
                )
                docs = jax.device_put(
                    np.full(d, doc_id, dtype=np.int32), rep
                )
                kv, trunc = tokenize(shards)
                local, p_ovf, b_ovf = kv_shuffle(kv, docs)
                state, evicted, ev_counts = merge(state, local)
                pending.append((trunc, p_ovf, b_ovf, ev_counts, evicted, group, doc_id))
            if len(pending) >= 2 * depth:
                drain(depth)
    drain(len(pending))
    _finish_mesh_state(app, mesh, state, stats, acc)


def _stream_mesh(cfg: Config, app: App, inputs, stats, acc, dictionary) -> None:
    """Group-of-D-chunks pipeline over the 1-D mesh (parallel/shuffle.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mapreduce_rust_tpu.parallel.shuffle import (
        AXIS,
        default_bucket_cap,
        make_mesh,
        make_shuffle_step_fns,
        sharded_empty_state,
        wire_bytes_per_round,
    )

    enable_compilation_cache(cfg.compilation_cache_dir)
    backend = None if cfg.device == "auto" else cfg.device
    mesh = make_mesh(cfg.mesh_shape, backend)
    d = mesh.devices.size
    u_cap = cfg.effective_partial_capacity()
    bucket_cap = default_bucket_cap(u_cap, d, cfg.bucket_capacity_factor)
    fast = make_shuffle_step_fns(app, u_cap, bucket_cap, mesh)
    tiers: dict[str, tuple] = {}  # lazily-compiled exact replay paths

    state = sharded_empty_state(mesh, max(cfg.merge_capacity // d, 16))
    in_shard = NamedSharding(mesh, P(AXIS))
    # Each in-flight group pins d chunk-sized host arrays for the rare
    # replay, so scale the window down by d to keep pending memory at the
    # same O(depth × chunk_bytes) the single-chip path pays.
    depth = max(max(cfg.pipeline_depth, 1) // d, 4)
    pending: collections.deque = collections.deque()

    fingerprint = _job_fingerprint(cfg, app, inputs, d)
    groups_done = 0
    skip_chunks = 0
    if cfg.resume:
        ck = _load_ckpt(cfg, fingerprint)
        if ck is not None:
            st_host, groups_done, ak, av, sev, skk, dict_path = ck
            state = jax.device_put(st_host, NamedSharding(mesh, P(AXIS, None)))
            skip_chunks = groups_done * d
            acc.add(ak, av)
            dictionary.merge(Dictionary.load(dict_path))
            stats.spill_events, stats.spilled_keys = sev, skk
            log.info("resumed from checkpoint: %d groups already merged", groups_done)

    def replay_group(chunks_host, docs_host, p_ovf_n: int) -> None:
        # The fast path clamped this whole group to empty on device
        # (make_shuffle_step_fns psum clamp), so re-run it through a tier
        # wide enough that the overflow cannot recur, and merge that.
        nonlocal state
        chunks_dev = jax.device_put(chunks_host, in_shard)
        docs_dev = jax.device_put(docs_host, in_shard)
        if p_ovf_n > 0:
            # A chunk had more distinct keys than u_cap: widest tier.
            stats.partial_overflow_replays += 1
            if "full" not in tiers:
                tiers["full"] = make_shuffle_step_fns(
                    app, cfg.chunk_bytes, cfg.chunk_bytes, mesh
                )
            fns, tier_cap = tiers["full"], cfg.chunk_bytes
        else:
            # Bucket skew: bucket_cap=u_cap makes overflow impossible.
            stats.bucket_skew_replays += 1
            if "skew" not in tiers:
                tiers["skew"] = make_shuffle_step_fns(app, u_cap, u_cap, mesh)
            fns, tier_cap = tiers["skew"], u_cap
        stats.mesh_rounds += 1
        stats.shuffle_wire_bytes += wire_bytes_per_round(d, tier_cap)
        with _a2a_span(stats, round=stats.mesh_rounds, tier="replay",
                       wire_bytes=wire_bytes_per_round(d, tier_cap)):
            local, _, _ = fns[0](chunks_dev, docs_dev)
            state, evicted, ev_counts = fns[1](state, local)
        # Blocking readback + spill fold OUTSIDE the a2a block: they are
        # device-wait/host work, and inside they would inflate all_to_all_s
        # — the ICI numerator — with non-interconnect time.
        t0 = time.perf_counter()
        with trace_span("device.drain", steps=1):
            ev_n = int(np.asarray(jax.device_get(ev_counts)).sum())
        stats.device_wait_s += time.perf_counter() - t0
        if ev_n > 0:
            stats.spill_events += 1
            stats.spilled_keys += ev_n
            acc.add_batch(evicted)

    def drain(n: int) -> None:
        # One batched readback per window — see _stream_single.drain.
        if n <= 0:
            return
        batch = [pending.popleft() for _ in range(n)]
        t0 = time.perf_counter()
        with trace_span("device.drain", steps=n):
            flat = jax.device_get(
                [x for (p, b, e, *_rest) in batch for x in (p, b, e)]
            )
        dt = time.perf_counter() - t0
        stats.device_wait_s += dt
        stats.record_hist("device.drain_s", dt)
        _sample_device_memory(stats)
        for (p, b, e, evicted, chunks_host, docs_host), p_arr, b_arr, e_arr in zip(
            batch, flat[::3], flat[1::3], flat[2::3]
        ):
            ev_n = int(np.asarray(e_arr).sum())
            if ev_n > 0:
                stats.spill_events += 1
                stats.spilled_keys += ev_n
                with trace_span("spill", keys=ev_n):
                    acc.add_batch(evicted)
            p_n = int(np.asarray(p_arr).sum())
            if p_n > 0 or int(np.asarray(b_arr).sum()) > 0:
                replay_group(chunks_host, docs_host, p_n)

    group_chunks: list[np.ndarray] = []
    group_docs: list[int] = []

    def submit_group() -> None:
        nonlocal state, groups_done
        while len(group_chunks) < d:  # pad the tail group with space chunks
            group_chunks.append(np.full(cfg.chunk_bytes, 0x20, dtype=np.uint8))
            group_docs.append(0)
        chunks_host = np.stack(group_chunks)
        docs_host = np.asarray(group_docs, dtype=np.int32)
        group_chunks.clear()
        group_docs.clear()
        stats.mesh_rounds += 1
        stats.shuffle_wire_bytes += wire_bytes_per_round(d, bucket_cap)
        with _a2a_span(stats, round=stats.mesh_rounds, tier="fast",
                       wire_bytes=wire_bytes_per_round(d, bucket_cap)):
            local, p_ovf, b_ovf = fast[0](
                jax.device_put(chunks_host, in_shard), jax.device_put(docs_host, in_shard)
            )
            # Merge dispatches immediately — an overflowed group is empty on
            # device, so merging before the flags reach the host is safe.
            # Host arrays are kept for the rare replay, not device buffers.
            state, evicted, ev_counts = fast[1](state, local)
            pending.append((p_ovf, b_ovf, ev_counts, evicted, chunks_host, docs_host))
        groups_done += 1
        if (
            cfg.checkpoint_every_groups > 0
            and groups_done % cfg.checkpoint_every_groups == 0
        ):
            drain(len(pending))  # state must reflect every submitted group
            # The dictionary must also reflect them: scan futures fold
            # lazily, and a checkpointed count whose word never made the
            # saved dictionary would resume into a permanent unknown key.
            while ingest.scans:
                ingest._fold_done(block=True)
            _write_ckpt(cfg, fingerprint, state, groups_done, acc, dictionary, stats)
        elif len(pending) >= 2 * depth:
            drain(depth)

    ingest = _IngestStream(cfg, inputs, stats, dictionary, skip_chunks=skip_chunks,
                           host_mask=app.host_mask,
                           lineage_range=app.partition_mode == "range")
    try:
        for chunk in ingest:
            group_chunks.append(chunk.data)
            group_docs.append(chunk.doc_id)
            if len(group_chunks) == d:
                submit_group()
        if group_chunks:
            submit_group()
        drain(len(pending))
    except BaseException:
        ingest.close(abort=True)
        raise
    ingest.close()
    _finish_mesh_state(app, mesh, state, stats, acc)


def _collect_spill_stats(stats: JobStats, dictionary, acc) -> None:
    """Fold the spill writers' final tallies into JobStats — run_job
    thread only, AFTER remove_runs joined the writer threads, so no
    write races exist (the fold-plane collect() doctrine). The per-run
    write_s histograms merge into one ``spill.write_s`` distribution."""
    d = dictionary.spill_stats()
    a = acc.spill_stats()
    stats.spill_s = d["write_s"] + a["write_s"]
    stats.spill_stall_s = d["stall_s"] + a["stall_s"]
    stats.spill_bytes = d["bytes"] + a["bytes"]
    for h in (d["hist"], a["hist"]):
        if h is not None and h.count:
            agg = stats.hists.get("spill.write_s")
            if agg is None:
                agg = stats.hists["spill.write_s"] = Histogram()
            agg.merge(h)


def _publish_spill_live(stats: JobStats, dictionary, acc) -> None:
    """Per-window live publication of the running spill totals (consumer/
    router thread): the writers' float cells are benign-stale at worst —
    the live metrics ring and the streaming doctor must see a spill-bound
    job DURING the run, not only post-mortem (the PR 9 fold_s pattern).
    Exact finals land in _collect_spill_stats at teardown."""
    total_w = total_st = 0.0
    total_b = 0
    seen = False
    for tier in (dictionary, acc):
        snap = tier.spill_snapshot()
        if snap is None:
            continue
        seen = True
        total_w += snap[0]
        total_st += snap[1]
        total_b += snap[2]
    if seen:
        stats.spill_s = total_w
        stats.spill_stall_s = total_st
        stats.spill_bytes = total_b


def run_job(
    cfg: Config,
    inputs: Sequence[str] | None = None,
    app: App | None = None,
    write_outputs: bool = True,
    corpus_bounds: Sequence[int] | None = None,
) -> JobResult:
    """Run one job end-to-end. Exact results on any device/mesh shape.

    With egress budgets set (Config.host_accum_budget_mb /
    dictionary_budget_words) and exceeded, finalize switches to the
    streaming merge-join egress and JobResult.table comes back EMPTY —
    the results live in the output files, whose content is identical to
    the in-RAM path's.

    Multi-corpus jobs (ISSUE 15): with ``inputs=None`` the corpora come
    from Config.corpora() (``input_dirs``) and the flat doc_id space
    concatenates their sorted listings; explicit ``inputs`` callers pass
    the matching ``corpus_bounds`` (resolve_corpora's) themselves.
    """
    t0 = time.perf_counter()
    app = app or WordCount()
    if inputs is None:
        inputs, auto_bounds, _names = resolve_corpora(cfg)
        if corpus_bounds is None:
            corpus_bounds = auto_bounds
    else:
        inputs = list(inputs)
    if not inputs:
        raise ValueError("no input files")

    budgeted = cfg.host_accum_budget_mb is not None or cfg.dictionary_budget_words is not None
    if budgeted and (cfg.checkpoint_every_groups or cfg.resume or jax.process_count() > 1):
        raise ValueError(
            "egress budgets are incompatible with checkpoint/resume and "
            "multi-process runs"
        )
    if budgeted and not write_outputs:
        # Streaming egress delivers results ONLY through output files; a
        # budgeted run without them would compute everything and return
        # an empty table — silently discarding the job.
        raise ValueError("egress budgets require write_outputs=True")
    # Sanitize-aware construction (analysis/sanitize.py): plain instances
    # unless Config.sanitize / MR_SANITIZE=1, in which case cross-thread
    # writes to stats or dictionary raise at the write site.
    from mapreduce_rust_tpu.analysis.sanitize import new_dictionary, new_job_stats

    stats = new_job_stats(cfg)
    # Workload plane (ISSUE 15): bind corpus bounds and — for range apps —
    # sampler-derived splitters onto the app BEFORE anything streams. The
    # pre-pass is seeded and pure in (inputs, config), so every engine and
    # every re-execution derives identical routing; its cost lands in
    # stats.splitter_s/splitter_samples for the bench sort leg.
    from mapreduce_rust_tpu.runtime.splitter import prepare_app

    app = prepare_app(app, cfg, inputs, corpus_bounds or (), stats=stats)
    # Crash-safe run scavenging (ISSUE 11 satellite): a SIGKILLed job's
    # remove_runs never ran, so its dictrun-*/accrun-* files leak forever
    # in a shared work_dir. Reclaim orphans whose writer pid is gone (live
    # concurrent jobs keep answering kill(pid, 0), so theirs are never
    # touched); best-effort, before this job's own tiers exist.
    from mapreduce_rust_tpu.runtime.spill import scavenge_stale_runs

    scavenge_stale_runs(cfg.work_dir, logger=log)
    acc = HostAccumulator(
        app.combine_op,
        budget_bytes=(
            cfg.host_accum_budget_mb << 20
            if cfg.host_accum_budget_mb is not None else None
        ),
        spill_dir=cfg.work_dir,
        async_spill=cfg.spill_async,
    )
    # Sharded egress fold (ISSUE 9): the single-process host-map engine
    # splits the dictionary into S key-hash-disjoint shards, each owned by
    # one fold thread of _FoldShardPlane. Every other engine keeps the
    # single-dictionary fold (mesh tokenizes on device; multihost already
    # merges per-PROCESS dictionary shards; checkpoint/resume persists the
    # plain Dictionary). The word budget splits across shards so the
    # bounded-memory contract is per-process, not per-shard×S.
    fold_shards = 1
    if (cfg.map_engine == "host"
            and not (cfg.mesh_shape and cfg.mesh_shape > 1)
            and jax.process_count() == 1):
        fold_shards = cfg.effective_fold_shards()
    if fold_shards > 1:
        per_shard_budget = (
            max(1, cfg.dictionary_budget_words // fold_shards)
            if cfg.dictionary_budget_words is not None else None
        )
        dictionary = ShardedDictionary([
            new_dictionary(cfg, budget_words=per_shard_budget,
                           spill_dir=cfg.work_dir,
                           async_spill=cfg.spill_async)
            for _ in range(fold_shards)
        ])
    else:
        dictionary = new_dictionary(
            cfg, budget_words=cfg.dictionary_budget_words,
            spill_dir=cfg.work_dir, async_spill=cfg.spill_async,
        )
    # Compile instrumentation rides every run (cheap: two listeners, a
    # list append per compile); the slice below scopes the process-global
    # log to THIS run's interval.
    _install_compile_listener()
    compile_log_start = len(_COMPILE_LOG)
    tracer = start_tracing(tag="driver") if cfg.trace_path else None
    if tracer is not None:
        # Flight recorder: the stream loops tick maybe_snapshot() per
        # chunk/window, so a killed or wedged driver still leaves an
        # atomic *.partial.json that `trace merge` accepts.
        tracer.enable_flight_recorder(
            partial_path(cfg.trace_path),
            period_s=cfg.flight_record_period_s,
        )
    # Live metrics (ISSUE 8): the registry pulls JobStats aggregates into
    # the time-series ring when the SAME loops that tick the flight
    # recorder call metrics_tick() — no engine grows a second
    # instrumentation site, nothing runs per record. Serialized into the
    # manifest as stats.timeseries by build_manifest.
    registry = None
    if cfg.metrics_enabled:
        registry = start_metrics(cfg.metrics_sample_period_s,
                                 cfg.metrics_ring_points)
        registry.add_collector(jobstats_collector(stats))
        if tracer is not None:
            tracer.metrics_registry = registry  # partials keep the series
    # Sampling profiler (ISSUE 19): one thread walks sys._current_frames()
    # at ~97 Hz, collapsed stacks keyed by the mr/ plane-thread names.
    # Observational only — nothing the data plane reads is touched, so
    # outputs stay bit-identical ON vs OFF. Lands in the manifest as
    # stats.profile (build_manifest reads the still-active profiler).
    sprof = None
    if cfg.profile or profile_forced():
        from mapreduce_rust_tpu.runtime.prof import start_profiler

        sprof = start_profiler(cfg.profile_hz)
        if tracer is not None:
            tracer.profiler = sprof  # partials keep the flamegraph
            sprof.tracer = tracer    # per-plane self-time counter tracks
    # Provenance ledger (ISSUE 20): per-chunk content digests + partition
    # routing recorded from the same consumer loops that tick the flight
    # recorder. Observational only — outputs stay bit-identical ON vs
    # OFF. Lands in the manifest as stats.lineage (build_manifest reads
    # the still-active ledger) and in partials as body["lineage"].
    ledger = None
    if cfg.lineage or lineage_forced():
        from mapreduce_rust_tpu.runtime.lineage import (
            LEDGER_NAME,
            start_ledger,
        )

        os.makedirs(cfg.work_dir, exist_ok=True)
        ledger = start_ledger(os.path.join(cfg.work_dir, LEDGER_NAME),
                              inputs=inputs, reduce_n=cfg.reduce_n)
        if tracer is not None:
            tracer.lineage = ledger  # partials keep the provenance tail
    output_files: list[str] = []
    table: dict = {}

    try:
        prof = (
            jax.profiler.trace(cfg.profile_dir)
            if cfg.profile_dir
            else contextlib.nullcontext()
        )
        with stats.phase("stream"), prof:
            if cfg.map_engine == "host" and cfg.mesh_shape and cfg.mesh_shape > 1:
                log.warning(
                    "map_engine='host' applies to the single-chip driver only; "
                    "mesh runs tokenize on device (the mesh IS the map engine)"
                )
            if jax.process_count() > 1:
                _stream_multihost(cfg, app, inputs, stats, acc, dictionary)
            elif cfg.mesh_shape and cfg.mesh_shape > 1 and cfg.sharded_stream:
                _stream_sharded(cfg, app, inputs, stats, acc, dictionary)
            elif cfg.mesh_shape and cfg.mesh_shape > 1:
                _stream_mesh(cfg, app, inputs, stats, acc, dictionary)
            elif cfg.map_engine == "host":
                _stream_host_map(cfg, app, inputs, stats, acc, dictionary)
            else:
                _stream_single(cfg, app, inputs, stats, acc, dictionary)

        streaming = (acc.has_runs or dictionary.spilled) and type(app).finalize is App.finalize
        if (acc.has_runs or dictionary.spilled) and not streaming:
            log.warning(
                "app %s overrides finalize — rehydrating spilled egress tiers "
                "into RAM (exact, but unbounded)", app.name
            )

        if streaming:
            # _stream_finalize opens its own finalize/egress phase blocks —
            # nesting both here would double-count one interval under two keys.
            output_files = _stream_finalize(
                cfg, app, stats, acc, dictionary, write_outputs
            )
        else:
            with stats.phase("finalize"):
                stats.distinct_keys = len(acc.table)
                stats.dictionary_words = len(dictionary)
                stats.hash_collisions = len(dictionary.collisions)
                items = []
                is_distinct = app.combine_op == "distinct"
                lookup = dictionary.lookup
                if dictionary.spilled:
                    # Rehydrate fallback: serve point lookups from the full
                    # sorted stream (runs + RAM) materialized once.
                    full = {(k1, k2): w for _p, k1, k2, w in dictionary.iter_sorted()}
                    lookup = lambda k1, k2: full.get((k1, k2))  # noqa: E731
                for key, v in acc.table.items():
                    word = lookup(*key)
                    if word is None:
                        stats.unknown_keys += 1
                        continue
                    value = sorted(v) if is_distinct else v
                    items.append((word, value, key))
                    table[word] = value

            with stats.phase("egress"):
                parts = app.finalize(items, cfg.reduce_n)
                if write_outputs:
                    os.makedirs(cfg.output_dir, exist_ok=True)
                    # Multi-process: each process emits ITS hash classes'
                    # lines under a process-suffixed name; `merge` globs them
                    # all (for top_k, App.merge_lines is the cross-process
                    # selection root).
                    suffix = f".p{jax.process_index()}" if jax.process_count() > 1 else ""
                    for r in range(cfg.reduce_n):
                        path = os.path.join(cfg.output_dir, f"mr-{r}{suffix}.txt")
                        written = 0
                        with open(path, "wb") as f:
                            for line in parts.get(r, []):
                                f.write(line + b"\n")
                                written += len(line) + 1
                        # Per-partition output bytes: the reduce-side skew
                        # signal the doctor scores (index = partition r).
                        stats.partition_bytes.append(written)
                        if ledger is not None:
                            # Egress claim (ISSUE 20): partition r's bytes
                            # + the chunks whose routed keys contributed.
                            ledger.record_partition(r, written)
                        output_files.append(path)

        stats.wall_seconds = time.perf_counter() - t0
        log.info("job %s done: %s", app.name, stats.summary())
    finally:
        # Failure path still gets real wall time: the manifest is written
        # even on a crash, and a 0.0-second crashed run would corrupt every
        # post-mortem throughput comparison.
        if not stats.wall_seconds:
            stats.wall_seconds = time.perf_counter() - t0
        # Fold this run's XLA compiles into the stats (count / seconds /
        # persistent-cache hit-miss split) — the doctor's compile-bound
        # attribution and the manifest's "compile" block.
        for rec in _COMPILE_LOG[compile_log_start:]:
            stats.compile_count += 1
            stats.compile_s += rec["dur_s"]
            if rec["cache"] == "hit":
                stats.compile_cache_hits += 1
            elif rec["cache"] == "miss":
                stats.compile_cache_misses += 1
            stats.record_hist("xla.compile_s", rec["dur_s"])
        # Spill runs are job-scoped scratch: a shared work_dir must not
        # accumulate accrun-*/dictrun-* files across jobs (or leak them on
        # a failed run) — ADVICE r5. Their counts survive in the stats (and
        # manifest) as the proof the disk tiers engaged.
        stats.accum_spill_runs = acc.run_count
        stats.dict_spill_runs = dictionary.run_count
        # remove_runs closes (joins) every async spill writer, so the
        # collection below reads FINAL counters — no thread still adding.
        acc.remove_runs()
        dictionary.remove_runs()
        _collect_spill_stats(stats, dictionary, acc)
        # Packed-merge jit cache hygiene (ISSUE 13 satellite): enforce the
        # LRU bound at job teardown so a long-lived multi-job process
        # (ROADMAP item 2) holds a bounded working set of compiled merges
        # — clear_packed_fns() is the full-drop hook for embedders.
        trim_packed_fns()
        if sprof is not None:
            # Freeze sampling before the artifact flush: the profile
            # covers the job (stream/finalize/egress + spill joins), not
            # manifest serialization. The stopped profiler stays in the
            # global slot so build_manifest embeds its final aggregate.
            sprof.stop()
        if ledger is not None:
            # Seal the jsonl (end record: folded corpus content digest)
            # before the flush; the closed ledger stays in the global
            # slot so build_manifest embeds stats.lineage.
            try:
                ledger.close()
            except Exception:
                log.warning("lineage ledger close failed", exc_info=True)
        if tracer is not None:
            stop_tracing()
        if tracer is not None or cfg.manifest_path:
            # Written even on failure (with an "error" field): a crashed
            # run's manifest names what ran, which is the point. The whole
            # block is best-effort — a telemetry failure (including a
            # wedged distributed runtime below) must never mask the job's
            # real exception.
            import sys as _sys

            from mapreduce_rust_tpu.runtime.telemetry import flush_run_artifacts

            exc = _sys.exc_info()[1]
            extra: dict = {}
            if exc is not None:
                extra["error"] = repr(exc)
            if stats.merge_dispatches:
                # Per-dispatch merge cost (flops / bytes accessed) for
                # the roofline's device-merge intensity (ISSUE 19).
                try:
                    mc = _merge_cost_analysis(app, cfg)
                    if mc:
                        extra["merge_cost"] = mc
                except Exception:
                    pass  # telemetry stays best-effort
            tag = None
            try:
                if jax.process_count() > 1:
                    from mapreduce_rust_tpu.parallel.distributed import cluster_info

                    extra["cluster"] = cluster_info()
                    # Per-process file names, like the .p{rank} output
                    # suffix above: co-hosted federated drivers must not
                    # clobber each other's trace/manifest.
                    tag = f"p{jax.process_index()}"
            except Exception as e:
                log.warning("cluster telemetry unavailable: %s", e)
            flush_run_artifacts(
                cfg, tracer, tag=tag, logger=log,
                stats=stats, app_name=app.name, inputs=inputs,
                output_files=output_files, extra=extra or None,
            )
        if registry is not None:
            # After the flush: build_manifest serialized the ring from the
            # still-active registry. Compare-and-clear: an in-process
            # co-hosted worker may have replaced the global slot.
            stop_metrics(registry)
        if sprof is not None:
            # Same order and compare-and-clear discipline as the registry.
            from mapreduce_rust_tpu.runtime.prof import stop_profiler

            stop_profiler(sprof)
        if ledger is not None:
            # Same order and compare-and-clear discipline as the profiler.
            from mapreduce_rust_tpu.runtime.lineage import stop_ledger

            stop_ledger(ledger)
    return JobResult(stats=stats, table=table, output_files=output_files)


def _stream_finalize(cfg: Config, app: App, stats: JobStats, acc: HostAccumulator,
                     dictionary: Dictionary, write_outputs: bool) -> list[str]:
    """Bounded-memory egress: a single merge-join of the accumulator's
    sorted fold against the dictionary's sorted word stream, routed into
    per-partition line files, each sorted independently at the end. Peak
    RAM is O(fold rows + one partition's lines), never O(vocabulary) of
    Python objects — the tier the reference cannot have (its reduce holds
    a whole partition's pairs in one Vec, src/mr/worker.rs:82-108).

    Implements the DEFAULT egress contract (route by k1 % reduce_n,
    app.format_line, bytewise sort per partition) — run_job falls back to
    the in-RAM path for apps that override App.finalize.
    """
    import tempfile

    from mapreduce_rust_tpu.runtime.lineage import active_ledger

    ledger = active_ledger()

    with stats.phase("finalize"):
        rows = acc.fold_arrays()  # sorted by (k1, k2[, value])
        is_distinct = app.combine_op == "distinct"
        packed_rows = (rows[:, 0].astype(np.uint64) << np.uint64(32)) | rows[
            :, 1
        ].astype(np.uint64)
        n = len(rows)
        if is_distinct:
            key_change = np.empty(n, dtype=bool)
            if n:
                key_change[0] = True
                key_change[1:] = packed_rows[1:] != packed_rows[:-1]
            stats.distinct_keys = int(key_change.sum())
        else:
            stats.distinct_keys = n
        stats.dictionary_words = len(dictionary)
        stats.hash_collisions = len(dictionary.collisions)

    with stats.phase("egress"):
        os.makedirs(cfg.output_dir, exist_ok=True)
        tmpdir = tempfile.mkdtemp(prefix="egress-", dir=cfg.output_dir)
        # ONE try/finally spans the whole egress phase — the merge-join loop
        # AND the per-partition sort/rewrite — so a failure anywhere in
        # either (a bad run file, a full disk mid-sort) still removes the
        # egress tmpdir instead of leaking part-* files into the output dir
        # (ADVICE r5).
        try:
            parts = [
                open(os.path.join(tmpdir, f"part-{r}"), "wb")
                for r in range(cfg.reduce_n)
            ]
            matched = 0
            try:
                # Batched k-way merge-join (ISSUE 11): the dictionary's
                # sources (all runs, all shards, RAM tiers — key-disjoint
                # by construction) merge in key/index BLOCKS through the
                # native loser tree, and each block joins the fold with
                # one vectorized searchsorted. Word bytes are sliced only
                # for keys the fold actually holds — the per-key Python
                # heap interleave + text parse this replaces was the
                # spill-engaged egress wall.
                from mapreduce_rust_tpu.runtime import spill as spill_io

                sources = dictionary.run_sources()
                stats.merge_fanin = len(sources)
                merge_it = spill_io.merge_sources(sources)
                while True:
                    t0 = time.perf_counter()
                    blk = next(merge_it, None)
                    if blk is None:
                        break
                    keys_b, src_b, idx_b = blk
                    ends_g = None
                    if n:
                        pos = np.searchsorted(packed_rows, keys_b)
                        posc = np.minimum(pos, n - 1)
                        hit = (pos < n) & (packed_rows[posc] == keys_b)
                        if is_distinct:
                            # Fold rows repeat per (key, doc): the group's
                            # exclusive end, found once per block.
                            ends_g = np.searchsorted(
                                packed_rows, keys_b, side="right"
                            )
                    else:
                        hit = np.zeros(len(keys_b), dtype=bool)
                    stats.record_hist(
                        "egress.merge_s", time.perf_counter() - t0
                    )
                    hits = np.nonzero(hit)[0]
                    if not len(hits):
                        continue  # dictionary words absent from the fold
                    # Batched word slicing (spill_io.slice_block_words,
                    # shared with the streaming save): word bytes are
                    # materialized only for keys the fold holds — at
                    # millions of matched words the per-item .word() path
                    # was a measurable slice of egress.
                    words = spill_io.slice_block_words(
                        sources, src_b[hits], idx_b[hits]
                    )
                    # Routing goes through the app's partition seam
                    # (ISSUE 15): hash apps keep k1 % reduce_n, range
                    # apps (sort) searchsorted the word prefixes over
                    # their sampler-bound splitters — element-wise equal
                    # to App.route, the in-RAM tier's router.
                    rr = app.route_block(
                        words,
                        (keys_b[hits] >> np.uint64(32)).astype(np.int64),
                        cfg.reduce_n,
                    )
                    pos_h = pos[hits]
                    emit = app.emit_lines
                    # One buffered write per (block, partition), not one
                    # per line: the formatted lines batch through a join.
                    blk_lines: list[list] = [[] for _ in range(cfg.reduce_n)]
                    if is_distinct:
                        for w, r, i, j2 in zip(
                            words, rr, pos_h.tolist(), ends_g[hits].tolist()
                        ):
                            blk_lines[r].extend(
                                emit(w, sorted(rows[i:j2, 2].tolist()))
                            )
                    else:
                        for w, r, v in zip(
                            words, rr, rows[pos_h, 2].tolist()
                        ):
                            blk_lines[r].extend(emit(w, v))
                    for r, ls in enumerate(blk_lines):
                        if ls:
                            parts[r].write(b"\n".join(ls) + b"\n")
                    matched += len(hits)
            finally:
                for f in parts:
                    f.close()
            stats.unknown_keys = stats.distinct_keys - matched

            output_files: list[str] = []
            for r in range(cfg.reduce_n):
                with open(os.path.join(tmpdir, f"part-{r}"), "rb") as f:
                    lines = f.read().splitlines()
                lines.sort()
                buf = b"\n".join(lines) + b"\n" if lines else b""
                # Same reduce-skew signal as the in-RAM egress path (the
                # joined buffer's length IS sum(len(line) + 1)).
                stats.partition_bytes.append(len(buf))
                if ledger is not None:
                    # Egress claim (ISSUE 20), streaming tier: same
                    # contract as the in-RAM path's record_partition.
                    ledger.record_partition(r, len(buf))
                if write_outputs:
                    path = os.path.join(cfg.output_dir, f"mr-{r}.txt")
                    with open(path, "wb") as f:
                        f.write(buf)
                    output_files.append(path)
        finally:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)
    return output_files


def merge_outputs(output_files: Sequence[str], out_path: str) -> None:
    """`cat mr-* | sort > final.txt` (reference src/run.sh:17-21)."""
    lines: list[bytes] = []
    for path in output_files:
        with open(path, "rb") as f:
            lines.extend(f.read().splitlines())
    lines.sort()
    with open(out_path, "wb") as f:
        for line in lines:
            f.write(line + b"\n")
