"""Chunked streaming driver — the end-to-end engine (single-chip and mesh).

This is the TPU-native replacement for the reference's whole worker
execution path (src/mr/worker.rs:65-193): instead of per-task files and
per-record writes, a single host loop streams whitespace-aligned chunks
(runtime/chunker.py) through a compiled per-chunk step and keeps running
distinct-key state on device:

    chunk bytes ──device_put──▶ tokenize_and_hash ─▶ app.device_map
        ─▶ count_unique (map-side combiner)  ─▶ merge into state
                                                   │
         evicted tail (rare) ◀─────────────────────┘
              └─▶ host spill accumulator (exact, nothing dropped)

With ``cfg.mesh_shape > 1`` the same loop feeds groups of D chunks to the
mesh pipeline (parallel/shuffle.py): per-chip combine → bucket scatter →
``lax.all_to_all`` over ICI → per-chip merge into a hash-class-sharded
state. That collective IS the reference's mr-{m}-{r}.txt file shuffle
(src/mr/worker.rs:117-140), lowered to the interconnect.

The loop is pipelined: JAX dispatch is async, so while the device works on
chunk k the host normalizes/chunks k+1 and feeds the egress dictionary
(runtime/dictionary.py). Device sync points trail dispatch by two steps
(overflow/spill counters), so the device never idles on the host.

Capacity faults are handled, not asserted (VERDICT r1 weak 3):
- per-chunk distinct keys > partial_capacity → the chunk/group is
  *replayed* through a lazily-compiled wider tier (counted, exact);
- mesh bucket skew > bucket capacity → same replay, tier sized so bucket
  overflow is impossible (bucket_cap = whole update);
- merged distinct keys > merge_capacity → the evicted tail spills whole
  to the host accumulator (ops/groupby.merge_batches; counted, exact).

At egress the final table joins the hash→word dictionary and each app
formats its partitions (apps/base.py), written as mr-{r}.txt like the
reference (src/mr/worker.rs:167,180-183) — including every partition's
last key, which the reference drops (worker.rs:169-184).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import os
import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from mapreduce_rust_tpu.apps.base import App
from mapreduce_rust_tpu.apps.word_count import WordCount
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.core.kv import KVBatch
from mapreduce_rust_tpu.ops.groupby import count_unique, merge_batches
from mapreduce_rust_tpu.ops.tokenize import tokenize_and_hash
from mapreduce_rust_tpu.runtime.chunker import chunk_stream, list_inputs
from mapreduce_rust_tpu.runtime.dictionary import Dictionary
from mapreduce_rust_tpu.runtime.metrics import JobStats, log

_PIPELINE_DEPTH = 2  # device sync trails dispatch by this many steps


def select_device(kind: str = "auto"):
    """cfg.device → a jax.Device. "auto" prefers the accelerator backend."""
    if kind == "auto":
        return jax.devices()[0]
    devs = jax.devices(kind)
    if not devs:
        raise RuntimeError(f"no {kind} devices available")
    return devs[0]


def make_step_fns(app: App, u_cap: int):
    """(map_combine, merge) jitted for one app + update capacity.

    map_combine: chunk bytes → compacted per-chunk partial + overflow count.
    merge: fold the partial into the running state, returning the evicted
    tail and its record count (donates the old state's buffers).
    """
    op = app.combine_op

    @jax.jit
    def map_combine(chunk: jnp.ndarray, doc_id: jnp.ndarray):
        kv = tokenize_and_hash(chunk)
        kv = app.device_map(kv, doc_id)
        partial = count_unique(kv, op=op)
        update = partial.take_front(u_cap)
        ovf = jnp.sum(partial.valid[u_cap:].astype(jnp.int32))
        return update, ovf

    @functools.partial(jax.jit, donate_argnums=(0,))
    def merge(state: KVBatch, update: KVBatch):
        new_state, evicted = merge_batches(state, update, op=op)
        ev_count = jnp.sum(evicted.valid.astype(jnp.int32))
        return new_state, evicted, ev_count

    return map_combine, merge


class HostAccumulator:
    """Exact host-side fold of device spills + the final state, per op."""

    def __init__(self, op: str) -> None:
        self.op = op
        self.table: dict = (
            collections.defaultdict(set) if op == "distinct" else {}
        )

    def add(self, keys: np.ndarray, vals: np.ndarray) -> None:
        op, t = self.op, self.table
        for (a, b), v in zip(keys.tolist(), vals.tolist()):
            k = (a, b)
            if op == "sum":
                t[k] = t.get(k, 0) + v
            elif op == "distinct":
                t[k].add(v)
            elif op == "max":
                t[k] = v if k not in t else max(t[k], v)
            else:
                t[k] = v if k not in t else min(t[k], v)

    def add_batch(self, batch: KVBatch) -> None:
        keys, vals = batch.to_host()
        self.add(keys, vals)


@dataclasses.dataclass
class JobResult:
    stats: JobStats
    table: dict            # word bytes → final value (int or sorted doc list)
    output_files: list[str]


def _scan_payload(payload: bytes):
    """Tagged scan result of one chunk — runs on the ingest pool. The
    native C pass releases the GIL, so scans of consecutive chunks overlap
    each other, the chunker thread, and device dispatch."""
    from mapreduce_rust_tpu.native.host import scan_unique_raw

    res = scan_unique_raw(payload)
    if res is not None:
        return ("raw", *res)
    from mapreduce_rust_tpu.core.hashing import hash_words
    from mapreduce_rust_tpu.runtime.dictionary import extract_words

    seen: set = set()
    words = [w for w in extract_words(payload) if not (w in seen or seen.add(w))]
    return ("list", words, hash_words(words))


_SENTINEL = object()


class _IngestStream:
    """Shared ingest: a prefetch thread runs read→normalize→chunk ahead of
    the consumer (bounded queue), and a thread pool runs the dictionary
    scans; scan results fold into the Dictionary only on the consumer
    thread. doc_id = position in inputs + doc_id_offset (a worker's map
    task passes its task id so inverted_index doc ids stay global)."""

    def __init__(self, cfg: Config, inputs: Sequence[str], stats: JobStats,
                 dictionary: Dictionary, doc_id_offset: int = 0) -> None:
        import queue
        import threading
        from concurrent.futures import ThreadPoolExecutor

        self.cfg = cfg
        self.dictionary = dictionary
        self.workers = max(cfg.ingest_threads, 1)
        self.pool = ThreadPoolExecutor(max_workers=self.workers)
        self.scans: collections.deque = collections.deque()
        self.q: "queue.Queue" = queue.Queue(maxsize=max(cfg.prefetch_chunks, 1))
        self.err: BaseException | None = None
        self._stop = False
        self._thread = threading.Thread(
            target=self._produce, args=(list(inputs), stats, doc_id_offset), daemon=True
        )
        self._thread.start()

    def _put(self, item) -> bool:
        import queue

        while True:
            try:
                self.q.put(item, timeout=0.2)
                return True
            except queue.Full:
                if self._stop:
                    return False

    def _produce(self, inputs, stats, doc_id_offset) -> None:
        try:
            for i, path in enumerate(inputs):
                stats.bytes_in += os.path.getsize(path)
                with open(path, "rb") as f:
                    for chunk in chunk_stream(f, doc_id_offset + i, self.cfg.chunk_bytes):
                        stats.chunks += 1
                        stats.forced_cuts += int(chunk.forced_cut)
                        if not self._put(chunk):
                            return
        except BaseException as e:  # re-raised on the consumer thread
            self.err = e
        finally:
            self._put(_SENTINEL)

    def _fold_done(self, block: bool = False) -> None:
        while self.scans and (block or self.scans[0].done()):
            kind, *rest = self.scans.popleft().result()
            if kind == "raw":
                self.dictionary.add_scanned_raw(*rest)
            else:
                self.dictionary.add_scanned(*rest)
            block = False  # blocking drain pops exactly one

    def __iter__(self):
        while True:
            chunk = self.q.get()
            if chunk is _SENTINEL:
                if self.err is not None:
                    raise self.err
                return
            self.scans.append(
                self.pool.submit(_scan_payload, bytes(chunk.data[: chunk.nbytes]))
            )
            # Backpressure: each pending future pins a chunk-sized payload;
            # fold the oldest (blocking) once the backlog exceeds the pool.
            self._fold_done(block=len(self.scans) > 2 * self.workers + 4)
            yield chunk

    def close(self, abort: bool = False) -> None:
        """Fold remaining scans and release threads. abort=True (exception
        path) skips folding and just unblocks + reaps the producer."""
        self._stop = True
        if abort:
            try:
                while True:
                    self.q.get_nowait()
            except Exception:
                pass
            for f in self.scans:
                f.cancel()
            self.scans.clear()
        else:
            while self.scans:
                self._fold_done(block=True)
        self.pool.shutdown(wait=False)
        self._thread.join(timeout=5)


def _stream_single(cfg: Config, app: App, inputs, stats, acc, dictionary,
                   doc_id_offset: int = 0) -> None:
    device = select_device(cfg.device)
    u_cap = cfg.effective_partial_capacity()
    map_combine, merge = make_step_fns(app, u_cap)
    slow_fns = None  # full-width replay path, compiled only if ever needed

    state = jax.device_put(KVBatch.empty(cfg.merge_capacity), device)
    mc_pending: collections.deque = collections.deque()
    sp_pending: collections.deque = collections.deque()

    def resolve_map_combine() -> None:
        nonlocal state, slow_fns
        update, ovf, chunk_dev, doc_id = mc_pending.popleft()
        this_merge = merge
        if int(ovf) > 0:
            # More distinct keys in the chunk than partial_capacity: replay
            # at full width. Exact, never silent (VERDICT r1 weak 3).
            stats.partial_overflow_replays += 1
            if slow_fns is None:
                slow_fns = make_step_fns(app, cfg.chunk_bytes)
            update, _ = slow_fns[0](chunk_dev, doc_id)
            this_merge = slow_fns[1]
        state, evicted, ev_count = this_merge(state, update)
        sp_pending.append((evicted, ev_count))

    def resolve_spill() -> None:
        evicted, ev_count = sp_pending.popleft()
        n = int(ev_count)
        if n > 0:
            stats.spill_events += 1
            stats.spilled_keys += n
            acc.add_batch(evicted)

    ingest = _IngestStream(cfg, inputs, stats, dictionary, doc_id_offset)
    try:
        for chunk in ingest:
            chunk_dev = jax.device_put(chunk.data, device)
            did = jax.device_put(np.int32(chunk.doc_id), device)
            update, ovf = map_combine(chunk_dev, did)
            mc_pending.append((update, ovf, chunk_dev, did))
            if len(mc_pending) > _PIPELINE_DEPTH:
                resolve_map_combine()
            if len(sp_pending) > _PIPELINE_DEPTH:
                resolve_spill()
        while mc_pending:
            resolve_map_combine()
        while sp_pending:
            resolve_spill()
    except BaseException:
        ingest.close(abort=True)
        raise
    ingest.close()
    acc.add_batch(state)


def _stream_mesh(cfg: Config, app: App, inputs, stats, acc, dictionary) -> None:
    """Group-of-D-chunks pipeline over the 1-D mesh (parallel/shuffle.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mapreduce_rust_tpu.parallel.shuffle import (
        AXIS,
        default_bucket_cap,
        make_mesh,
        make_shuffle_step_fns,
        sharded_empty_state,
    )

    backend = None if cfg.device == "auto" else cfg.device
    mesh = make_mesh(cfg.mesh_shape, backend)
    d = mesh.devices.size
    u_cap = cfg.effective_partial_capacity()
    bucket_cap = default_bucket_cap(u_cap, d, cfg.bucket_capacity_factor)
    fast = make_shuffle_step_fns(app, u_cap, bucket_cap, mesh)
    tiers: dict[str, tuple] = {}  # lazily-compiled exact replay paths

    state = sharded_empty_state(mesh, max(cfg.merge_capacity // d, 16))
    in_shard = NamedSharding(mesh, P(AXIS))
    mc_pending: collections.deque = collections.deque()
    sp_pending: collections.deque = collections.deque()

    def resolve_group() -> None:
        nonlocal state
        local, p_ovf, b_ovf, chunks_dev, docs_dev, fns = mc_pending.popleft()
        if int(jnp.sum(p_ovf)) > 0:
            # A chunk had more distinct keys than u_cap: widest tier.
            stats.partial_overflow_replays += 1
            if "full" not in tiers:
                tiers["full"] = make_shuffle_step_fns(
                    app, cfg.chunk_bytes, cfg.chunk_bytes, mesh
                )
            fns = tiers["full"]
            local, _, _ = fns[0](chunks_dev, docs_dev)
        elif int(jnp.sum(b_ovf)) > 0:
            # Bucket skew: bucket_cap=u_cap makes overflow impossible.
            stats.bucket_skew_replays += 1
            if "skew" not in tiers:
                tiers["skew"] = make_shuffle_step_fns(app, u_cap, u_cap, mesh)
            fns = tiers["skew"]
            local, _, _ = fns[0](chunks_dev, docs_dev)
        state, evicted, ev_counts = fns[1](state, local)
        sp_pending.append((evicted, ev_counts))

    def resolve_spill() -> None:
        evicted, ev_counts = sp_pending.popleft()
        n = int(jnp.sum(ev_counts))
        if n > 0:
            stats.spill_events += 1
            stats.spilled_keys += n
            acc.add_batch(evicted)

    group_chunks: list[np.ndarray] = []
    group_docs: list[int] = []

    def submit_group() -> None:
        while len(group_chunks) < d:  # pad the tail group with space chunks
            group_chunks.append(np.full(cfg.chunk_bytes, 0x20, dtype=np.uint8))
            group_docs.append(0)
        chunks_dev = jax.device_put(np.stack(group_chunks), in_shard)
        docs_dev = jax.device_put(np.asarray(group_docs, dtype=np.int32), in_shard)
        group_chunks.clear()
        group_docs.clear()
        local, p_ovf, b_ovf = fast[0](chunks_dev, docs_dev)
        mc_pending.append((local, p_ovf, b_ovf, chunks_dev, docs_dev, fast))
        if len(mc_pending) > _PIPELINE_DEPTH:
            resolve_group()
        if len(sp_pending) > _PIPELINE_DEPTH:
            resolve_spill()

    ingest = _IngestStream(cfg, inputs, stats, dictionary)
    try:
        for chunk in ingest:
            group_chunks.append(chunk.data)
            group_docs.append(chunk.doc_id)
            if len(group_chunks) == d:
                submit_group()
        if group_chunks:
            submit_group()
        while mc_pending:
            resolve_group()
        while sp_pending:
            resolve_spill()
    except BaseException:
        ingest.close(abort=True)
        raise
    ingest.close()
    acc.add_batch(state)


def run_job(
    cfg: Config,
    inputs: Sequence[str] | None = None,
    app: App | None = None,
    write_outputs: bool = True,
) -> JobResult:
    """Run one job end-to-end. Exact results on any device/mesh shape."""
    t0 = time.perf_counter()
    app = app or WordCount()
    inputs = list(inputs) if inputs is not None else list_inputs(cfg.input_dir, cfg.input_pattern)
    if not inputs:
        raise ValueError("no input files")

    stats = JobStats()
    acc = HostAccumulator(app.combine_op)
    dictionary = Dictionary()

    import contextlib

    prof = (
        jax.profiler.trace(cfg.profile_dir)
        if cfg.profile_dir
        else contextlib.nullcontext()
    )
    with stats.phase("stream"), prof:
        if cfg.mesh_shape and cfg.mesh_shape > 1:
            _stream_mesh(cfg, app, inputs, stats, acc, dictionary)
        else:
            _stream_single(cfg, app, inputs, stats, acc, dictionary)

    with stats.phase("finalize"):
        stats.distinct_keys = len(acc.table)
        stats.dictionary_words = len(dictionary)
        stats.hash_collisions = len(dictionary.collisions)
        items = []
        table: dict = {}
        is_distinct = app.combine_op == "distinct"
        for key, v in acc.table.items():
            word = dictionary.lookup(*key)
            if word is None:
                stats.unknown_keys += 1
                continue
            value = sorted(v) if is_distinct else v
            items.append((word, value, key))
            table[word] = value

    output_files: list[str] = []
    with stats.phase("egress"):
        parts = app.finalize(items, cfg.reduce_n)
        if write_outputs:
            os.makedirs(cfg.output_dir, exist_ok=True)
            for r in range(cfg.reduce_n):
                path = os.path.join(cfg.output_dir, f"mr-{r}.txt")
                with open(path, "wb") as f:
                    for line in parts.get(r, []):
                        f.write(line + b"\n")
                output_files.append(path)

    stats.wall_seconds = time.perf_counter() - t0
    log.info("job %s done: %s", app.name, stats.summary())
    return JobResult(stats=stats, table=table, output_files=output_files)


def merge_outputs(output_files: Sequence[str], out_path: str) -> None:
    """`cat mr-* | sort > final.txt` (reference src/run.sh:17-21)."""
    lines: list[bytes] = []
    for path in output_files:
        with open(path, "rb") as f:
            lines.extend(f.read().splitlines())
    lines.sort()
    with open(out_path, "wb") as f:
        for line in lines:
            f.write(line + b"\n")
