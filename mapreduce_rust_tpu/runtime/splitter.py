"""Sampled-splitter subsystem (ISSUE 15 tentpole): the cheap pre-pass
that turns "range partition" from a config flag into derived, DETERMINISTIC
splitters.

The TeraSort problem: a globally sorted output needs partition r's keys to
all precede partition r+1's, but nobody knows the key distribution before
reading the corpus. The classic answer (Coded TeraSort, arXiv:1702.04850;
every TeraSort since O'Malley's) is sampling: read a small, seeded sample
of keys from each input, merge the samples on the driver/coordinator side,
and take R−1 quantiles as range splitters. This module is that subsystem:

- :func:`sample_file` — per-input sampling: a handful of evenly spaced
  blocks (never the whole file — the pre-pass must stay O(samples), not
  O(corpus)), normalized and tokenized with the CORPUS pipeline's own
  rules (core/normalize + dictionary.extract_words, so the sample space
  is exactly the key space), edge tokens dropped (a block boundary may
  clip them), then a seeded ``random.Random`` draw.
- :func:`derive_splitters` — merge + sort all samples, take the R−1
  evenly spaced order statistics of the packed-uint64 prefixes
  (ops/partition.pack_word_prefix). Pure order statistics, no
  interpolation: splitters are always REAL sampled keys, exact uint64.
- :func:`splitters_for_job` — the one entry point drivers AND workers
  call. Everything downstream of (sorted input listing, seed,
  split_samples) is a pure function, which is the determinism contract
  the chaos ``kill`` leg tests: a re-executed map task re-derives
  bit-identical splitters from the same seeded sample, so two attempts
  of one task can never route one key to two partitions.

Skew is expected and MEASURED, not hidden: too few samples on a skewed
corpus gives uneven partitions, which shows up in the per-partition
output bytes (``stats.partition_bytes``) the doctor already scores — the
``splitter-quality`` finding names this module's knob
(``Config.split_samples`` / ``--split-samples``) as the fix.

No jax import (package rule: the pre-pass runs in backend-free worker
processes and must cost milliseconds).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Sequence

import numpy as np

from mapreduce_rust_tpu.ops.partition import pack_word_prefix

#: Fixed sampling seed: splitters must be a pure function of the corpus
#: and config so re-executed tasks agree (Config carries no seed knob on
#: purpose — a wall-clock or per-process seed here would break the
#: bit-identical-outputs invariant on every recovery path).
SPLIT_SEED = 0x517
#: Block size and per-file block count of the sampling pre-pass.
SAMPLE_BLOCK_BYTES = 64 << 10
SAMPLE_MAX_BLOCKS = 8


def sample_file(path: str | os.PathLike, samples: int,
                seed: int = SPLIT_SEED,
                file_index: int = 0) -> list[bytes]:
    """Seeded token sample from one input file: up to SAMPLE_MAX_BLOCKS
    evenly spaced SAMPLE_BLOCK_BYTES reads, tokenized with the corpus
    pipeline's rules, first/last token of each interior block dropped
    (possibly clipped by the block cut), then a ``random.Random((seed,
    file_index))`` draw of ``samples`` tokens. Deterministic for a fixed
    (path contents, samples, seed, file_index)."""
    from mapreduce_rust_tpu.core.normalize import normalize_unicode
    from mapreduce_rust_tpu.runtime.dictionary import extract_words

    try:
        size = os.path.getsize(path)
    except OSError:
        return []
    if size <= 0:
        return []
    n_blocks = min(SAMPLE_MAX_BLOCKS,
                   max(1, size // SAMPLE_BLOCK_BYTES or 1))
    pool: list[bytes] = []
    with open(path, "rb") as f:
        for b in range(n_blocks):
            # Even spacing over the file so a sorted or clustered corpus
            # still samples its whole key range, head and tail included.
            off = (size - SAMPLE_BLOCK_BYTES) * b // max(n_blocks - 1, 1) \
                if size > SAMPLE_BLOCK_BYTES else 0
            f.seek(max(off, 0))
            raw = f.read(SAMPLE_BLOCK_BYTES)
            if not raw:
                continue
            toks = extract_words(normalize_unicode(raw))
            if off > 0 and toks:
                toks = toks[1:]  # head token may be a clipped fragment
            if off + len(raw) < size and toks:
                toks = toks[:-1]  # tail token likewise
            pool.extend(toks)
    if not pool:
        return []
    # One int seed per (job seed, file): stable across interpreter
    # versions (tuple seeding is deprecated hash-based).
    rng = random.Random((int(seed) << 20) ^ int(file_index))
    if len(pool) <= samples:
        return pool
    return rng.sample(pool, samples)


def corpus_samples(inputs: Sequence[str | os.PathLike],
                   samples_per_file: int,
                   seed: int = SPLIT_SEED) -> np.ndarray:
    """Merged driver-side sample over the whole (sorted) input listing:
    uint64[n] packed word prefixes. The file index — the doc_id ordering
    contract (chunker.list_inputs) — keys each file's rng stream, so the
    merged sample is independent of which process sampled which file."""
    words: list[bytes] = []
    for i, path in enumerate(inputs):
        words.extend(sample_file(path, samples_per_file, seed=seed,
                                 file_index=i))
    return pack_word_prefix(words)


def derive_splitters(samples: np.ndarray, reduce_n: int) -> np.ndarray:
    """R−1 range splitters from merged packed-uint64 samples: the evenly
    spaced order statistics of the sorted sample. Returns uint64
    [reduce_n - 1]; an EMPTY sample yields all-max splitters (every key
    below the max sentinel routes to partition 0 — exact, maximally
    skewed, and the doctor's splitter-quality finding will say so)."""
    r = max(int(reduce_n), 1)
    if r == 1:
        return np.zeros(0, dtype=np.uint64)
    s = np.sort(np.asarray(samples, dtype=np.uint64))
    if not len(s):
        return np.full(r - 1, np.iinfo(np.uint64).max, dtype=np.uint64)
    idx = (np.arange(1, r, dtype=np.int64) * len(s)) // r
    return s[np.minimum(idx, len(s) - 1)]


def splitters_for_job(cfg, inputs: Sequence[str | os.PathLike]) -> np.ndarray:
    """THE shared sampler entry: seeded sample of every input, merged,
    reduced to cfg.reduce_n − 1 splitters. Driver run_job and every
    distributed worker call exactly this, so a re-executed task's
    splitters are bit-identical to the first attempt's — the determinism
    half of the range-partition contract (tested by the chaos kill leg,
    tests/test_sort_join.py)."""
    samples = corpus_samples(inputs, cfg.split_samples)
    return derive_splitters(samples, cfg.reduce_n)


def prepare_app(app, cfg, inputs: Sequence[str | os.PathLike],
                corpus_bounds: Sequence[int] = (), stats=None):
    """Bind the job-derived partitioning state onto the app (frozen
    dataclass → a rebound COPY): corpus bounds for multi-corpus apps
    (join's side split) and sampler-derived splitters for range apps
    (sort). Validates the app's corpus-arity contract at bind time — a
    join submitted with one corpus must fail HERE, before any lease or
    chunk. ``stats`` (a JobStats) gets the splitter pre-pass accounting
    when given."""
    bounds = tuple(int(b) for b in (corpus_bounds or ()))
    need = getattr(app, "requires_corpora", 0)
    if need and len(bounds) != need - 1:
        raise ValueError(
            f"app {app.name!r} needs exactly {need} input corpora "
            f"(got {len(bounds) + 1}); submit them as --input a=DIR b=DIR"
        )
    if getattr(app, "corpus_bounds", ()) != bounds:
        app = dataclasses.replace(app, corpus_bounds=bounds)
    if app.partition_mode == "range" \
            and len(app.splitters) != max(cfg.reduce_n - 1, 0):
        t0 = time.perf_counter()
        samples = corpus_samples(inputs, cfg.split_samples)
        spl = derive_splitters(samples, cfg.reduce_n)
        app = dataclasses.replace(
            app, splitters=tuple(int(x) for x in spl)
        )
        if stats is not None:
            stats.splitter_samples = int(len(samples))
            stats.splitter_s = time.perf_counter() - t0
    if stats is not None:
        stats.partition_mode = app.partition_mode
    return app
