"""Native host tier: C++ one-pass tokenize/dedupe/hash scanner (loader.cpp)
bridged via ctypes (host.py) with a pure-Python fallback."""

from mapreduce_rust_tpu.native.host import get_lib, scan_unique  # noqa: F401
