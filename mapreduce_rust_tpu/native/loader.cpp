// Native host scanner: tokenize + dedupe + hash one normalized byte chunk
// in a single pass. This is the ingest-side host hot loop — the dictionary
// build (runtime/dictionary.py) — which otherwise costs three C-level
// passes plus Python set churn per chunk (translate, split, set()).
//
// The reference's equivalent work is wc::map's regex strip + split
// (/root/reference/src/app/wc.rs:6-13) plus DefaultHasher per pair
// (src/mr/worker.rs:111-115) — per-record, per-task, in Rust. Here one
// C++ pass per chunk feeds the egress dictionary while the TPU does the
// counting; the byte classes and the two polynomial hash lanes MUST match
// core/hashing.py exactly (tests/test_native.py proves it).
//
// Exposed via a C ABI for ctypes (no pybind11 in this image — see
// native/host.py).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint32_t H1_MULT = 0x01000193u;  // FNV-1a prime
constexpr uint32_t H1_INIT = 0x811C9DC5u;  // FNV offset basis
constexpr uint32_t H2_MULT = 1000003u;     // CPython string-hash prime
constexpr uint32_t H2_INIT = 0x9E3779B9u;  // golden ratio

// Byte classes (core/hashing.byte_class_tables): 0 = delete (ASCII
// punctuation), 1 = word char, 2 = whitespace.
struct Tables {
  uint8_t cls[256];
  Tables() {
    for (int b = 0; b < 256; ++b) cls[b] = 0;
    const char* ws = " \t\n\r\v\f";
    for (const char* p = ws; *p; ++p) cls[(uint8_t)*p] = 2;
    for (int b = 'a'; b <= 'z'; ++b) cls[b] = 1;
    for (int b = 'A'; b <= 'Z'; ++b) cls[b] = 1;
    for (int b = '0'; b <= '9'; ++b) cls[b] = 1;
    cls[(uint8_t)'_'] = 1;
    for (int b = 0x80; b < 256; ++b) cls[b] = 1;  // UTF-8 stays in words
  }
};
const Tables kTables;

struct Slot {
  uint32_t k1, k2;
  int64_t off;   // offset into words_out
  int32_t len;
  int32_t used;
};

}  // namespace

extern "C" {

// Scan [buf, buf+len): tokenize on whitespace, delete punctuation inside
// tokens, hash the cleaned word with both lanes, deduplicate EXACTLY (hash
// pair + bytes; two different words with equal pairs stay distinct so the
// Python side can detect the collision). Outputs:
//   words_out  — cleaned unique words, concatenated (capacity >= len)
//   ends_out   — exclusive end offset of word i in words_out
//   k1/k2_out  — hash lanes of word i
// Returns the number of unique words, or -1 if max_words was too small.
int64_t mr_scan_unique(const uint8_t* buf, int64_t len,
                       uint8_t* words_out, int64_t* ends_out,
                       uint32_t* k1_out, uint32_t* k2_out,
                       int64_t max_words) {
  // Open addressing with growth: start small (typical chunks have ~1
  // unique per 30 bytes), rehash at 70% load so the probe loop always has
  // empty slots — a table that fills completely would otherwise spin
  // forever on the first non-duplicate probe.
  int64_t cap = 1024;
  while (cap < (len / 16 + 16)) cap <<= 1;
  std::vector<Slot> table((size_t)cap);
  std::memset(table.data(), 0, sizeof(Slot) * (size_t)cap);

  std::vector<uint8_t> word;
  word.reserve(256);
  int64_t n_unique = 0;
  int64_t words_len = 0;

  auto grow = [&]() {
    int64_t ncap = cap << 1;
    std::vector<Slot> ntab((size_t)ncap);
    std::memset(ntab.data(), 0, sizeof(Slot) * (size_t)ncap);
    uint64_t nmask = (uint64_t)ncap - 1;
    for (int64_t j = 0; j < cap; ++j) {
      const Slot& s = table[j];
      if (!s.used) continue;
      uint64_t i = (((uint64_t)s.k1 << 32) | s.k2) & nmask;
      while (ntab[i].used) i = (i + 1) & nmask;
      ntab[i] = s;
    }
    table.swap(ntab);
    cap = ncap;
  };

  auto flush = [&]() -> bool {
    if (word.empty()) return true;
    uint32_t h1 = H1_INIT, h2 = H2_INIT;
    for (uint8_t b : word) {
      h1 = h1 * H1_MULT + b + 1;
      h2 = h2 * H2_MULT + b + 1;
    }
    if (n_unique * 10 >= cap * 7) grow();  // keep load factor < 0.7
    uint64_t mask = (uint64_t)cap - 1;
    uint64_t i = (((uint64_t)h1 << 32) | h2) & mask;
    for (;;) {
      Slot& s = table[i];
      if (!s.used) {
        if (n_unique >= max_words) return false;
        s.used = 1;
        s.k1 = h1;
        s.k2 = h2;
        s.off = words_len;
        s.len = (int32_t)word.size();
        std::memcpy(words_out + words_len, word.data(), word.size());
        words_len += (int64_t)word.size();
        ends_out[n_unique] = words_len;
        k1_out[n_unique] = h1;
        k2_out[n_unique] = h2;
        ++n_unique;
        break;
      }
      if (s.k1 == h1 && s.k2 == h2 && s.len == (int32_t)word.size() &&
          std::memcmp(words_out + s.off, word.data(), word.size()) == 0) {
        break;  // duplicate
      }
      i = (i + 1) & mask;  // probe on (true collision or different word)
    }
    word.clear();
    return true;
  };

  for (int64_t p = 0; p < len; ++p) {
    uint8_t c = buf[p];
    uint8_t cls = kTables.cls[c];
    if (cls == 2) {
      if (!flush()) return -1;
    } else if (cls == 1) {
      word.push_back(c);
    }  // cls == 0: punctuation — deleted, does not split the token
  }
  if (!flush()) return -1;
  return n_unique;
}

}  // extern "C"
