// Native host scanner: tokenize + dedupe + hash one normalized byte chunk
// in a single pass. This is the ingest-side host hot loop — the dictionary
// build (runtime/dictionary.py) — which otherwise costs three C-level
// passes plus Python set churn per chunk (translate, split, set()).
//
// The reference's equivalent work is wc::map's regex strip + split
// (/root/reference/src/app/wc.rs:6-13) plus DefaultHasher per pair
// (src/mr/worker.rs:111-115) — per-record, per-task, in Rust. Here one
// C++ pass per chunk feeds the egress dictionary while the TPU does the
// counting; the byte classes and the two polynomial hash lanes MUST match
// core/hashing.py exactly (tests/test_native.py proves it).
//
// Exposed via a C ABI for ctypes (no pybind11 in this image — see
// native/host.py).

#include <cstdint>
#include <cstring>
#include <vector>

#ifdef __AVX2__
#include <immintrin.h>
#endif

namespace {

constexpr uint32_t H1_MULT = 0x01000193u;  // FNV-1a prime
constexpr uint32_t H1_INIT = 0x811C9DC5u;  // FNV offset basis
constexpr uint32_t H2_MULT = 1000003u;     // CPython string-hash prime
constexpr uint32_t H2_INIT = 0x9E3779B9u;  // golden ratio

// Byte classes (core/hashing.byte_class_tables): 0 = delete (ASCII
// punctuation), 1 = word char, 2 = whitespace.
struct Tables {
  uint8_t cls[256];
  Tables() {
    for (int b = 0; b < 256; ++b) cls[b] = 0;
    const char* ws = " \t\n\r\v\f";
    for (const char* p = ws; *p; ++p) cls[(uint8_t)*p] = 2;
    for (int b = 'a'; b <= 'z'; ++b) cls[b] = 1;
    for (int b = 'A'; b <= 'Z'; ++b) cls[b] = 1;
    for (int b = '0'; b <= '9'; ++b) cls[b] = 1;
    cls[(uint8_t)'_'] = 1;
    for (int b = 0x80; b < 256; ++b) cls[b] = 1;  // UTF-8 stays in words
  }
};
const Tables kTables;

// 16-byte slot, two per cache line: the duplicate test is
// (k1, k2, len, first-4-bytes) — all inline — so probing a duplicate (the
// overwhelmingly common case) costs ONE cache miss and never touches the
// words buffer. Equality short of full bytes is justified by the same
// birthday bound as the 64-bit key itself (~2^-64; SURVEY.md §7 hard part
// 3): the words ARE keyed by hash pair throughout the framework, and the
// Python layer still detects cross-word pair collisions at insert.
struct Slot {
  uint32_t k1, k2;
  uint32_t prefix;  // first up-to-4 cleaned bytes, zero-padded
  int32_t len;      // 0 = slot unused
};

// Decode one UTF-8 sequence at buf[p] (caller guarantees buf[p] >= 0x80).
// true  → valid codepoint: *cp set, *n = continuation bytes (advance n+1).
// false → invalid (stray continuation, truncation, overlong, surrogate,
//          out of range): caller advances by 1 and the byte is deleted —
//          Python's errors="replace" per bad byte, then U+FFFD → delete.
// THE single decode+validate used by every walker in this file
// (mr_normalize, mr_scan_count's hash pass and its reclean): stored word
// bytes are only correct if all walkers classify identically, so this
// logic must never be duplicated.
inline bool decode_utf8(const uint8_t* buf, int64_t len, int64_t p,
                        uint32_t* cp, int* n) {
  uint8_t c = buf[p];
  uint32_t v = 0;
  int k = 0;
  if ((c & 0xE0) == 0xC0) { v = c & 0x1F; k = 1; }
  else if ((c & 0xF0) == 0xE0) { v = c & 0x0F; k = 2; }
  else if ((c & 0xF8) == 0xF0) { v = c & 0x07; k = 3; }
  else return false;
  bool ok = (p + k < len);
  for (int j = 1; ok && j <= k; ++j) {
    if ((buf[p + j] & 0xC0) != 0x80) ok = false;
    else v = (v << 6) | (buf[p + j] & 0x3F);
  }
  if (!ok || v > 0x10FFFF || (v >= 0xD800 && v <= 0xDFFF) ||
      (k == 1 && v < 0x80) || (k == 2 && v < 0x800) || (k == 3 && v < 0x10000))
    return false;
  *cp = v;
  *n = k;
  return true;
}

}  // namespace

extern "C" {

// Scan [buf, buf+len): tokenize on whitespace, delete punctuation inside
// tokens, hash the cleaned word with both lanes, deduplicate EXACTLY (hash
// pair + bytes; two different words with equal pairs stay distinct so the
// Python side can detect the collision). Outputs:
//   words_out  — cleaned unique words, concatenated (capacity >= len)
//   ends_out   — exclusive end offset of word i in words_out
//   k1/k2_out  — hash lanes of word i
// Returns the number of unique words, or -1 if max_words was too small.
int64_t mr_scan_unique(const uint8_t* buf, int64_t len,
                       uint8_t* words_out, int64_t* ends_out,
                       uint32_t* k1_out, uint32_t* k2_out,
                       int64_t max_words) {
  // Open addressing with growth: start small (typical chunks have ~1
  // unique per 30 bytes), rehash at 70% load so the probe loop always has
  // empty slots — a table that fills completely would otherwise spin
  // forever on the first non-duplicate probe.
  int64_t cap = 1024;
  while (cap < (len / 16 + 16)) cap <<= 1;
  std::vector<Slot> table((size_t)cap);
  std::memset(table.data(), 0, sizeof(Slot) * (size_t)cap);

  // The candidate word is built IN PLACE at words_out+words_len: on insert
  // it is already where it belongs (no copy); on duplicate the next
  // candidate simply overwrites it. words_out has capacity >= len, and
  // committed + candidate bytes can never exceed the input length.
  int64_t n_unique = 0;
  int64_t words_len = 0;
  int64_t wlen = 0;  // candidate length

  auto grow = [&]() {
    int64_t ncap = cap << 1;
    std::vector<Slot> ntab((size_t)ncap);
    std::memset(ntab.data(), 0, sizeof(Slot) * (size_t)ncap);
    uint64_t nmask = (uint64_t)ncap - 1;
    for (int64_t j = 0; j < cap; ++j) {
      const Slot& s = table[j];
      if (!s.len) continue;
      uint64_t i = (((uint64_t)s.k1 << 32) | s.k2) & nmask;
      while (ntab[i].len) i = (i + 1) & nmask;
      ntab[i] = s;
    }
    table.swap(ntab);
    cap = ncap;
  };

  // Hash lanes accumulate incrementally as word bytes arrive — flush never
  // re-reads the word (one classify+hash pass over the input total).
  uint32_t h1 = H1_INIT, h2 = H2_INIT;

  auto flush = [&]() -> bool {
    if (wlen == 0) {
      h1 = H1_INIT;
      h2 = H2_INIT;
      return true;
    }
    if (n_unique * 10 >= cap * 7) grow();  // keep load factor < 0.7
    const uint8_t* cand = words_out + words_len;
    uint32_t prefix = 0;
    std::memcpy(&prefix, cand, (size_t)(wlen < 4 ? wlen : 4));
    uint64_t mask = (uint64_t)cap - 1;
    uint64_t i = (((uint64_t)h1 << 32) | h2) & mask;
    for (;;) {
      Slot& s = table[i];
      if (!s.len) {
        if (n_unique >= max_words) return false;
        s.k1 = h1;
        s.k2 = h2;
        s.prefix = prefix;
        s.len = (int32_t)wlen;
        words_len += wlen;  // bytes already in place — commit them
        ends_out[n_unique] = words_len;
        k1_out[n_unique] = h1;
        k2_out[n_unique] = h2;
        ++n_unique;
        break;
      }
      if (s.k1 == h1 && s.k2 == h2 && s.len == (int32_t)wlen && s.prefix == prefix) {
        break;  // duplicate — candidate bytes are simply overwritten next
      }
      i = (i + 1) & mask;  // probe: different word (or a true pair collision)
    }
    wlen = 0;
    h1 = H1_INIT;
    h2 = H2_INIT;
    return true;
  };

  for (int64_t p = 0; p < len; ++p) {
    uint8_t c = buf[p];
    uint8_t cls = kTables.cls[c];
    if (cls == 1) {
      words_out[words_len + wlen] = c;
      ++wlen;
      h1 = h1 * H1_MULT + c + 1;
      h2 = h2 * H2_MULT + c + 1;
    } else if (cls == 2) {
      if (!flush()) return -1;
    }  // cls == 0: punctuation — deleted, does not split the token
  }
  if (!flush()) return -1;
  return n_unique;
}

// Fused normalize + tokenize + dedupe + count over RAW UTF-8, one pass.
// Equivalent by construction to mr_normalize followed by mr_scan_unique —
// word-class bytes are hashed/appended verbatim, whitespace-class
// codepoints flush the token, delete-class codepoints vanish without
// splitting it — but touches every byte once instead of three times
// (normalize write + normalized read + scan). This is the map side of the
// host-map engine (runtime/driver.py _stream_host_map): the same token
// stream feeds the egress dictionary AND, with counts_out, the data plane
// update the TPU merges. The reference does its map exactly here too — on
// the worker CPU (src/app/wc.rs:6-13) — the framework's job being the
// shuffle/reduce behind it.
//   counts_out[i] = occurrences of unique word i in this buffer.
// Returns unique-word count, or -1 if max_words was too small.
int64_t mr_scan_count(const uint8_t* buf, int64_t len,
                      const uint8_t* cpclass,  // [0x110000]
                      uint8_t* words_out, int64_t* ends_out,
                      uint32_t* k1_out, uint32_t* k2_out, uint32_t* counts_out,
                      int64_t max_words) {
  // Start cache-sized and grow at 70% load: sizing by len/16 would build a
  // table proportional to the WINDOW (20 MB for a 16 MB window) and turn
  // every probe into a DRAM miss; typical windows have far fewer uniques
  // than bytes/16, and growth amortizes for the ones that don't.
  int64_t cap = 1 << 15;
  // 16-byte slot, four per cache line. Duplicate test is (k1, k2, len):
  // the same ~2^-64 birthday bound that justifies keying the whole
  // framework on the hash pair (SURVEY.md §7 hard part 3) — word bytes are
  // not compared here (the hot loop no longer materializes them; see
  // flush/reclean below). mr_scan_unique keeps its byte-prefix check.
  struct CSlot {
    uint32_t k1, k2;
    int32_t len;   // 0 = unused
    uint32_t idx;  // output index (counts_out[idx] is this word's count)
  };
  std::vector<CSlot> table((size_t)cap);
  std::memset(table.data(), 0, sizeof(CSlot) * (size_t)cap);

  int64_t n_unique = 0;
  int64_t words_len = 0;
  int64_t wlen = 0;
  uint32_t h1 = H1_INIT, h2 = H2_INIT;

  auto grow = [&]() {
    int64_t ncap = cap << 1;
    std::vector<CSlot> ntab((size_t)ncap);
    std::memset(ntab.data(), 0, sizeof(CSlot) * (size_t)ncap);
    uint64_t nmask = (uint64_t)ncap - 1;
    for (int64_t j = 0; j < cap; ++j) {
      const CSlot& s = table[j];
      if (!s.len) continue;
      uint64_t i = (((uint64_t)s.k1 << 32) | s.k2) & nmask;
      while (ntab[i].len) i = (i + 1) & nmask;
      ntab[i] = s;
    }
    table.swap(ntab);
    cap = ncap;
  };

  // The hot loops hash WITHOUT materializing word bytes (the store per
  // byte and its bookkeeping cost ~15% of the scan). tok_start remembers
  // where the current token began in the RAW buffer; only when a key is
  // first inserted does reclean() walk that span again to extract the
  // cleaned bytes — re-walking is rare (once per unique word) and short.
  //
  // Measured dead ends (do not re-attempt without new evidence; A/B'd on
  // this image, 16 MB inputs, min-of-5): (a) batching the hash recurrence
  // 4 bytes/step via (b+1)*M^j tables — 188→169 MB/s on the reference
  // corpus; the serial multiply chain is already hidden by OoO overlap
  // with classification, and the table loads+extra bookkeeping only add
  // work. (b) software-pipelining flush() through a prefetch ring —
  // 188→170 MB/s on text-like vocabularies (≤100K distinct, table is
  // L2-resident); it only wins (+27%, 79→103 MB/s) at ≥1M distinct keys
  // per window, a profile none of the framework's workloads have.
  int64_t tok_start = -1;

  // Re-extract the cleaned word bytes of raw span [from, to) — the same
  // classification walk as the hashing pass, emitting instead of hashing.
  auto reclean = [&](int64_t from, int64_t to, uint8_t* dst) -> int64_t {
    int64_t o = 0;
    int64_t q = from;
    while (q < to) {
      uint8_t c = buf[q];
      if (c < 0x80) {
        if (kTables.cls[c] == 1) dst[o++] = c;
        ++q;
        continue;
      }
      uint32_t cp = 0;
      int n = 0;
      if (!decode_utf8(buf, len, q, &cp, &n)) {
        ++q;
        continue;
      }
      if (cpclass[cp] == 1)
        for (int j = 0; j <= n; ++j) dst[o++] = buf[q + j];
      q += n + 1;
    }
    return o;
  };

  // Close the current token whose raw span ends at `to` (exclusive).
  auto flush = [&](int64_t to) -> bool {
    if (wlen == 0) {
      h1 = H1_INIT;
      h2 = H2_INIT;
      return true;
    }
    if (n_unique * 10 >= cap * 7) grow();
    uint64_t mask = (uint64_t)cap - 1;
    uint64_t i = (((uint64_t)h1 << 32) | h2) & mask;
    for (;;) {
      CSlot& s = table[i];
      if (!s.len) {
        if (n_unique >= max_words) return false;
        s.k1 = h1;
        s.k2 = h2;
        s.len = (int32_t)wlen;
        s.idx = (uint32_t)n_unique;
        words_len += reclean(tok_start, to, words_out + words_len);
        ends_out[n_unique] = words_len;
        k1_out[n_unique] = h1;
        k2_out[n_unique] = h2;
        counts_out[n_unique] = 1;
        ++n_unique;
        break;
      }
      if (s.k1 == h1 && s.k2 == h2 && s.len == (int32_t)wlen) {
        ++counts_out[s.idx];
        break;
      }
      i = (i + 1) & mask;
    }
    wlen = 0;
    h1 = H1_INIT;
    h2 = H2_INIT;
    return true;
  };

  // One scalar byte/codepoint step; advances p. Returns false only on
  // max_words overflow. Shared by the non-ASCII block path and the tail.
  auto scalar_step = [&](int64_t& p) -> bool {
    uint8_t c = buf[p];
    if (c < 0x80) {  // ASCII — the kTables classes
      uint8_t cls = kTables.cls[c];
      if (cls == 1) {
        if (!wlen) tok_start = p;
        ++wlen;
        h1 = h1 * H1_MULT + c + 1;
        h2 = h2 * H2_MULT + c + 1;
      } else if (cls == 2) {
        if (!flush(p)) return false;
      }
      ++p;
      return true;
    }
    // Non-ASCII: decode exactly like mr_normalize, classify via cpclass.
    uint32_t cp = 0;
    int n = 0;
    if (!decode_utf8(buf, len, p, &cp, &n)) {
      ++p;  // invalid → U+FFFD → delete, resync at the next byte
      return true;
    }
    uint8_t cls = cpclass[cp];
    if (cls == 1) {  // word codepoint: original bytes, hashed verbatim
      if (!wlen) tok_start = p;
      for (int j = 0; j <= n; ++j) {
        uint8_t wc = buf[p + j];
        ++wlen;
        h1 = h1 * H1_MULT + wc + 1;
        h2 = h2 * H2_MULT + wc + 1;
      }
    } else if (cls == 2) {
      if (!flush(p)) return false;
    }
    p += n + 1;
    return true;
  };

  int64_t p = 0;
#ifdef __AVX2__
  // SIMD fast path: classify a 64-byte all-ASCII block into word /
  // whitespace / delete BIT MASKS with eight AVX2 ops, then walk only the
  // set bits. Removes the per-byte class lookup and its mispredicted
  // 3-way branch — the scalar loop's main cost — while producing exactly
  // the same (word bytes, flush points) event stream: delete bits are
  // simply absent from both masks, so punctuation still vanishes without
  // splitting the token. Any block containing a non-ASCII byte falls back
  // to the scalar stepper for its 64 bytes (UTF-8 may step past the block
  // edge; the next SIMD load is unaligned-safe).
  while (p + 64 <= len) {
    __m256i lo = _mm256_loadu_si256((const __m256i*)(buf + p));
    __m256i hi = _mm256_loadu_si256((const __m256i*)(buf + p + 32));
    uint32_t na_lo = (uint32_t)_mm256_movemask_epi8(lo);
    uint32_t na_hi = (uint32_t)_mm256_movemask_epi8(hi);
    if (na_lo | na_hi) {  // non-ASCII somewhere in the block
      int64_t stop = p + 64;
      while (p < stop) {
        if (!scalar_step(p)) return -1;
      }
      continue;
    }
    auto classify = [](__m256i v, uint32_t& w, uint32_t& s) {
      __m256i lower = _mm256_or_si256(v, _mm256_set1_epi8(0x20));
      __m256i alpha = _mm256_and_si256(
          _mm256_cmpgt_epi8(lower, _mm256_set1_epi8('a' - 1)),
          _mm256_cmpgt_epi8(_mm256_set1_epi8('z' + 1), lower));
      __m256i digit = _mm256_and_si256(
          _mm256_cmpgt_epi8(v, _mm256_set1_epi8('0' - 1)),
          _mm256_cmpgt_epi8(_mm256_set1_epi8('9' + 1), v));
      __m256i us = _mm256_cmpeq_epi8(v, _mm256_set1_epi8('_'));
      w = (uint32_t)_mm256_movemask_epi8(
          _mm256_or_si256(_mm256_or_si256(alpha, digit), us));
      __m256i sp = _mm256_cmpeq_epi8(v, _mm256_set1_epi8(' '));
      __m256i ctl = _mm256_and_si256(
          _mm256_cmpgt_epi8(v, _mm256_set1_epi8(8)),
          _mm256_cmpgt_epi8(_mm256_set1_epi8(14), v));  // \t\n\v\f\r
      s = (uint32_t)_mm256_movemask_epi8(_mm256_or_si256(sp, ctl));
    };
    uint32_t w_lo, s_lo, w_hi, s_hi;
    classify(lo, w_lo, s_lo);
    classify(hi, w_hi, s_hi);
    uint64_t m_word = ((uint64_t)w_hi << 32) | w_lo;
    uint64_t m_ws = ((uint64_t)s_hi << 32) | s_lo;
    while (m_ws) {
      int nxt = __builtin_ctzll(m_ws);
      uint64_t seg = nxt ? (m_word & ((1ULL << nxt) - 1)) : 0;
      while (seg) {
        int i = __builtin_ctzll(seg);
        uint8_t c = buf[p + i];
        if (!wlen) tok_start = p + i;
        ++wlen;
        h1 = h1 * H1_MULT + c + 1;
        h2 = h2 * H2_MULT + c + 1;
        seg &= seg - 1;
      }
      if (nxt < 63)  // consume bits <= nxt (seg bits hashed above)
        m_word &= ~((1ULL << (nxt + 1)) - 1);
      else
        m_word = 0;
      if (!flush(p + nxt)) return -1;
      m_ws &= m_ws - 1;
    }
    while (m_word) {  // trailing word bytes after the last whitespace
      int i = __builtin_ctzll(m_word);
      uint8_t c = buf[p + i];
      if (!wlen) tok_start = p + i;
      ++wlen;
      h1 = h1 * H1_MULT + c + 1;
      h2 = h2 * H2_MULT + c + 1;
      m_word &= m_word - 1;
    }
    p += 64;
  }
#endif
  while (p < len) {
    if (!scalar_step(p)) return -1;
  }
  if (!flush(len)) return -1;
  return n_unique;
}

// Fold-shard routing (ISSUE 9): xor-shift + splitmix64-multiplier bit mix
// of the packed key, then high-bits modulo — MUST stay identical to
// runtime/dictionary.shard_of_packed (the Python fallback, sanitizer route
// check and egress lookup), or a key's folds silently split across two
// shards. The mix exists because bare `packed % S` is the low bits of the
// h2 polynomial lane, where correlated token classes collapse onto one
// shard (zero fold parallelism).
inline int64_t shard_of_packed(uint64_t packed, int64_t n_shards) {
  uint64_t x = (packed ^ (packed >> 33)) * 0x9E3779B97F4A7C15ull;
  return (int64_t)((x >> 32) % (uint64_t)n_shards);
}

// Sharded variant of mr_scan_count (ISSUE 9): the same fused
// normalize+tokenize+dedupe+count pass, then a stable counting sort that
// groups the unique-word outputs by fold shard (shard_of_packed above —
// shared with the Python fold plane). Outputs:
//   words/ends/k1/k2/counts — grouped by shard, scan order WITHIN a shard
//     (ends stay global exclusive offsets over the grouped words buffer,
//     so shard s's bytes are one contiguous slice);
//   pos_out[g]            — the ORIGINAL scan index of grouped word g: the
//     router scatters keys/counts back to exact scan order for the device
//     merge stream, which is what keeps outputs bit-identical to the
//     unsharded engine (merge/evict order never changes);
//   shard_counts_out[s]   — unique words routed to shard s.
// The grouping pass is O(n_unique + word bytes) against a scan that
// already touched every input byte — the per-word Python routing loop it
// replaces was the host-glue bottleneck this kernel exists to kill.
// Returns the unique-word count, or -1 if max_words was too small.
int64_t mr_scan_count_sharded(const uint8_t* buf, int64_t len,
                              const uint8_t* cpclass,  // [0x110000]
                              int64_t n_shards,
                              uint8_t* words_out, int64_t* ends_out,
                              uint32_t* k1_out, uint32_t* k2_out,
                              uint32_t* counts_out,
                              int64_t* pos_out, int64_t* shard_counts_out,
                              int64_t max_words) {
  for (int64_t s = 0; s < n_shards; ++s) shard_counts_out[s] = 0;
  int64_t n = mr_scan_count(buf, len, cpclass, words_out, ends_out,
                            k1_out, k2_out, counts_out, max_words);
  if (n <= 0) return n;
  if (n_shards <= 1) {
    shard_counts_out[0] = n;
    for (int64_t i = 0; i < n; ++i) pos_out[i] = i;
    return n;
  }
  std::vector<int64_t> shard_of((size_t)n);
  for (int64_t i = 0; i < n; ++i) {
    uint64_t packed = (((uint64_t)k1_out[i]) << 32) | k2_out[i];
    int64_t s = shard_of_packed(packed, n_shards);
    shard_of[i] = s;
    ++shard_counts_out[s];
  }
  // Stable grouped position per scan index (counting sort: scan order is
  // first-occurrence order, and the fold's collision policy — first word
  // wins — depends on preserving it within each shard).
  std::vector<int64_t> cur((size_t)n_shards, 0);
  for (int64_t s = 1; s < n_shards; ++s)
    cur[s] = cur[s - 1] + shard_counts_out[s - 1];
  std::vector<int64_t> gpos((size_t)n);
  for (int64_t i = 0; i < n; ++i) gpos[i] = cur[shard_of[i]]++;
  // Permute keys/counts; record the inverse (grouped -> scan) for the
  // router's device-order scatter.
  std::vector<uint32_t> tk1((size_t)n), tk2((size_t)n), tc((size_t)n);
  for (int64_t i = 0; i < n; ++i) {
    int64_t g = gpos[i];
    tk1[g] = k1_out[i];
    tk2[g] = k2_out[i];
    tc[g] = counts_out[i];
    pos_out[g] = i;
  }
  std::memcpy(k1_out, tk1.data(), sizeof(uint32_t) * (size_t)n);
  std::memcpy(k2_out, tk2.data(), sizeof(uint32_t) * (size_t)n);
  std::memcpy(counts_out, tc.data(), sizeof(uint32_t) * (size_t)n);
  // Permute the concatenated word bytes into shard-grouped order and
  // rebuild the (still global, still exclusive) end offsets.
  int64_t words_len = ends_out[n - 1];
  std::vector<int64_t> gends((size_t)n);
  for (int64_t i = 0; i < n; ++i) {
    int64_t b = i ? ends_out[i - 1] : 0;
    gends[gpos[i]] = ends_out[i] - b;  // lengths first, grouped
  }
  int64_t acc = 0;
  for (int64_t g = 0; g < n; ++g) {
    acc += gends[g];
    gends[g] = acc;
  }
  std::vector<uint8_t> wtmp((size_t)words_len);
  for (int64_t i = 0; i < n; ++i) {
    int64_t b = i ? ends_out[i - 1] : 0;
    int64_t g = gpos[i];
    int64_t gb = g ? gends[g - 1] : 0;
    std::memcpy(wtmp.data() + gb, words_out + b, (size_t)(ends_out[i] - b));
  }
  std::memcpy(words_out, wtmp.data(), (size_t)words_len);
  std::memcpy(ends_out, gends.data(), sizeof(int64_t) * (size_t)n);
  return n;
}

// Cross-window update coalescing (ISSUE 13): merge two SORTED unique-key
// (packed-uint64 key, int64 count) columns into one sorted unique-key
// column, SUMMING counts where a key appears in both — the staging-combine
// kernel of the device-merge dispatch plane. Window n's grouped scan
// result folds into the staging buffer here instead of shipping straight
// to the device: under a Zipf vocabulary most of a window's keys already
// sit in staging, so the merge dispatch that finally goes out carries one
// record per distinct key across the coalesced windows, not one per
// (window, key). Pre-summing is exact for the "sum" combine op and only
// that op — the Python side gates on it. Inputs must not alias `out_*`
// (the caller ping-pongs two staging buffers). The linear two-pointer walk
// is O(m + n) against inputs a scan already paid O(bytes) for.
// Returns the merged unique-key count (<= m + n).
int64_t mr_coalesce_updates(const uint64_t* a_keys, const int64_t* a_vals,
                            int64_t m,
                            const uint64_t* b_keys, const int64_t* b_vals,
                            int64_t n,
                            uint64_t* out_keys, int64_t* out_vals) {
  int64_t i = 0, j = 0, o = 0;
  while (i < m && j < n) {
    uint64_t ka = a_keys[i], kb = b_keys[j];
    if (ka < kb) {
      out_keys[o] = ka;
      out_vals[o++] = a_vals[i++];
    } else if (kb < ka) {
      out_keys[o] = kb;
      out_vals[o++] = b_vals[j++];
    } else {
      out_keys[o] = ka;
      out_vals[o++] = a_vals[i++] + b_vals[j++];
    }
  }
  while (i < m) {
    out_keys[o] = a_keys[i];
    out_vals[o++] = a_vals[i++];
  }
  while (j < n) {
    out_keys[o] = b_keys[j];
    out_vals[o++] = b_vals[j++];
  }
  return o;
}

// k-way disjoint merge over sorted uint64 key columns (ISSUE 11): the
// batched loser-tree egress that replaces the per-key Python heap
// interleave of the spill plane. The caller memory-maps each binary run's
// key column and hands the pointers here; one call fills up to `block`
// outputs — merged key, source index, index within source — and advances
// `cursors` (caller-owned, so the merge streams in O(block) memory however
// many keys the runs hold). Sources are key-DISJOINT by construction
// (dictionary tiers + fold shards never share a key), so no dedup exists;
// ties (impossible by that invariant, checked not assumed upstream) would
// break toward the lower source index via the <= comparisons below.
// Returns the number of outputs produced; 0 = every source exhausted.
int64_t mr_merge_runs(const uint64_t** keys, const int64_t* lens, int64_t k,
                      int64_t* cursors, uint64_t* out_keys, int32_t* out_src,
                      int64_t* out_idx, int64_t block) {
  if (k <= 0 || block <= 0) return 0;
  if (k == 1) {  // degenerate merge: a straight copy of the remainder
    int64_t n = 0;
    while (n < block && cursors[0] < lens[0]) {
      out_keys[n] = keys[0][cursors[0]];
      out_src[n] = 0;
      out_idx[n] = cursors[0];
      ++cursors[0];
      ++n;
    }
    return n;
  }
  // Loser tree over m = next-pow2(k) leaves; leaves >= k are permanently
  // exhausted sentinels. `key[s]` caches source s's current head so the
  // replay path never re-reads the (possibly page-faulting) mapped column
  // twice for one comparison. Exhaustion is a FLAG, not a sentinel key:
  // 0xFFFF...F is a legal packed key ((k1,k2) = (max,max)) — astronomically
  // unlikely, but checked, not assumed (the house rule).
  int64_t m = 1;
  while (m < k) m <<= 1;
  std::vector<uint64_t> key((size_t)k);
  std::vector<uint8_t> alive((size_t)k, 0);
  for (int64_t s = 0; s < k; ++s) {
    if (cursors[s] < lens[s]) {
      alive[s] = 1;
      key[s] = keys[s][cursors[s]];
    }
  }
  // does a beat b? (exhausted/virtual leaves lose to everything)
  auto beats = [&](int32_t a, int32_t b) -> bool {
    bool ba = a < k && alive[a], bb = b < k && alive[b];
    if (!bb) return true;
    if (!ba) return false;
    return key[a] <= key[b];
  };
  // Build: play every leaf pair up the tree, storing losers at internal
  // nodes; win[1] is the overall winner.
  std::vector<int32_t> loser((size_t)m, (int32_t)k);  // k = virtual leaf
  std::vector<int32_t> win((size_t)(2 * m));
  for (int64_t i = 0; i < m; ++i) win[m + i] = (int32_t)i;
  for (int64_t i = m - 1; i >= 1; --i) {
    int32_t a = win[2 * i], b = win[2 * i + 1];
    if (beats(a, b)) {
      win[i] = a;
      loser[i] = b;
    } else {
      win[i] = b;
      loser[i] = a;
    }
  }
  int32_t winner = win[1];
  int64_t n = 0;
  while (n < block) {
    int32_t s = winner;
    if (s >= k || !alive[s]) break;  // every source exhausted
    out_keys[n] = key[s];
    out_src[n] = s;
    out_idx[n] = cursors[s];
    ++n;
    ++cursors[s];
    if (cursors[s] < lens[s]) {
      key[s] = keys[s][cursors[s]];
    } else {
      alive[s] = 0;
    }
    // Replay only s's leaf-to-root path: O(log k) per output.
    int32_t w = s;
    for (int64_t node = (m + s) >> 1; node >= 1; node >>= 1) {
      if (!beats(w, loser[node])) {
        int32_t tmp = w;
        w = loser[node];
        loser[node] = tmp;
      }
    }
    winner = w;
  }
  return n;
}

// Normalize raw UTF-8 in one pass (the C replacement for
// core/normalize.normalize_unicode — byte-exact by contract, proven by
// tests/test_native.py):
//   - ASCII bytes pass through untouched;
//   - each non-ASCII codepoint is classified by cpclass[cp] (a table the
//     Python side builds ONCE from the same `re` \w / isspace rules):
//     1 = word char (original bytes kept verbatim), 2 = whitespace (one
//     0x20 per codepoint), 0 = delete;
//   - malformed sequences decode like Python errors="replace": each bad
//     byte run becomes U+FFFD, which classifies as delete.
// Output never exceeds the input length. Returns the normalized length.
int64_t mr_normalize(const uint8_t* buf, int64_t len,
                     const uint8_t* cpclass,  // [0x110000]
                     uint8_t* out) {
  int64_t o = 0;
  int64_t p = 0;
  while (p < len) {
    uint8_t c = buf[p];
    if (c < 0x80) {
      out[o++] = c;
      ++p;
      continue;
    }
    // Decode one UTF-8 sequence (the shared strict decoder); invalid →
    // replace (delete) and resync at the next byte, like Python's decoder.
    uint32_t cp = 0;
    int n = 0;
    if (!decode_utf8(buf, len, p, &cp, &n)) {
      ++p;  // consume just the lead byte (Python replaces per bad byte)
      continue;
    }
    uint8_t cls = cpclass[cp];
    if (cls == 1) {
      for (int j = 0; j <= n; ++j) out[o++] = buf[p + j];
    } else if (cls == 2) {
      out[o++] = 0x20;
    }
    p += n + 1;
  }
  return o;
}

}  // extern "C"
