"""ctypes bridge to the native host scanner (loader.cpp).

Builds the shared object on first use with g++ (no pybind11 in this image;
the C ABI + ctypes keeps the binding dependency-free), caches it next to
the source with an mtime check, and degrades gracefully: if the toolchain
or compile is unavailable, callers fall back to the pure-Python path
(runtime/dictionary.py works either way — tests cover both).
"""

from __future__ import annotations

import ctypes
import logging
import os
import pathlib
import subprocess
import threading  # noqa: F401 — thread-local scratch + build lock

import numpy as np

log = logging.getLogger("mapreduce_rust_tpu.native")

_SRC = pathlib.Path(__file__).with_name("loader.cpp")
_SO = pathlib.Path(__file__).with_name("_mrnative.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    try:
        if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
            return True
        # Compile to a per-process temp then atomically rename: concurrent
        # workers (README quickstart spawns several) must never observe a
        # half-written .so.
        tmp = _SO.with_name(f".{_SO.name}.{os.getpid()}.tmp")
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
               "-o", str(tmp), str(_SRC)]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native build unavailable (%s) — using Python fallback", e)
        return False


def get_lib() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not _build():
                return None
            lib = ctypes.CDLL(str(_SO))
            lib.mr_scan_unique.restype = ctypes.c_int64
            lib.mr_scan_unique.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int64,
            ]
            lib.mr_normalize.restype = ctypes.c_int64
            lib.mr_normalize.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ]
            lib.mr_scan_count.restype = ctypes.c_int64
            lib.mr_scan_count.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int64,
            ]
            lib.mr_scan_count_sharded.restype = ctypes.c_int64
            lib.mr_scan_count_sharded.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
            ]
            lib.mr_coalesce_updates.restype = ctypes.c_int64
            lib.mr_coalesce_updates.argtypes = [
                ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
            ]
            lib.mr_merge_runs.restype = ctypes.c_int64
            lib.mr_merge_runs.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
            ]
        except (OSError, AttributeError) as e:
            # AttributeError: a stale .so (fresh mtime, old ABI) missing a
            # newer symbol must engage the Python fallback, not crash.
            log.warning("native load failed (%s) — using Python fallback", e)
            return None
        _lib = lib
        return _lib


_CPCLASS_CACHE = pathlib.Path(__file__).with_name("_cpclass.npz")
_cpclass_arr: np.ndarray | None = None


def _cpclass() -> np.ndarray:
    """uint8[0x110000] codepoint classes (0 delete / 1 word / 2 space),
    built ONCE from the exact rules core/normalize.py uses (re \\w + str
    .isspace) and cached on disk — the C normalizer is table-driven so its
    semantics are definitionally identical to the Python path."""
    global _cpclass_arr
    if _cpclass_arr is not None:
        return _cpclass_arr
    import unicodedata

    fingerprint = unicodedata.unidata_version  # rebuild on Unicode-table change
    if _CPCLASS_CACHE.exists():
        try:
            with np.load(_CPCLASS_CACHE) as z:
                if str(z["unidata"]) == fingerprint:
                    _cpclass_arr = np.ascontiguousarray(z["cls"], dtype=np.uint8)
                    return _cpclass_arr
        except (OSError, KeyError, ValueError):
            pass  # corrupt/old cache — rebuild below
    import re

    cls = np.zeros(0x110000, dtype=np.uint8)
    everything = "".join(map(chr, range(0x80, 0x110000)))
    for ch in re.findall(r"\w", everything, re.UNICODE):
        cls[ord(ch)] = 1
    for i, ch in enumerate(everything):
        if cls[i + 0x80] == 0 and ch.isspace():
            cls[i + 0x80] = 2
    _cpclass_arr = cls
    try:
        # np.savez appends '.npz' unless the name already ends with it.
        tmp = _CPCLASS_CACHE.with_name(f".cpclass.{os.getpid()}.tmp.npz")
        np.savez_compressed(tmp, cls=cls, unidata=fingerprint)
        os.replace(tmp, _CPCLASS_CACHE)
    except OSError:
        pass
    return _cpclass_arr


def normalize_native(data: bytes) -> bytes | None:
    """One-pass C normalization of raw UTF-8 (byte-exact vs the Python
    path; tests/test_native.py), or None when the native lib is absent."""
    lib = get_lib()
    if lib is None:
        return None
    out = np.empty(max(len(data), 1), dtype=np.uint8)
    n = lib.mr_normalize(
        data, len(data),
        _cpclass().ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out[: int(n)].tobytes()


_scratch = threading.local()

# Arena accounting: one scratch arena per scan thread means the host-map
# engine's memory cost scales with host_map_workers, not with the corpus —
# the registry makes that price observable (stats.host_arena_bytes / the
# run manifest) instead of folklore. Entries are keyed by the words buffer
# and removed by a weakref finalizer when the arena is collected (thread
# death frees its thread-locals), so the gauge tracks LIVE arenas only.
_arena_lock = threading.Lock()
_arena_sizes: dict[int, int] = {}


def _arena_release(key: int) -> None:
    with _arena_lock:
        _arena_sizes.pop(key, None)


def arena_bytes() -> int:
    """Total bytes of live per-thread scan scratch arenas in this process."""
    with _arena_lock:
        return sum(_arena_sizes.values())


def arena_count() -> int:
    """How many threads currently hold a scan scratch arena."""
    with _arena_lock:
        return len(_arena_sizes)


_sanitize_cached: "bool | None" = None


def _sanitizing() -> bool:
    """MR_SANITIZE resolved once per process — _buffers is per-scan hot."""
    global _sanitize_cached
    if _sanitize_cached is None:
        from mapreduce_rust_tpu.analysis.sanitize import sanitize_enabled

        _sanitize_cached = sanitize_enabled()
    return _sanitize_cached


def _buffers(n: int, max_words: int):
    """Per-thread reusable scratch (allocating ~10 MB of numpy buffers per
    call costs ~40% of the scan; scan results are copied out before the
    next call on the same thread can overwrite them)."""
    import weakref

    bufs = getattr(_scratch, "bufs", None)
    if bufs is not None and _sanitizing():
        # Thread-locals survive os.fork(): a child reusing the parent's
        # arena would scribble over (and read) another process's scan
        # state. The sanitizer turns that silent aliasing into a raise.
        from mapreduce_rust_tpu.analysis.sanitize import check_arena_owner

        check_arena_owner(*_scratch.owner)
    if bufs is None or bufs[0].size < n + 1 or bufs[1].size < max_words:
        bufs = (
            np.empty(max(n + 1, 1 << 20), dtype=np.uint8),
            np.empty(max(max_words, 1 << 18), dtype=np.int64),
            np.empty(max(max_words, 1 << 18), dtype=np.uint32),
            np.empty(max(max_words, 1 << 18), dtype=np.uint32),
            np.empty(max(max_words, 1 << 18), dtype=np.uint32),
            # grouped->scan position map of the sharded scan (ISSUE 9);
            # rides in the arena so the gauge prices the sharded engine too
            np.empty(max(max_words, 1 << 18), dtype=np.int64),
        )
        key = id(bufs[0])
        with _arena_lock:
            _arena_sizes[key] = sum(int(b.nbytes) for b in bufs)
        weakref.finalize(bufs[0], _arena_release, key)
        _scratch.bufs = bufs
        _scratch.owner = (os.getpid(), threading.get_ident())
    return bufs


def scan_count_raw(
    data: "bytes | np.ndarray",
) -> tuple[bytes, np.ndarray, np.ndarray, np.ndarray] | None:
    """(concatenated unique words, int64[n] end offsets, uint32[n,2] hash
    pairs, uint32[n] occurrence counts) over RAW un-normalized UTF-8 — the
    fused one-pass map kernel of the host-map engine, or None when the
    native lib is unavailable. Byte-exact equivalent of
    normalize_unicode → scan_unique_raw plus per-word counting
    (tests/test_native.py proves the equivalence).

    Accepts bytes or a uint8 numpy view (e.g. a memory-mapped window) —
    the view path copies nothing on the way in."""
    lib = get_lib()
    if lib is None:
        return None
    empty = (
        b"",
        np.empty(0, dtype=np.int64),
        np.empty((0, 2), dtype=np.uint32),
        np.empty(0, dtype=np.uint32),
    )
    buf = data if isinstance(data, np.ndarray) else np.frombuffer(data, dtype=np.uint8)
    buf = np.ascontiguousarray(buf, dtype=np.uint8)  # views stay zero-copy
    n = int(buf.size)
    if n == 0:
        return empty
    max_words = n // 2 + 2
    words_buf, ends, k1, k2, counts, _pos = _buffers(n, max_words)
    count = lib.mr_scan_count(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        _cpclass().ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        words_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        k1.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        k2.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        max_words,
    )
    if count < 0:  # cannot happen with max_words = n//2+2; belt and braces
        return None
    count = int(count)
    if not count:
        return empty
    raw = words_buf[: int(ends[count - 1])].tobytes()
    return (
        raw,
        ends[:count].copy(),
        np.stack([k1[:count], k2[:count]], axis=1),
        counts[:count].copy(),
    )


def scan_count_sharded_raw(
    data: "bytes | np.ndarray", n_shards: int,
) -> "tuple[bytes, np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray] | None":
    """Sharded fused scan (ISSUE 9): like :func:`scan_count_raw` but the
    unique-word outputs come back GROUPED by fold shard (shard = packed
    key % n_shards, scan order preserved within a shard), plus

    - ``pos``          int64[n] — original scan index of grouped word i
      (the driver scatters keys/counts back to exact scan order for the
      device merge, keeping outputs bit-identical to the unsharded path);
    - ``shard_counts`` int64[n_shards] — uniques per shard, so shard s's
      slice is rows [cum[s], cum[s+1]) and its word bytes are one
      contiguous span of the returned buffer.

    Returns None when the native lib is unavailable (callers fall back to
    the pure-Python scan + per-shard selection)."""
    lib = get_lib()
    if lib is None:
        return None
    shard_counts = np.zeros(max(int(n_shards), 1), dtype=np.int64)
    empty = (
        b"",
        np.empty(0, dtype=np.int64),
        np.empty((0, 2), dtype=np.uint32),
        np.empty(0, dtype=np.uint32),
        np.empty(0, dtype=np.int64),
        shard_counts,
    )
    buf = data if isinstance(data, np.ndarray) else np.frombuffer(data, dtype=np.uint8)
    buf = np.ascontiguousarray(buf, dtype=np.uint8)  # views stay zero-copy
    n = int(buf.size)
    if n == 0:
        return empty
    max_words = n // 2 + 2
    words_buf, ends, k1, k2, counts, pos = _buffers(n, max_words)
    count = lib.mr_scan_count_sharded(
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        _cpclass().ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        int(n_shards),
        words_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        k1.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        k2.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        pos.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        shard_counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        max_words,
    )
    if count < 0:  # cannot happen with max_words = n//2+2; belt and braces
        return None
    count = int(count)
    if not count:
        return empty
    raw = words_buf[: int(ends[count - 1])].tobytes()
    return (
        raw,
        ends[:count].copy(),
        np.stack([k1[:count], k2[:count]], axis=1),
        counts[:count].copy(),
        pos[:count].copy(),
        shard_counts,
    )


def coalesce_updates_into(a_keys, a_vals, m: int, b_keys, b_vals,
                          out_keys, out_vals) -> "int | None":
    """Native staging combine (ISSUE 13: loader.cpp ``mr_coalesce_updates``):
    merge sorted unique-key column ``a[:m]`` with sorted unique-key column
    ``b`` into caller-owned ``out_*`` (capacity >= m + len(b)), summing
    counts on duplicate keys. All arrays must be contiguous uint64/int64
    and ``out_*`` must not alias either input (the dispatch plane
    ping-pongs two staging buffers). Returns the merged count, or None
    when the native lib is unavailable (callers fall back to the
    vectorized numpy merge in runtime/driver.py)."""
    lib = get_lib()
    if lib is None or not hasattr(lib, "mr_coalesce_updates"):
        return None
    n = len(b_keys)
    return int(lib.mr_coalesce_updates(
        a_keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        a_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        int(m),
        b_keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        b_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        out_keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        out_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    ))


def merge_runs_stream(key_arrays, block: int = 1 << 16):
    """Generator of (keys uint64[b], src int32[b], idx int64[b]) blocks
    merged over K sorted key-disjoint uint64 columns — the native
    loser-tree egress (ISSUE 11: loader.cpp ``mr_merge_runs``). Streams in
    O(block) memory however large the runs are (columns may be memory
    maps: the kernel reads them sequentially, so the OS pages them
    through). Returns None when the native lib is unavailable — callers
    fall back to the vectorized argsort merge (runtime/spill.py)."""
    lib = get_lib()
    if lib is None:
        return None
    arrays = [np.ascontiguousarray(a, dtype=np.uint64) for a in key_arrays]
    k = len(arrays)

    def gen():
        ptrs = (ctypes.c_void_p * k)(*[a.ctypes.data for a in arrays])
        lens = np.asarray([len(a) for a in arrays], dtype=np.int64)
        cursors = np.zeros(k, dtype=np.int64)
        out_keys = np.empty(block, dtype=np.uint64)
        out_src = np.empty(block, dtype=np.int32)
        out_idx = np.empty(block, dtype=np.int64)
        while True:
            n = int(lib.mr_merge_runs(
                ptrs,
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                k,
                cursors.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                out_keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                out_src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                block,
            ))
            if n <= 0:
                return
            # Copies: the kernel reuses the out buffers next call, and the
            # consumer may hold a block across iterations.
            yield out_keys[:n].copy(), out_src[:n].copy(), out_idx[:n].copy()

    return gen()


def scan_unique_raw(data: bytes) -> tuple[bytes, np.ndarray, np.ndarray] | None:
    """(concatenated unique words, int64[n] exclusive end offsets,
    uint32[n,2] hash pairs) — or None when the native lib is unavailable.
    One C pass: tokenize, dedupe, hash. The caller slices individual words
    lazily (runtime/dictionary.py slices only keys it hasn't seen)."""
    lib = get_lib()
    if lib is None:
        return None
    if not data:
        return b"", np.empty(0, dtype=np.int64), np.empty((0, 2), dtype=np.uint32)
    n = len(data)
    max_words = n // 2 + 2
    words_buf, ends, k1, k2, _counts, _pos = _buffers(n, max_words)
    count = lib.mr_scan_unique(
        data, n,
        words_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        k1.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        k2.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        max_words,
    )
    if count < 0:  # cannot happen with max_words = n//2+2; belt and braces
        return None
    count = int(count)
    raw = words_buf[: int(ends[count - 1])].tobytes() if count else b""
    return raw, ends[:count].copy(), np.stack([k1[:count], k2[:count]], axis=1)


def scan_unique(data: bytes) -> tuple[list[bytes], np.ndarray] | None:
    """(unique cleaned words, uint32[n,2] hash pairs) — list form of
    scan_unique_raw, for callers that want materialized words."""
    res = scan_unique_raw(data)
    if res is None:
        return None
    raw, ends, keys = res
    words = []
    start = 0
    for end in ends.tolist():
        words.append(raw[start:end])
        start = end
    return words, keys
