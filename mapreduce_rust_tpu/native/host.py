"""ctypes bridge to the native host scanner (loader.cpp).

Builds the shared object on first use with g++ (no pybind11 in this image;
the C ABI + ctypes keeps the binding dependency-free), caches it next to
the source with an mtime check, and degrades gracefully: if the toolchain
or compile is unavailable, callers fall back to the pure-Python path
(runtime/dictionary.py works either way — tests cover both).
"""

from __future__ import annotations

import ctypes
import logging
import os
import pathlib
import subprocess
import threading

import numpy as np

log = logging.getLogger("mapreduce_rust_tpu.native")

_SRC = pathlib.Path(__file__).with_name("loader.cpp")
_SO = pathlib.Path(__file__).with_name("_mrnative.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    try:
        if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
            return True
        # Compile to a per-process temp then atomically rename: concurrent
        # workers (README quickstart spawns several) must never observe a
        # half-written .so.
        tmp = _SO.with_name(f".{_SO.name}.{os.getpid()}.tmp")
        cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
               "-o", str(tmp), str(_SRC)]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native build unavailable (%s) — using Python fallback", e)
        return False


def get_lib() -> ctypes.CDLL | None:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not _build():
                return None
            lib = ctypes.CDLL(str(_SO))
            lib.mr_scan_unique.restype = ctypes.c_int64
            lib.mr_scan_unique.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int64,
            ]
        except OSError as e:
            log.warning("native load failed (%s) — using Python fallback", e)
            return None
        _lib = lib
        return _lib


def scan_unique(data: bytes) -> tuple[list[bytes], np.ndarray] | None:
    """(unique cleaned words, uint32[n,2] hash pairs) — or None if the
    native path is unavailable. One C pass: tokenize, dedupe, hash."""
    lib = get_lib()
    if lib is None or not data:
        return ([], np.empty((0, 2), dtype=np.uint32)) if lib and not data else None
    n = len(data)
    max_words = n // 2 + 2
    words_buf = np.empty(n + 1, dtype=np.uint8)
    ends = np.empty(max_words, dtype=np.int64)
    k1 = np.empty(max_words, dtype=np.uint32)
    k2 = np.empty(max_words, dtype=np.uint32)
    count = lib.mr_scan_unique(
        data, n,
        words_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        k1.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        k2.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        max_words,
    )
    if count < 0:  # cannot happen with max_words = n//2+2; belt and braces
        return None
    count = int(count)
    raw = words_buf[: int(ends[count - 1])].tobytes() if count else b""
    words = []
    start = 0
    for i in range(count):
        end = int(ends[i])
        words.append(raw[start:end])
        start = end
    keys = np.stack([k1[:count], k2[:count]], axis=1)
    return words, keys
