"""Worker runtime: pull-based task loop + map/reduce task execution.

Behavioral port of the reference worker (src/bin/mrworker.rs:43-151 loop,
src/mr/worker.rs:65-193 engines), with the data plane swapped for this
framework's kernels and one reference bug class fixed throughout:

- Task loop: register (get_worker_id), then a two-state machine — map
  phase until get_map_task returns -1, then reduce phase until
  get_reduce_task returns -1, then exit (mrworker.rs:115-118). Sentinels
  -2/-3 sleep poll_retry_s and retry (mrworker.rs:51-58).
- Lease renewal: an asyncio task renewing every lease_renew_period_s —
  including on the map side, fixing the reference's no-sleep busy flood
  (mrworker.rs:87-93); a False renewal (stale) just stops the loop.
- Map task m: stream input file m through the chunker, tokenize+combine
  (device engine: the jitted kernels; host engine: the C-speed extract +
  Counter path — the faithful CPU-baseline worker), partition the final
  per-task table by k1 % reduce_n, and write one spill file per partition
  plus a dictionary shard — the mr-{m}-{r}.txt protocol of the reference
  (worker.rs:117-140) with npz arrays instead of text lines, written
  temp+rename so task re-execution is atomic (the reference's
  File::create truncation can interleave with a replacement worker,
  SURVEY.md §3-D).
- Reduce task r: load every map's partition-r spill, fold exactly
  (HostAccumulator), merge dictionary shards, emit sorted lines to
  mr-{r}.txt (worker.rs:157-193 — including the last key group, which the
  reference drops).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import pathlib
import signal as _signal
import sys
import threading
import time
import uuid

import numpy as np

from mapreduce_rust_tpu.analysis.chaos import ChaosPlan
from mapreduce_rust_tpu.apps import get_app
from mapreduce_rust_tpu.apps.base import App
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.coordinator.server import (
    DONE,
    NOT_READY,
    WAIT,
    ClockSync,
    CoordinatorClient,
    RpcTimeout,
)
from mapreduce_rust_tpu.core.hashing import hash_words
from mapreduce_rust_tpu.runtime.backoff import Backoff, BackoffExhausted
from mapreduce_rust_tpu.runtime.chunker import chunk_stream
from mapreduce_rust_tpu.runtime.dictionary import Dictionary, extract_words
from mapreduce_rust_tpu.runtime.metrics import (
    start_metrics,
    stop_metrics,
)
from mapreduce_rust_tpu.runtime.telemetry import JobReport
from mapreduce_rust_tpu.runtime.trace import (
    maybe_snapshot,
    partial_path,
    per_process_path,
    start_tracing,
    stop_tracing,
    trace_flow,
    trace_instant,
    trace_span,
)

log = logging.getLogger("mapreduce_rust_tpu.worker")


def _atomic_write(path: pathlib.Path, write_fn) -> None:
    """Write-temp-then-rename with a per-writer-unique temp name: a lease
    straggler and its replacement can execute the same task concurrently
    (SURVEY.md §3-D), so the temp file must never be shared."""
    tmp = path.with_name(f".{path.name}.{os.getpid()}.{uuid.uuid4().hex[:8]}.tmp")
    write_fn(tmp)
    os.replace(tmp, path)


def _atomic_savez(path: pathlib.Path, **arrays) -> None:
    def _w(tmp: pathlib.Path) -> None:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)

    _atomic_write(path, _w)


class Worker:
    def __init__(self, cfg: Config, app: App | None = None, engine: str = "host") -> None:
        if engine not in ("host", "device"):
            raise ValueError(f"unknown engine {engine!r}")
        self.cfg = cfg
        self.app = app or get_app("word_count")
        self.engine = engine
        # Multi-corpus input API (ISSUE 15): the flat doc_id space
        # concatenates every corpus's sorted listing; prepare_app binds
        # the boundaries (join's side split) and — for range apps —
        # derives splitters from the SHARED seeded sampler, so every
        # worker process and every re-executed attempt routes keys
        # identically (the chaos kill leg's determinism contract).
        from mapreduce_rust_tpu.runtime.chunker import resolve_corpora
        from mapreduce_rust_tpu.runtime.splitter import prepare_app

        self.inputs, bounds, _names = resolve_corpora(cfg)
        self.app = prepare_app(self.app, cfg, self.inputs, bounds)
        self.work = pathlib.Path(cfg.work_dir)
        self.out = pathlib.Path(cfg.output_dir)
        self.worker_id: int | None = None
        # Worker-side control-plane telemetry, symmetric with the
        # coordinator's: tasks this worker ran (grant → finish, durations)
        # and CLIENT-observed RPC latencies — which include the network and
        # the coordinator's event loop, so comparing against the server-side
        # numbers in the `stats` RPC isolates where a slow RPC spends.
        self.report = JobReport()
        # NTP-style offset to the coordinator's clock, shared by every
        # client this worker opens (renewal connections included): lands in
        # the manifest and trace metadata for `trace merge`.
        self.sync = ClockSync()
        self._attempts: dict[tuple[str, int], int] = {}  # (phase, tid) → n
        # Deterministic fault injection (analysis/chaos.py): None unless
        # Config.chaos / MR_CHAOS carries a spec. Every site below calls
        # self._chaos(...) — one checkpoint, one trigger log.
        self.chaos = ChaosPlan.from_config(cfg)
        # Graceful drain (SIGTERM): set (thread-safely — signal handlers
        # and executor threads both touch it) to finish the current task,
        # report it, deregister, and exit cleanly between tasks.
        self._drain = threading.Event()
        self.drained = False
        # Tasks whose lease was REVOKED mid-compute (a speculative race
        # lost): their finish report is skipped — the winner already
        # journaled — and the manifest lists them.
        self.revoked_tasks: list[str] = []
        # Worker-side data-plane stats (a real JobStats, sanitized under
        # MR_SANITIZE — ISSUE 7 satellite; this replaced a SimpleNamespace
        # shim): bytes mapped, per-task wall histogram, and the
        # device-memory high water _sample_device_memory records. Written
        # from the event-loop thread (memory samples between tasks) AND
        # from executor pool threads (per-task accounting) — every one of
        # which must register_writer first; see _execute_task.
        from mapreduce_rust_tpu.analysis.sanitize import new_job_stats

        self.stats = new_job_stats(cfg)
        self.registry = None  # THIS worker's live registry (ISSUE 8);
        # the process-global slot is unreliable under in-process
        # co-hosted workers, so every worker-side tick/ship uses this.
        # Job-service context (ISSUE 14): the job id of the task currently
        # being executed. None for the classic single-job worker; the
        # ServiceWorker sets it per job so task flow-chain ids carry the
        # same ``<jid>:`` prefix the service-side coordinator emits (two
        # jobs' ``map:0:1`` chains must never merge into one arrow).
        self._job_ctx: "str | None" = None
        # Per-reduce-partition intermediate bytes of the map task just
        # executed (ISSUE 16): stashed by _run_map_task on the executor
        # thread, popped by _execute_granted and shipped on the finish
        # report as a TRAILING default RPC field — the coordinator turns
        # it into partition-readiness instants for the fleet profiler.
        # MR_FLEET=0 disables the shipping (telemetry only: outputs are
        # bit-identical either way).
        self._part_bytes: dict[int, list] = {}
        self._fleet_enabled = os.environ.get("MR_FLEET", "1") != "0"
        # Provenance (ISSUE 20): per-map-task chunk content digests,
        # stashed like _part_bytes and shipped as one more trailing
        # default field on the finish report — the coordinator appends
        # them to {work}/lineage.jsonl as attempt records. Opt-in
        # (Config.lineage / MR_LINEAGE); observational only.
        from mapreduce_rust_tpu.runtime.lineage import lineage_forced

        self._task_chunks: dict[int, list] = {}
        self._lineage_on = cfg.lineage or lineage_forced()
        self._scan_digests: list = []  # executor thread, reset per task

    def _metrics_tick(self) -> None:
        """Sampler tick on this worker's own registry (the global
        metrics_tick() would sample whichever co-hosted worker installed
        the slot last)."""
        reg = self.registry
        if reg is not None:
            reg.maybe_sample()

    def _metrics_collect(self) -> dict:
        """Pull source for the live registry (ISSUE 8): the worker-side
        series that ship to the coordinator in the renewal envelope and
        land in this worker's manifest ring. Plain attribute/dict reads —
        benign against the executor threads that write them."""
        h = self.stats.hists.get("worker.task_s")
        return {
            "worker.bytes_in": self.stats.bytes_in,
            "worker.tasks_done": h.count if h is not None else 0,
            "worker.task_s_sum": round(h.total, 6) if h is not None else 0.0,
            "worker.device_mem_high_bytes": self.stats.device_mem_high_bytes,
            "worker.revoked_tasks": len(self.revoked_tasks),
            # Wait split (folded per task, executor thread): the live
            # doctor aggregates these fleet-wide into its bottleneck
            # attribution (diagnose_live._WAIT_FIELDS).
            "worker.host_map_s": round(self.stats.host_map_s, 6),
            "worker.host_glue_s": round(self.stats.host_glue_s, 6),
            "worker.ingest_wait_s": round(self.stats.ingest_wait_s, 6),
            "worker.device_wait_s": round(self.stats.device_wait_s, 6),
            "worker.scan_wait_s": round(self.stats.scan_wait_s, 6),
            "worker.all_to_all_s": round(self.stats.all_to_all_s, 6),
        }

    @property
    def _wid(self) -> int:
        """Worker id for RPC attribution (-1 = not yet registered: the
        coordinator treats it as anonymous, never a phantom worker row)."""
        return self.worker_id if self.worker_id is not None else -1

    def request_drain(self) -> None:
        """Graceful drain: finish the current task, report it, deregister,
        exit 0. Thread- and signal-safe (a threading.Event, checked at
        task boundaries — never mid-compute). The CLI wires SIGTERM here."""
        # The requester may be a signal handler or an embedding's watcher
        # thread the stats object has never seen; the drain bookkeeping it
        # triggers (final memory sample, manifest fields) must not trip
        # the sanitizer's registered-writer gate (ISSUE 7 satellite: the
        # drain path was an unregistered writer).
        self.stats.register_writer()
        trace_instant("worker.drain_requested")
        self._drain.set()

    def _chaos_pick(self, site: str, **ctx):
        """The single chaos checkpoint: returns the matching Fault (or
        None), logging + tracing every trigger so the injected fault is
        visible in the timeline next to its recovery."""
        if self.chaos is None:
            return None
        f = self.chaos.pick(site, **ctx)
        if f is not None:
            trace_instant(f"chaos.{site}",
                          **{k: v for k, v in ctx.items() if v is not None})
            log.warning("chaos: injecting %s (%s)", site, ctx)
        return f

    def _sample_memory(self) -> None:
        """Device-memory gauge from the worker task loop (PR 5 leftover):
        only when a backend is ALREADY INITIALIZED in this process (the
        device engine's first task does that). Merely-imported jax is not
        enough — jax.local_devices() on an uninitialized process would
        TRIGGER backend init, and against an absent accelerator that is a
        ~minutes-long metadata probe; a telemetry gauge must never be the
        thing that wedges a worker."""
        jax = sys.modules.get("jax")
        if jax is None:
            return
        try:
            from jax._src import xla_bridge

            if not xla_bridge._backends:
                return
        except Exception:
            return  # unknown jax layout: skip the gauge, never the task
        from mapreduce_rust_tpu.runtime.driver import _sample_device_memory

        _sample_device_memory(self.stats)

    # ---- map/reduce engines ----

    def _map_table(self, doc_id: int, path: str) -> tuple[dict, Dictionary]:
        """(key-pair → combined value, dictionary shard) for one input file."""
        from mapreduce_rust_tpu.analysis.sanitize import new_dictionary

        dictionary = new_dictionary(self.cfg)
        op = self.app.combine_op
        self._scan_digests = []  # fresh provenance per task (ISSUE 20)
        if self.engine == "device":
            # Device-engine tasks ship no chunk list: windows stream
            # through _IngestStream, whose recorder is the driver-side
            # process-global ledger (absent in a worker process).
            return self._map_table_device(doc_id, path, dictionary)
        if op in ("sum", "distinct"):
            fast = self._map_table_host_native(doc_id, path, dictionary)
            if fast is not None:
                return fast, dictionary
        # A native pass that bailed mid-file recorded partial windows;
        # the fallback re-reads from byte 0, so restart the digest list.
        self._scan_digests = []
        # Fallback (no native lib, or an op the fused scan doesn't model):
        # the reference's exact per-task work (wc::map + combiner) in Python.
        counts: collections.Counter = collections.Counter()
        with open(path, "rb") as f:
            for chunk in chunk_stream(f, doc_id, self.cfg.chunk_bytes):
                payload = bytes(chunk.data[: chunk.nbytes])
                if self._lineage_on:
                    from mapreduce_rust_tpu.runtime.lineage import chunk_digest

                    self._scan_digests.append(chunk_digest(payload))
                words = extract_words(payload)
                counts.update(words)
        table: dict = {}
        uniq = list(counts.keys())
        keys = hash_words(uniq)
        mask = self.app.host_mask(keys) if len(uniq) else None
        kept_words: list = []
        for i, (w, (k1, k2)) in enumerate(zip(uniq, keys.tolist())):
            if mask is not None and not mask[i]:
                continue  # filtering app: not a query key (nor a dict entry)
            kept_words.append(w)
            key = (k1, k2)
            if op == "sum":
                table[key] = table.get(key, 0) + counts[w]
            elif op == "distinct":
                table.setdefault(key, set()).add(doc_id)
            else:  # max/min of count within the task — app-defined payloads
                table[key] = counts[w]
        dictionary.add_words(kept_words)
        return table, dictionary

    def _map_table_host_native(self, doc_id: int, path: str,
                               dictionary: Dictionary):
        """Map one input with the fused native scan (the driver host-map
        engine's kernel, native/loader.cpp mr_scan_count): one pass over
        raw bytes per window instead of normalize+extract+Counter. Returns
        None when the native lib is unavailable."""
        from mapreduce_rust_tpu.native.host import scan_count_raw
        from mapreduce_rust_tpu.runtime.driver import _iter_windows
        from mapreduce_rust_tpu.runtime.metrics import JobStats

        op = self.app.combine_op
        table: dict = {}
        from mapreduce_rust_tpu.runtime.driver import fold_scan_into_dictionary

        for _doc, window in _iter_windows(self.cfg, [path], JobStats()):
            if self._lineage_on:
                # Same raw-window digest the driver's host-map engine
                # records — a re-executed attempt (same cfg, same file)
                # must produce the identical chunk list, which is what
                # mrcheck's lineage-conservation equality checks.
                from mapreduce_rust_tpu.runtime.lineage import chunk_digest

                self._scan_digests.append(chunk_digest(window))
            res = scan_count_raw(window)
            if res is None:
                return None
            raw, ends, keys, counts = res
            mask = self.app.host_mask(keys)
            fold_scan_into_dictionary(dictionary, mask, "raw", (raw, ends, keys))
            if mask is not None:  # filtering app: keep query keys only
                keys, counts = keys[mask], counts[mask]
            if op == "sum":
                for (k1, k2), c in zip(keys.tolist(), counts.tolist()):
                    key = (k1, k2)
                    table[key] = table.get(key, 0) + c
            else:  # distinct: the value set is this doc id
                for k1, k2 in keys.tolist():
                    table.setdefault((k1, k2), set()).add(doc_id)
        return table

    def _map_table_device(self, doc_id: int, path: str, dictionary: Dictionary):
        from mapreduce_rust_tpu.analysis.sanitize import new_job_stats
        from mapreduce_rust_tpu.runtime.driver import HostAccumulator, _stream_single

        acc = HostAccumulator(self.app.combine_op)
        task_stats = new_job_stats(self.cfg)
        _stream_single(self.cfg, self.app, [path], task_stats, acc,
                       dictionary, doc_id_offset=doc_id)
        # Fold the task-local wait split into the worker's stats (executor
        # thread: a registered writer) so the renewal envelope ships a real
        # per-worker wait breakdown — the live doctor's fleet-wide
        # bottleneck attribution reads exactly these fields (ISSUE 8).
        for field in ("host_map_s", "host_glue_s", "ingest_wait_s",
                      "device_wait_s", "scan_wait_s", "all_to_all_s"):
            setattr(self.stats, field,
                    getattr(self.stats, field) + getattr(task_stats, field))
        return acc.table, dictionary

    def _chaos_task_entry(self, phase: str, tid: int, att: int) -> None:
        """Injection sites at task entry (runs on the executor thread, so
        sleeps here never starve the event loop or the renewal heartbeat):
        ``slow_scan`` — this worker computes N s slower per task (the
        heterogeneous-fleet straggler); ``kill`` — SIGKILL mid-task, lease
        held, nothing reported (the crash the lease detector exists for)."""
        f = self._chaos_pick("slow_scan", phase=phase, tid=tid, attempt=att,
                             wid=self._wid)
        if f is not None:
            time.sleep(f.seconds)
        f = self._chaos_pick("kill", phase=phase, tid=tid, attempt=att,
                             wid=self._wid)
        if f is not None:
            maybe_snapshot()  # the flight recorder keeps what we had
            os.kill(os.getpid(), _signal.SIGKILL)

    def _chaos_before_finish(self, phase: str, tid: int, att: int) -> None:
        """``pause`` site: sleep before the task returns — the task is
        DONE computing but holds its lease, renewals keep flowing. The
        slow-but-alive straggler only speculation (or patience) beats."""
        f = self._chaos_pick("pause", phase=phase, tid=tid, attempt=att,
                             wid=self._wid)
        if f is not None:
            time.sleep(f.seconds)

    def _task_fid(self, phase: str, tid: int, att: int) -> str:
        """Flow-chain id of this attempt — job-prefixed under the service
        (mirrors Coordinator._fid, the other end of the same arrow)."""
        base = f"{phase}:{tid}:{att}"
        return f"{self._job_ctx}:{base}" if self._job_ctx else base

    def _job_args(self) -> dict:
        return {"job": self._job_ctx} if self._job_ctx else {}

    def run_map_task(self, tid: int) -> None:
        att = self._attempts.get(("map", tid), 1)
        with trace_span("worker.map_task", tid=tid, attempt=att):
            # The flow step links this span into the coordinator's grant →
            # ... → finish-report chain; the instant survives in a flight-
            # recorder partial even though the span itself is only recorded
            # at task exit (a SIGKILLed attempt leaves the begin mark).
            trace_flow("task", "t", self._task_fid("map", tid, att),
                       phase="map", tid=tid, **self._job_args())
            trace_instant("worker.task_begin", phase="map", tid=tid, attempt=att)
            self._chaos_task_entry("map", tid, att)
            self._run_map_task(tid)
            self._chaos_before_finish("map", tid, att)

    def _run_map_task(self, tid: int) -> None:
        path = self.inputs[tid]
        # Data-plane accounting on the executor thread (the sanitizer's
        # registered-writer gate covers this — _execute_task registered).
        try:
            self.stats.bytes_in += os.path.getsize(path)
        except OSError:
            pass
        t_map = time.perf_counter()
        table, dictionary = self._map_table(tid, path)
        if self.engine != "device":
            # Per-task (never per-record) scan accounting: the device path
            # folds its own exact wait split in _map_table_device; the
            # host/python paths book the whole table build as scan time so
            # the renewal envelope still ships a usable host_map_s series.
            self.stats.host_map_s += time.perf_counter() - t_map
        self.work.mkdir(parents=True, exist_ok=True)
        op = self.app.combine_op
        reduce_n = self.cfg.reduce_n
        # Partition routing goes through the app seam (ISSUE 15): hash
        # apps keep k1 % reduce_n; range apps (sort) need the WORD to
        # searchsorted their sampler-bound splitters — resolved in one
        # vectorized route_block sweep over the task dictionary's sorted
        # stream (iter_sorted serves spilled dictionaries too), keeping
        # only a hash→partition INT per key, never a second copy of the
        # word bytes. A key the dictionary somehow lost routes to
        # partition 0 — the same key would be an unknown_keys count at
        # egress.
        route = self.app.route
        part_of: "dict | None" = None
        if self.app.partition_mode == "range":
            part_of = {}
            blk_keys: list = []
            blk_words: list = []

            def _route_blk() -> None:
                if blk_words:
                    rr = self.app.route_block(
                        blk_words, [k1 for k1, _ in blk_keys], reduce_n
                    )
                    part_of.update(zip(blk_keys, rr))
                    blk_keys.clear()
                    blk_words.clear()

            for _packed, k1, k2, word in dictionary.iter_sorted():
                blk_keys.append((k1, k2))
                blk_words.append(word)
                if len(blk_words) >= (1 << 16):
                    _route_blk()
            _route_blk()
        parts: dict[int, list] = {r: [] for r in range(reduce_n)}
        for (k1, k2), v in table.items():
            r = part_of.get((k1, k2), 0) if part_of is not None \
                else route(None, k1, reduce_n)
            if op == "distinct":
                for d in sorted(v):
                    parts[r].append((k1, k2, d))
            else:
                parts[r].append((k1, k2, v))
        part_bytes = [0] * reduce_n
        for r, rows in parts.items():
            arr = np.asarray(rows, dtype=np.int64).reshape(-1, 3)
            # 4+4+8 bytes per row as written (k1/k2 uint32, value int64) —
            # the intermediate-shard payload this map task contributes to
            # partition r, independent of npz container overhead.
            part_bytes[r] = 16 * arr.shape[0]
            _atomic_savez(
                self.work / f"mr-{tid}-{r}.npz",
                k1=arr[:, 0].astype(np.uint32),
                k2=arr[:, 1].astype(np.uint32),
                value=arr[:, 2].astype(np.int64),
            )
        if self._fleet_enabled:
            self._part_bytes[tid] = part_bytes
        if self._lineage_on and self._scan_digests:
            self._task_chunks[tid] = list(self._scan_digests)
        # Dictionary shards are partitioned by the same app route as the
        # spills, so reduce task r reads exactly its own words —
        # mirroring the mr-{m}-{r} protocol (src/mr/worker.rs:121).
        # iter_sorted, not items(): it serves the WHOLE dictionary whether
        # or not a budget flush spilled words to disk runs (items() raises
        # on a spilled instance — mrlint rule spilled-dict-api caught this).
        dict_parts: dict[int, Dictionary] = {r: Dictionary() for r in range(reduce_n)}
        for _packed, k1, k2, word in dictionary.iter_sorted():
            r = part_of.get((k1, k2), 0) if part_of is not None \
                else route(word, k1, reduce_n)
            dict_parts[r]._word_of[(k1, k2)] = word
        for r, dp in dict_parts.items():
            dp.collisions = list(dictionary.collisions) if r == 0 else []
            _atomic_write(self.work / f"dict-{tid}-{r}.txt", dp.save)
        log.info("map %d: %s → %d keys, %d dict words", tid, path, len(table), len(dictionary))

    def run_reduce_task(self, tid: int) -> None:
        att = self._attempts.get(("reduce", tid), 1)
        with trace_span("worker.reduce_task", tid=tid, attempt=att):
            trace_flow("task", "t", self._task_fid("reduce", tid, att),
                       phase="reduce", tid=tid, **self._job_args())
            trace_instant("worker.task_begin", phase="reduce", tid=tid, attempt=att)
            self._chaos_task_entry("reduce", tid, att)
            self._run_reduce_task(tid)
            self._chaos_before_finish("reduce", tid, att)

    def _run_reduce_task(self, tid: int) -> None:
        from mapreduce_rust_tpu.analysis.sanitize import new_dictionary
        from mapreduce_rust_tpu.runtime.driver import HostAccumulator

        acc = HostAccumulator(self.app.combine_op)
        dictionary = new_dictionary(self.cfg)
        for m in range(len(self.inputs)):
            spill = self.work / f"mr-{m}-{tid}.npz"
            with np.load(spill) as z:
                keys = np.stack([z["k1"], z["k2"]], axis=1)
                acc.add(keys, z["value"])
            dictionary.merge(Dictionary.load(self.work / f"dict-{m}-{tid}.txt"))
        is_distinct = self.app.combine_op == "distinct"
        items = []
        for key, v in acc.table.items():
            word = dictionary.lookup(*key)
            if word is None:
                continue
            items.append((word, sorted(v) if is_distinct else v, key))
        lines = self.app.finalize_partition(items, tid)
        self.out.mkdir(parents=True, exist_ok=True)
        tmp = self.out / f"mr-{tid}.txt.tmp"
        with open(tmp, "wb") as f:
            for line in lines:
                f.write(line + b"\n")
        os.replace(tmp, self.out / f"mr-{tid}.txt")
        log.info("reduce %d: %d keys → mr-%d.txt", tid, len(items), tid)

    # ---- task loop ----

    async def _main(self, client: CoordinatorClient) -> bool:
        """The pull loop proper — between registration and teardown.
        Returns True when the worker exited because a DRAIN was
        requested. The classic two-phase machine here; the ServiceWorker
        overrides this with the multi-job loop (same setup/teardown).
        Under ``--sched pipeline`` (ISSUE 17) the two phases interleave
        instead — coordinator and workers must agree on the mode."""
        wid = self.worker_id
        if self.cfg.sched_pipeline:
            log.info("worker %d: pipelined map+reduce loop", wid)
            return await self._run_pipelined(client)
        log.info("worker %d: map phase", wid)
        draining = await self._run_phase(
            client, "get_map_task", "renew_map_lease",
            "report_map_task_finish", self.run_map_task)
        if not draining:
            log.info("worker %d: reduce phase", wid)
            draining = await self._run_phase(
                client, "get_reduce_task", "renew_reduce_lease",
                "report_reduce_task_finish", self.run_reduce_task)
        return draining

    async def _run_pipelined(self, client: CoordinatorClient) -> bool:
        """Interleaved pull loop (``--sched pipeline``, ISSUE 17): one
        poll round asks the map side first and, when it has nothing to
        give right now (WAIT — every map issued, stragglers in flight),
        asks for per-partition-released reduce work, so this worker
        starts reducing ready partitions while other workers' map tasks
        are still running. DONE from the reduce side ends the job; DONE
        from the map side just stops asking it. Same drain/backoff/
        teardown contract as _run_phase."""
        poll = Backoff(
            base_s=self.cfg.poll_retry_s,
            cap_s=self.cfg.effective_poll_retry_cap_s(),
            jitter=0.25,
        )
        map_done = False
        while True:
            if self._drain.is_set():
                return True  # between tasks: nothing held, nothing owed
            try:
                if not map_done:
                    tid = await self._call_with_retry(
                        client, "get_map_task", self._wid)
                    if tid == DONE:
                        map_done = True
                    elif tid not in (NOT_READY, WAIT):
                        poll.reset()
                        att = client.last_attempt or 1
                        if not await self._execute_granted(
                                client, "map", tid, att, "renew_map_lease",
                                "report_map_task_finish", self.run_map_task):
                            return False
                        continue  # map side is hot — ask it again first
                tid = await self._call_with_retry(
                    client, "get_reduce_task", self._wid)
            except ConnectionError:
                log.info("coordinator gone — assuming job complete")
                return False
            if tid == DONE:
                return False
            if tid not in (NOT_READY, WAIT):
                poll.reset()
                att = client.last_attempt or 1
                if not await self._execute_granted(
                        client, "reduce", tid, att, "renew_reduce_lease",
                        "report_reduce_task_finish", self.run_reduce_task):
                    return False
                continue
            maybe_snapshot()
            self._metrics_tick()
            self._sample_memory()
            await asyncio.sleep(poll.next_delay())

    def _execute_task(self, run_task, tid: int) -> None:
        """Executor-thread task wrapper: per-task data-plane accounting +
        the post-task device-memory sample, from the thread that just ran
        the compute. The pool hands SPECULATIVE attempts to whatever
        thread is free — often one the stats object has never seen — so
        each task registers its own thread as a writer (ISSUE 7
        satellite: the speculation fork was an unregistered writer under
        MR_SANITIZE=1)."""
        self.stats.register_writer()
        t0 = time.perf_counter()
        run_task(tid)
        self.stats.record_hist("worker.task_s", time.perf_counter() - t0)
        # Post-compute sample on THIS thread: the device engine's high
        # water peaks during the task, which the between-task event-loop
        # sample misses.
        self._sample_memory()

    async def _call(self, client: CoordinatorClient, method: str, *params):
        """client.call with the round-trip latency recorded (client-observed:
        network + coordinator event loop + handler)."""
        t0 = time.perf_counter()
        try:
            return await client.call(method, *params)
        finally:
            self.report.record_rpc(method, time.perf_counter() - t0)

    def _phase_name(self, method: str) -> str:
        return "map" if "map" in method else "reduce"

    async def _renewal_loop(self, client: CoordinatorClient, method: str,
                            tid: int, stop: asyncio.Event,
                            revoked: "asyncio.Event | None" = None,
                            job: "str | None" = None) -> None:
        # ``stop`` backs up task cancellation: on Python < 3.12,
        # asyncio.wait_for SWALLOWS a cancel that lands just as its inner
        # future completes (bpo-42130) — with the per-call rpc timeout
        # wrapping readline in wait_for, a renewal loop cancelled at
        # exactly a response boundary would keep renewing forever, the
        # lease would never expire, and the task's finish report would
        # never be sent: a distributed deadlock. The flag makes the exit
        # condition level-triggered instead of edge-triggered.
        #
        # ``revoked`` is the speculation-loser signal (ISSUE 6): a failed
        # renewal whose envelope says revoked=True means another attempt
        # already COMPLETED this task — set the event so the task loop
        # skips the finish report (the winner journaled; ours would only
        # land as a late report of work nobody needs).
        phase = self._phase_name(method)
        att = self._attempts.get((phase, tid), 1)
        try:
            if self._chaos_pick("wedge_renewal", phase=phase, tid=tid,
                                attempt=att, wid=self._wid) is not None:
                # Wedged heartbeat thread: the task keeps computing but no
                # renewal ever goes out — the lease expires under a LIVE
                # task and our eventual report lands late. stop.wait()
                # (not a sleep loop) so teardown stays immediate.
                await stop.wait()
                return
            while not stop.is_set():
                await asyncio.sleep(self.cfg.lease_renew_period_s)
                if stop.is_set():
                    return
                # Latest metrics sample rides the renewal envelope as a
                # TRAILING arg (ISSUE 8) — same wire-compat trick as wid:
                # an in-process/pre-metrics caller omits it and the
                # coordinator's default applies. Computed before the call
                # (cheap flat dict), shipped only when metrics are on.
                # THIS worker's registry, never the process-global slot:
                # in-process co-hosted workers replace the global, and a
                # sample shipped under the wrong wid would show every
                # worker with the last-started worker's stats.
                # ``job`` (ISSUE 14) is the OUTERMOST trailing arg — a
                # service renewal always ships 4 params (sample may be
                # None) so the job id keeps its position; the single-job
                # wire format below is untouched.
                reg = self.registry
                if job is not None:
                    ok = await self._call(
                        client, method, tid, self._wid,
                        reg.ship_sample() if reg is not None else None, job,
                    )
                elif reg is not None:
                    ok = await self._call(client, method, tid, self._wid,
                                          reg.ship_sample())
                else:
                    ok = await self._call(client, method, tid, self._wid)
                if stop.is_set():
                    return  # a swallowed cancel still exits here
                self.report.record_renewal(phase, tid, bool(ok), wid=self._wid)
                # Snapshot AFTER the renewal is on the wire: under GIL
                # contention with the compute thread the snapshot's IO can
                # take 100s of ms, and the heartbeat must never queue
                # behind telemetry (a delayed renewal is a lease expiry).
                maybe_snapshot()
                self._metrics_tick()
                if not ok:
                    if revoked is not None and client.last_revoked:
                        revoked.set()
                        log.info("%s %d attempt %d revoked — another "
                                 "attempt won", phase, tid, att)
                    return  # stale lease (already reported) — just stop
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        except RpcTimeout as e:
            # A wedged coordinator: stop renewing — the lease expires
            # server-side (if the coordinator ever recovers) and our own
            # eventual finish report lands as a late_report. The task
            # itself keeps computing; only the heartbeat is dead.
            log.warning("renewal loop for %s %d stopped: %s", phase, tid, e)

    async def _call_with_retry(self, client: CoordinatorClient, method: str,
                               *params):
        """Task-loop RPC with transient-failure hardening: an RpcTimeout
        (wedged or momentarily stalled coordinator) retries on a fresh
        connection under jittered exponential backoff with a budget —
        then surfaces the timeout. ConnectionError is NOT retried: a
        vanished coordinator means the job completed (the caller's
        long-standing heuristic), and retrying it for a full budget would
        turn every clean shutdown into a minute-long stall. Safe to
        retry: grants self-heal via lease expiry, finish reports are
        idempotent per (phase, tid)."""
        backoff = Backoff(
            self.cfg.rpc_backoff_base_s, self.cfg.rpc_backoff_cap_s,
            budget_s=self.cfg.rpc_backoff_budget_s,
        )
        while True:
            try:
                return await self._call(client, method, *params)
            except RpcTimeout as e:
                try:
                    delay = backoff.next_delay()
                except BackoffExhausted:
                    raise e from None
                log.warning("%s: %s — retrying in %.2fs (attempt %d)",
                            method, e, delay, backoff.attempts)
                await asyncio.sleep(delay)
                # The old connection is poisoned (a late response would
                # desync request ids): reconnect before the retry. A
                # refused reconnect = coordinator genuinely gone — the
                # ConnectionError propagates to the caller's done path.
                await client.close()
                await client.connect(
                    budget_s=self.cfg.rpc_backoff_budget_s
                )

    async def _run_phase(self, client: CoordinatorClient, get: str, renew: str,
                         report: str, run_task) -> bool:
        """One phase of the pull loop. Returns True when the worker exited
        because a DRAIN was requested (the caller then deregisters and
        skips any remaining phase) — False on normal phase completion."""
        phase = self._phase_name(get)
        # Sentinel (-2/-3) polling backs off exponentially from
        # poll_retry_s up to the cap instead of hammering at a fixed rate;
        # a real grant resets the envelope.
        poll = Backoff(
            base_s=self.cfg.poll_retry_s,
            cap_s=self.cfg.effective_poll_retry_cap_s(),
            jitter=0.25,
        )
        while True:
            if self._drain.is_set():
                return True  # between tasks: nothing held, nothing owed
            try:
                # The worker id rides on every task RPC so the coordinator
                # attributes grants/renewals/finishes per worker (the
                # `watch` worker column + doctor straggler input).
                tid = await self._call_with_retry(client, get, self._wid)
            except ConnectionError:
                # Coordinator exited between our WAIT poll and this call —
                # the job completed while we slept. A clean end, not a crash.
                # (ConnectionError only: other OSErrors — fd exhaustion,
                # network flaps — must surface, not fake success. An
                # RpcTimeout — wedged, not gone — propagates once the
                # retry budget is spent.)
                log.info("coordinator gone — assuming job complete")
                return False
            if tid == DONE:
                return False
            if tid in (NOT_READY, WAIT):
                maybe_snapshot()
                self._metrics_tick()
                self._sample_memory()
                await asyncio.sleep(poll.next_delay())
                continue
            poll.reset()
            # The grant response carried the coordinator's attempt number:
            # the task span joins that attempt's flow chain, and the
            # worker's own event log records the same attempt the
            # coordinator's does (mrcheck reads either side uniformly).
            att = client.last_attempt or 1
            if not await self._execute_granted(client, phase, tid, att,
                                               renew, report, run_task):
                return False

    async def _execute_granted(self, client: CoordinatorClient, phase: str,
                               tid: int, att: int, renew: str, report: str,
                               run_task, job: "str | None" = None) -> bool:
        """One granted task end to end — renewal heartbeat on its own
        connection, compute on the executor, speculation-revocation
        handling, the chaos finish sites, and the idempotent finish
        report. Shared by the single-job phase loop and the ServiceWorker
        (``job`` = the service job id, threaded onto the renewal/report
        RPCs as the trailing arg). Returns False when the coordinator
        vanished mid-report — job complete, the caller stops its loop."""
        self.report.record_grant(phase, tid, wid=self._wid, attempt=att)
        self._attempts[(phase, tid)] = att
        fid = self._task_fid(phase, tid, att)
        # Separate connection for renewals, like the reference's
        # spawned renewal task (mrworker.rs:70-94) — but paced.
        renew_client = CoordinatorClient(
            self.cfg.host, self.cfg.port,
            timeout_s=self.cfg.rpc_timeout_s, sync=self.sync,
        )
        await renew_client.connect()
        stop_renewal = asyncio.Event()
        revoked = asyncio.Event()
        renewal = asyncio.create_task(
            self._renewal_loop(renew_client, renew, tid, stop_renewal,
                               revoked, job=job)
        )
        try:
            # Heavy compute off the event loop so renewals keep flowing.
            await asyncio.get_running_loop().run_in_executor(
                None, self._execute_task, run_task, tid
            )
        finally:
            # Flag first, then cancel: see _renewal_loop on why cancel
            # alone can be swallowed mid-RPC on Python < 3.12.
            stop_renewal.set()
            renewal.cancel()
            await asyncio.gather(renewal, return_exceptions=True)
            await renew_client.close()
        self._sample_memory()
        if revoked.is_set():
            # Speculation loser: another attempt already completed and
            # journaled this task. Terminate OUR flow chain (the lost
            # race stays visible in the merged timeline) and never
            # send the finish report — the coordinator-side journal
            # must hold exactly one line per task.
            trace_flow("task", "f", fid, phase=phase, tid=tid, revoked=True,
                       **self._job_args())
            self.revoked_tasks.append(fid)
            log.info("%s %d: dropping finish report (revoked)", phase, tid)
            maybe_snapshot()
            return True
        f = self._chaos_pick("delay_finish", phase=phase, tid=tid,
                             attempt=att, wid=self._wid)
        if f is not None:
            await asyncio.sleep(f.seconds)
        if self._chaos_pick("drop_finish", phase=phase, tid=tid,
                            attempt=att, wid=self._wid) is not None:
            # The report never leaves this worker: the coordinator
            # sees only silence, the lease expires, the task re-runs
            # (atomic spill rewrites keep the rerun bit-identical).
            log.warning("%s %d: finish report dropped (chaos)", phase, tid)
        else:
            params = [tid, self._attempts.get((phase, tid), 0), self._wid]
            part_bytes = self._part_bytes.pop(tid, None) \
                if phase == "map" else None
            lineage = self._task_chunks.pop(tid, None) \
                if phase == "map" else None
            if lineage is not None:
                # One more trailing default after part_bytes (ISSUE 20):
                # the attempt's chunk digests, appended by the
                # coordinator to the job's lineage.jsonl. part_bytes
                # must fill its slot (possibly None — MR_FLEET=0).
                params.extend([job, part_bytes, {"chunks": lineage}])
            elif part_bytes is not None:
                # Trailing default fields, wid/sample-style: old servers
                # never see them, old clients stay wire-valid. ``job``
                # must fill its slot (possibly None) so part_bytes lands
                # as the 5th positional on both Coordinator and service.
                params.extend([job, part_bytes])
            elif job is not None:
                params.append(job)
            try:
                await self._call_with_retry(client, report, *params)
            except ConnectionError:
                # The coordinator exited while we computed: under
                # speculation a revoked loser can outlive the whole
                # JOB (another attempt won, every phase closed, the
                # coordinator left before our renewal could observe
                # the revocation). Our result is unneeded — terminate
                # the chain as revoked and end like the poll path.
                trace_flow("task", "f", fid, phase=phase, tid=tid,
                           revoked=True, **self._job_args())
                self.revoked_tasks.append(fid)
                log.info("%s %d: coordinator gone before finish report "
                         "— job complete, dropping it", phase, tid)
                return False
        self.report.record_finish(phase, tid, wid=self._wid,
                                  attempt=self._attempts.get((phase, tid)))
        maybe_snapshot()
        self._metrics_tick()
        return True

    async def run(self) -> None:
        # The loop thread may not be the thread that CONSTRUCTED this
        # worker (embedding harnesses run asyncio off-thread): its
        # between-task memory samples write stats, so it registers.
        self.stats.register_writer()
        # The worker honors Config.trace_path/manifest_path like the driver
        # does, under per-process names (several workers share one Config).
        tag = f"w{os.getpid()}"
        tracer = start_tracing(tag=tag) if self.cfg.trace_path else None
        if tracer is not None:
            tracer.clock_sync = self.sync  # live object: snapshots carry
            # whatever offset estimate exists at snapshot time
            tracer.enable_flight_recorder(
                partial_path(per_process_path(self.cfg.trace_path, tag)),
                period_s=self.cfg.flight_record_period_s,
            )
        # Live metrics (ISSUE 8): sampled from the renewal/poll loops into
        # this worker's ring (→ manifest stats.timeseries) and shipped to
        # the coordinator in the renewal envelope for the fleet-wide view.
        registry = None
        if self.cfg.metrics_enabled:
            # start_metrics installs the global slot too (the OS-process
            # case: build_manifest and engine-side ticks read it), but
            # every worker-side use goes through self.registry — the
            # global is last-writer-wins under in-process co-hosting.
            registry = self.registry = start_metrics(
                self.cfg.metrics_sample_period_s,
                self.cfg.metrics_ring_points,
            )
            registry.add_collector(self._metrics_collect)
            if tracer is not None:
                tracer.metrics_registry = registry
        client = CoordinatorClient(
            self.cfg.host, self.cfg.port,
            timeout_s=self.cfg.rpc_timeout_s, sync=self.sync,
        )
        await client.connect()
        try:
            wid = await client.call("get_worker_id")
            if wid == DONE:
                log.info("coordinator full — exiting")
                return
            self.worker_id = wid
            draining = await self._main(client)
            if draining:
                # Graceful drain: the current task is finished and
                # reported; deregister so watch/progress show DRAINED
                # instead of a silence the lease detector must diagnose.
                self.drained = True
                try:
                    await self._call(client, "deregister_worker", self._wid)
                except (ConnectionError, RpcTimeout):
                    pass  # coordinator gone/wedged: drain proceeds anyway
                log.info("worker %d: drained (%s)", wid, self.report.summary())
            else:
                log.info("worker %d: done (%s)", wid, self.report.summary())
        finally:
            await client.close()
            if tracer is not None:
                stop_tracing()
            from mapreduce_rust_tpu.runtime.telemetry import flush_run_artifacts

            extra = {
                "kind": "worker_manifest",
                "worker_id": self.worker_id,
                "engine": self.engine,
                "report": self.report.to_dict(),
                # NTP-style offset to the coordinator clock (offset ±
                # RTT/2): the stitcher's cross-process rebase evidence.
                "clock_sync": self.sync.best(),
                "drained": self.drained,
                # Worker-loop device-memory high water (PR 5 leftover; 0 on
                # backends without memory_stats or when jax never loaded).
                "device_mem_high_bytes": self.stats.device_mem_high_bytes,
                # Worker data-plane stats (ISSUE 7 satellite): bytes this
                # worker mapped + its per-task wall histogram — written
                # from registered executor threads only.
                "worker_stats": {
                    "bytes_in": self.stats.bytes_in,
                    "task_s": {
                        name: h.to_dict()
                        for name, h in sorted(self.stats.hists.items())
                    },
                },
            }
            if self.revoked_tasks:
                extra["revoked_tasks"] = self.revoked_tasks
            if self.chaos is not None:
                # The honest record of which injected faults actually
                # fired (a SIGKILLed worker can't write this — its faults
                # are visible as the crash itself).
                extra["chaos"] = {
                    "spec": self.chaos.spec,
                    "fired": self.chaos.fired(),
                }

            def _flush() -> None:
                flush_run_artifacts(
                    self.cfg, tracer, tag=f"w{os.getpid()}", logger=log,
                    extra=extra,
                )

            # Off the event loop (mrlint: blocking-in-async): the flush
            # shells out to git and writes trace/manifest files — nothing
            # else on this loop should stall behind teardown telemetry.
            await asyncio.get_running_loop().run_in_executor(None, _flush)
            if registry is not None:
                # After the flush: build_manifest serialized the ring from
                # the still-active registry. Compare-and-clear — a
                # co-hosted worker may own the global slot by now.
                stop_metrics(registry)
                self.registry = None


class ServiceWorker(Worker):
    """Multi-job worker for the JobService (ISSUE 14): one registration,
    then a single ``get_task`` pull across EVERY running job — the grant
    arrives job-tagged ({job, phase, tid, attempt}) and the worker
    switches its task context (app, inputs, namespaced work/output dirs,
    reduce_n) per job from a cached ``job_spec`` fetch. Task execution,
    renewal heartbeats, speculation revocation, chaos sites and the
    manifest teardown are the inherited single-job machinery — only the
    loop shape changes (jobs interleave instead of phases sequencing).

    Per-job-end teardown (ISSUE 14 satellite): switching jobs trims the
    driver's ``_PACKED_FNS`` jit cache — the PR 11 hook that used to run
    only at run_job/process end, which a long-lived multi-job worker
    would otherwise defeat."""

    #: Spec-cache bound: a fleet member that serves thousands of jobs
    #: over days must not hoard one spec dict per job forever (the
    #: _PACKED_FNS leak class, applied to the control plane). LRU — a
    #: dropped spec is just one job_spec RPC away.
    SPEC_CACHE_MAX = 64

    def __init__(self, cfg: Config, engine: str = "host") -> None:
        super().__init__(cfg, engine=engine)
        self._base_cfg = cfg
        self._specs: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self._current_job: "str | None" = None

    async def _main(self, client: CoordinatorClient) -> bool:
        wid = self.worker_id
        log.info("worker %d: service loop", wid)
        poll = Backoff(
            base_s=self.cfg.poll_retry_s,
            cap_s=self.cfg.effective_poll_retry_cap_s(),
            jitter=0.25,
        )
        while True:
            if self._drain.is_set():
                return True  # between tasks: nothing held, nothing owed
            try:
                grant = await self._call_with_retry(client, "get_task",
                                                    self._wid)
            except ConnectionError:
                # Service exited (drained) between polls — a clean end.
                log.info("service gone — exiting")
                return False
            if grant == DONE:
                return False  # drained and empty: the fleet goes home
            if not isinstance(grant, dict):
                # WAIT/NOT_READY: nothing grantable across any job.
                maybe_snapshot()
                self._metrics_tick()
                self._sample_memory()
                await asyncio.sleep(poll.next_delay())
                continue
            poll.reset()
            jid = grant.get("job")
            phase = grant.get("phase")
            tid = grant.get("tid")
            att = int(grant.get("attempt") or 1)
            if not isinstance(jid, str) or phase not in ("map", "reduce") \
                    or not isinstance(tid, int):
                log.warning("malformed grant %r — skipping", grant)
                await asyncio.sleep(poll.next_delay())
                continue
            if not await self._enter_job(client, jid):
                continue  # job closed between grant and spec fetch
            is_map = phase == "map"
            ok = await self._execute_granted(
                client, phase, tid, att,
                "renew_map_lease" if is_map else "renew_reduce_lease",
                "report_map_task_finish" if is_map
                else "report_reduce_task_finish",
                self.run_map_task if is_map else self.run_reduce_task,
                job=jid,
            )
            if not ok:
                return False

    async def _enter_job(self, client: CoordinatorClient, jid: str) -> bool:
        """Switch the task context to ``jid`` (no-op when already there):
        fetch + cache its spec, tear down the previous job's jit cache,
        and swap app/inputs/dirs. False = the job vanished (done or
        cancelled between the grant and this fetch) — skip the grant; its
        lease expires server-side."""
        if jid == self._current_job:
            return True
        spec = self._specs.get(jid)
        if spec is None:
            try:
                spec = await self._call_with_retry(client, "job_spec", jid)
            except ConnectionError:
                return False
            if not isinstance(spec, dict) or not spec.get("ok"):
                log.warning("job %s: spec unavailable (%s) — skipping grant",
                            jid, (spec or {}).get("error"))
                await asyncio.sleep(self.cfg.poll_retry_s)
                return False
            self._specs[jid] = spec
            while len(self._specs) > self.SPEC_CACHE_MAX:
                self._specs.popitem(last=False)
        else:
            self._specs.move_to_end(jid)  # LRU: reuse refreshes recency
        if self._current_job is not None:
            self._job_teardown()
        self._apply_spec(spec)
        self._current_job = jid
        return True

    def _job_teardown(self) -> None:
        """Per-job-end teardown: bound the jit packed-merge cache NOW, not
        at process exit (ISSUE 14 satellite — the PR 11 hook). Lazy on the
        driver module: a host-engine worker that never compiled anything
        must not import jax for a cache trim."""
        drv = sys.modules.get("mapreduce_rust_tpu.runtime.driver")
        if drv is not None:
            try:
                drv.trim_packed_fns()
            except Exception:  # teardown telemetry must never kill a task
                pass

    def _apply_spec(self, spec: dict) -> None:
        import dataclasses

        from mapreduce_rust_tpu.apps import get_app
        from mapreduce_rust_tpu.runtime.chunker import resolve_corpora
        from mapreduce_rust_tpu.runtime.splitter import prepare_app

        kwargs = dict(spec.get("app_args") or {})
        if spec["app"] == "grep":
            kwargs["query"] = tuple(kwargs.get("query") or ())
        if spec["app"] == "top_k" and "k" in kwargs:
            kwargs["k"] = int(kwargs["k"])
        self.app = get_app(spec["app"], **kwargs)
        # Multi-corpus jobs ship their ordered (name, dir) list in the
        # spec (ISSUE 15); classic jobs keep the single input_dir form.
        corpora = spec.get("inputs")
        self.cfg = dataclasses.replace(
            self._base_cfg,
            map_n=max(int(spec["map_n"]), 1),
            reduce_n=int(spec["reduce_n"]),
            # From the SPEC, never this worker's CLI default: two fleet
            # members sampling different counts would derive different
            # splitters for one sort job and route one key two ways.
            split_samples=int(spec.get("split_samples") or 512),
            input_dir=spec["input_dir"],
            input_dirs=(
                tuple((str(n), str(d)) for n, d in corpora)
                if corpora else None
            ),
            input_pattern=spec["input_pattern"],
            work_dir=spec["work_dir"],
            output_dir=spec["output_dir"],
        )
        self.inputs, bounds, _names = resolve_corpora(self.cfg)
        # Range apps re-derive splitters HERE, from the same seeded
        # sampler as every other fleet member — no splitter exchange RPC,
        # no divergence: the sample is a pure function of the listing.
        self.app = prepare_app(self.app, self.cfg, self.inputs, bounds)
        self.work = pathlib.Path(spec["work_dir"])
        self.out = pathlib.Path(spec["output_dir"])
        self._job_ctx = spec["job"]
        # Stamp this job onto the worker's OWN event-log rows too: the
        # report spans every job this worker serves, and un-stamped rows
        # would interleave two jobs' (phase, tid) histories under one
        # machine in mrcheck's replay (report identity stays the
        # worker's — row_job, not job_id).
        self.report.row_job = spec["job"]
