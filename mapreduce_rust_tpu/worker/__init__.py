"""Worker runtime: pull-based task loop, map/reduce engines, spill files."""

from mapreduce_rust_tpu.worker.runtime import Worker  # noqa: F401
