"""mrlint — framework-invariant static analysis + runtime sanitizer.

Every rule in this package is grounded in a bug this repo actually
shipped and later fixed by hand (see rules.py per-rule docstrings for the
incident each one encodes). The package has two halves:

- ``lint``/``rules``: an AST-based analyzer run as
  ``python -m mapreduce_rust_tpu lint`` — the static side, wired into
  tier-1 via tests/test_lint_clean.py so the invariants are machine-checked
  on every commit instead of rediscovered per PR.
- ``sanitize``: the opt-in dynamic companion (``Config.sanitize`` /
  ``MR_SANITIZE=1``) — thread-ownership asserts on JobStats, the egress
  Dictionary and the native scan arenas, catching at runtime the ownership
  violations the static rules can't prove structurally.

No jax import anywhere in this package: the linter must run in a
backend-free process (CI, pre-commit) in milliseconds.
"""

from mapreduce_rust_tpu.analysis.lint import (  # noqa: F401
    Finding,
    LintReport,
    lint_paths,
    load_baseline,
)
from mapreduce_rust_tpu.analysis.rules import ALL_RULES  # noqa: F401
