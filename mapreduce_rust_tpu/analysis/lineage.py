"""mrlineage queries (ISSUE 20): forward/backward provenance + blast radius.

The jax-free half of the provenance plane. ``runtime/lineage.py`` writes
the ledger during a run; this module answers questions about it after —
in any process, without initializing a backend (the lint/doctor/mrcheck
doctrine; tests/test_lineage.py gates the no-jax property):

- **forward**: chunk (ledger seq or digest prefix) → the reduce
  partitions its routed keys contributed to — "if this chunk changes,
  which outputs move?"
- **backward**: reduce partition → the contributing chunk set (digests,
  bytes, docs) plus the attempt chain that scanned them — "which input
  bytes does this output depend on, and who computed it?"
- **diff**: two ledgers (old run, new run) → recompute blast radius: the
  changed-chunk set, the affected-partition fraction, and the headline
  ``memo_hit_frac`` — the byte-weighted fraction of the NEW corpus whose
  chunks already existed (digest-identical) in the old run, i.e. exactly
  the work a chunk-level memo tier (ROADMAP item 4) would not redo.

Targets are flexible: a ``lineage.jsonl`` path, a work dir containing
one, a run manifest (``stats.lineage.path``), or a flight-recorder
``*.partial.json`` (its embedded lineage tail) — so a SIGKILLed run's
provenance resolves from the partial alone. Ledger parsing distrusts the
tail line (torn-append doctrine, same as the coordinator journal reader).
"""

from __future__ import annotations

import json
import os

from mapreduce_rust_tpu.runtime.lineage import LEDGER_NAME, fold_digests


class LineageError(Exception):
    """A target that cannot be resolved/parsed into a ledger."""


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

def _empty(source: str) -> dict:
    return {"source": source, "header": {}, "chunks": [], "attempts": [],
            "parts": [], "end": None, "bad_lines": 0, "partial": False}


def _parse_jsonl(path: str) -> dict:
    """Parse one ledger file. The last line is distrusted when the file
    does not end in a newline (torn append under SIGKILL); any
    unparseable line is counted and skipped, never fatal — a partial
    ledger still answers partial queries."""
    led = _empty(path)
    try:
        with open(path) as f:
            data = f.read()
    except OSError as e:
        raise LineageError(f"cannot read ledger: {e}") from e
    lines = data.splitlines()
    if lines and not data.endswith("\n"):
        lines.pop()  # torn tail from a crashed append — never trust it
        led["partial"] = True
    for line in lines:
        try:
            rec = json.loads(line)
            t = rec.get("t")
        except (ValueError, AttributeError):
            led["bad_lines"] += 1
            continue
        if t == "start":
            led["header"] = rec
        elif t == "chunk":
            led["chunks"].append(rec)
        elif t == "attempt":
            led["attempts"].append(rec)
        elif t == "part":
            led["parts"].append(rec)
        elif t == "end":
            led["end"] = rec
        else:
            led["bad_lines"] += 1
    return led


def _from_embed(doc: dict, source: str) -> dict:
    """Ledger view from a flight-recorder partial's embedded tail (or a
    manifest whose work dir is gone): header + the capped chunk-record
    tail. No part/attempt records — backward queries fall back to the
    chunks' own routing."""
    led = _empty(source)
    led["partial"] = True
    led["header"] = dict(doc.get("header") or {})
    led["chunks"] = [r for r in (doc.get("records") or [])
                     if isinstance(r, dict) and r.get("t") == "chunk"]
    return led


def load_ledger(target: str) -> dict:
    """Resolve ``target`` into a parsed ledger dict. Accepts a
    lineage.jsonl path, a work dir, a run manifest, or a *.partial.json
    flight-recorder snapshot."""
    if os.path.isdir(target):
        return _parse_jsonl(os.path.join(target, LEDGER_NAME))
    if not os.path.exists(target):
        raise LineageError(f"no such file or directory: {target}")
    if target.endswith(".jsonl"):
        return _parse_jsonl(target)
    # JSON documents: a manifest (stats.lineage) or a recorder partial
    # (top-level "lineage" tail) — the same two shapes mrprof reads its
    # profile from.
    try:
        with open(target) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise LineageError(f"cannot parse {target}: {e}") from e
    if isinstance(doc.get("lineage"), dict):          # recorder partial
        return _from_embed(doc["lineage"], target)
    summary = (doc.get("stats") or {}).get("lineage") if isinstance(
        doc.get("stats"), dict) else None
    if isinstance(summary, dict):                     # run manifest
        path = summary.get("path")
        if path and os.path.exists(path):
            return _parse_jsonl(path)
        # Manifest shipped without its work dir: summary-only view.
        led = _empty(target)
        led["partial"] = True
        led["header"] = {"corpus_meta_digest": summary.get(
            "corpus_meta_digest"), "reduce_n": summary.get("reduce_n")}
        return led
    raise LineageError(
        f"{target}: neither a lineage ledger, a manifest with "
        "stats.lineage, nor a recorder partial (was the run --lineage?)")


# ---------------------------------------------------------------------------
# Queries
# ---------------------------------------------------------------------------

def _reduce_n(led: dict) -> int:
    n = led["header"].get("reduce_n") or 0
    if not n:
        for p in led["parts"]:
            n = max(n, int(p.get("r", -1)) + 1)
    return int(n)


def _chunk_by_ref(led: dict, ref: str) -> dict:
    """Resolve a chunk reference — a ledger seq (decimal int) or a
    digest prefix (>= 6 hex chars, unambiguous) — to its chunk record."""
    chunks = led["chunks"]
    if ref.isdigit():
        for c in chunks:
            if c.get("seq") == int(ref):
                return c
        raise LineageError(f"no chunk with seq {ref} "
                           f"({len(chunks)} chunk records)")
    if len(ref) < 6:
        raise LineageError("digest prefix too short (need >= 6 hex chars)")
    hits = [c for c in chunks if str(c.get("dg", "")).startswith(ref)]
    if not hits:
        raise LineageError(f"no chunk digest matches {ref!r}")
    if len({c["dg"] for c in hits}) > 1:
        raise LineageError(f"digest prefix {ref!r} is ambiguous "
                           f"({len(hits)} matches)")
    return hits[0]


def forward(led: dict, ref: str) -> dict:
    """chunk → the reduce partitions it contributed to. Uses the chunk
    record's own routed-parts edge when present (driver ledgers); falls
    back to part-record claims (cluster ledgers, where routing lives on
    the egress side)."""
    c = _chunk_by_ref(led, ref)
    parts = list(c.get("parts") or [])
    via = "routing"
    if not parts and led["parts"]:
        parts = sorted(int(p["r"]) for p in led["parts"]
                       if c.get("dg") in (p.get("chunks") or []))
        via = "claims"
    return {"chunk": c, "partitions": parts, "via": via}


def backward(led: dict, r: int) -> dict:
    """reduce partition → contributing chunks + the attempt chain. The
    claim set comes from the partition's egress record when present;
    otherwise (partial/killed run) from the chunk records' routing edges
    — both sides of the same conservation invariant mrcheck replays."""
    part = next((p for p in led["parts"] if p.get("r") == r), None)
    if part is not None:
        claimed = list(part.get("chunks") or [])
        via = "claims"
    else:
        claimed = [c["dg"] for c in led["chunks"]
                   if r in (c.get("parts") or [])]
        via = "routing"
    by_dg = {c.get("dg"): c for c in led["chunks"]}
    chunks = [by_dg.get(dg, {"dg": dg}) for dg in claimed]
    attempts = [a for a in led["attempts"]
                if set(claimed) & set(a.get("chunks") or [])]
    return {
        "partition": r,
        "bytes": part.get("bytes") if part else None,
        "chunks": chunks,
        "attempts": attempts,
        "via": via,
    }


def diff(old: dict, new: dict) -> dict:
    """Recompute blast radius between two runs. Chunks are matched by
    content digest as a byte-weighted multiset — an appended/changed
    file shifts only the chunks whose bytes actually differ, and
    ``memo_hit_frac`` is the fraction of the NEW corpus's bytes a
    chunk-level memo tier keyed on (app, chunk digest) would serve
    without recomputation (ROADMAP item 4's headline number)."""
    def weights(led: dict) -> dict:
        w: dict = {}
        for c in led["chunks"]:
            dg = c.get("dg")
            if dg:
                w[dg] = w.get(dg, 0) + int(c.get("bytes") or 1)
        if not w:  # attempt-only (cluster) ledger: unit weights
            for a in led["attempts"]:
                for dg in a.get("chunks") or []:
                    w[dg] = w.get(dg, 0) + 1
        return w

    ow, nw = weights(old), weights(new)
    new_total = sum(nw.values())
    hit_bytes = sum(min(b, ow[dg]) for dg, b in nw.items() if dg in ow)
    changed = [dg for dg in nw if dg not in ow]
    removed = [dg for dg in ow if dg not in nw]
    # Affected partitions: everywhere a changed chunk routes. A chunk
    # with no recorded routing claims every partition (conservative).
    rn = max(_reduce_n(new), _reduce_n(old))
    parts_of: dict = {c.get("dg"): c.get("parts")
                      for c in new["chunks"]}
    affected: set = set()
    for dg in changed:
        ps = parts_of.get(dg)
        affected.update(ps if ps else range(rn))
    return {
        "old_chunks": sum(1 for _ in old["chunks"]) or len(ow),
        "new_chunks": sum(1 for _ in new["chunks"]) or len(nw),
        "changed_chunks": len(changed),
        "removed_chunks": len(removed),
        "changed_bytes": sum(nw[dg] for dg in changed),
        "memo_hit_frac": (hit_bytes / new_total) if new_total else 0.0,
        "affected_partitions": sorted(affected),
        "affected_partition_frac": (len(affected) / rn) if rn else 0.0,
        "reduce_n": rn,
    }


def stamp_manifest(path: str, d: dict) -> bool:
    """Write a diff's headline numbers into ``path``'s stats.lineage
    block (the doctor's incremental-opportunity finding cites them from
    there). Only meaningful when the diff's NEW target was a manifest;
    returns False when the file is not a stampable manifest."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return False
    stats = doc.get("stats")
    if not isinstance(stats, dict) or not isinstance(
            stats.get("lineage"), dict):
        return False
    stats["lineage"]["memo_hit_frac"] = round(d["memo_hit_frac"], 6)
    stats["lineage"]["changed_chunks"] = d["changed_chunks"]
    stats["lineage"]["affected_partition_frac"] = round(
        d["affected_partition_frac"], 6)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return True


# ---------------------------------------------------------------------------
# CLI (`mapreduce_rust_tpu lineage ...`)
# ---------------------------------------------------------------------------

def _summary_lines(led: dict) -> list:
    h, end = led["header"], led["end"]
    lines = [f"ledger: {led['source']}"
             + (" (partial)" if led["partial"] else "")]
    if h:
        lines.append(
            f"corpus: {h.get('corpus_meta_digest', '?')} "
            f"{h.get('corpus_bytes', '?')}B "
            f"inputs={len(h.get('inputs') or [])} "
            f"reduce_n={h.get('reduce_n', '?')}")
    lines.append(
        f"records: {len(led['chunks'])} chunks, {len(led['attempts'])} "
        f"attempts, {len(led['parts'])} partition claims"
        + (f", {led['bad_lines']} bad lines" if led["bad_lines"] else ""))
    if end:
        lines.append(f"content digest: {end.get('corpus_digest')} "
                     f"({end.get('chunks')} chunks, {end.get('bytes')}B)")
    elif led["chunks"]:
        lines.append("content digest (re-folded): "
                     + fold_digests(c["dg"] for c in led["chunks"]
                                    if c.get("dg")))
    for p in led["parts"]:
        lines.append(f"  part {p.get('r')}: {p.get('bytes')}B from "
                     f"{len(p.get('chunks') or [])} chunks")
    return lines


def run_cli(args) -> int:
    fmt = getattr(args, "format", "text")

    def emit(doc, text_lines) -> None:
        if fmt == "json":
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print("\n".join(text_lines))

    targets = list(args.target)
    try:
        if targets and targets[0] == "diff":
            if len(targets) != 3:
                print("lineage diff needs exactly two targets "
                      "(old, new)")
                return 2
            old, new = load_ledger(targets[1]), load_ledger(targets[2])
            d = diff(old, new)
            if getattr(args, "stamp", False):
                if stamp_manifest(targets[2], d):
                    d["stamped"] = targets[2]
            pct = 100.0 * d["memo_hit_frac"]
            emit(d, [
                f"old: {d['old_chunks']} chunks   new: {d['new_chunks']} "
                f"chunks   changed: {d['changed_chunks']} "
                f"(+{d['changed_bytes']}B)   removed: {d['removed_chunks']}",
                f"memo_hit_frac: {d['memo_hit_frac']:.4f} ({pct:.1f}% of "
                "new-corpus bytes reusable by a chunk-level memo tier)",
                f"blast radius: {len(d['affected_partitions'])}/"
                f"{d['reduce_n']} partitions "
                f"({100.0 * d['affected_partition_frac']:.1f}%): "
                f"{d['affected_partitions']}",
            ])
            return 0
        if len(targets) != 1:
            print("expected one ledger target (or: diff <old> <new>)")
            return 2
        led = load_ledger(targets[0])
        fwd = getattr(args, "forward", None)
        bwd = getattr(args, "backward", None)
        if fwd is not None:
            r = forward(led, fwd)
            c = r["chunk"]
            emit(r, [
                f"chunk seq={c.get('seq')} doc={c.get('doc')} "
                f"bytes={c.get('bytes')} dg={c.get('dg')}",
                f"→ partitions {r['partitions']} (via {r['via']})",
            ])
            return 0 if r["partitions"] or not led["parts"] else 0
        if bwd is not None:
            r = backward(led, int(bwd))
            lines = [f"partition {r['partition']}"
                     + (f" ({r['bytes']}B)" if r["bytes"] is not None
                        else "")
                     + f" ← {len(r['chunks'])} chunks (via {r['via']})"]
            for c in r["chunks"]:
                lines.append(f"  {c.get('dg')} seq={c.get('seq')} "
                             f"doc={c.get('doc')} bytes={c.get('bytes')}")
            for a in r["attempts"]:
                lines.append(f"  attempt: map tid={a.get('tid')} "
                             f"a{a.get('attempt')} w{a.get('wid')} "
                             f"({len(a.get('chunks') or [])} chunks)")
            emit(r, lines)
            return 0 if r["chunks"] else 2
        emit(led, _summary_lines(led))
        return 0
    except LineageError as e:
        print(f"lineage: {e}")
        return 2
