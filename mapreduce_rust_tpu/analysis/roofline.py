"""Roofline attribution for the data plane (ISSUE 19, tentpole b).

The methodology of the Xeon Phi MapReduce study (arXiv:1309.0215,
PAPERS.md): before optimizing a stage, place it against the machine's
roofs — how many bytes it actually moved per second vs what the hardware
can move, and how many flops per byte it performs (operational
intensity). "The scan is slow" becomes "the scan runs at 38 % of the
host memcpy roof, so a device-resident map projects ~N×" — the standing
evidence substrate for ROADMAP item 2.

Three parts:

- **Calibration** (``calibrate`` / ``load_machine``): machine peaks
  measured once into ``.bench/machine.json`` — a host memcpy-bandwidth
  micro-probe (bytearray slice copy, best-of-N), plus device peaks from
  ``jax.local_devices()`` device-kind props **only** when a jax backend
  is already initialized in this process (the ``platform_info`` /
  ``xla_bridge._backends`` guard — this module must never trigger
  backend init; it is imported by jax-free CLI tools).
- **Attribution** (``roofline_report``): per-stage achieved GB/s and
  achieved-vs-roof fractions derived from bytes the stack already
  tracks — ``bytes_in`` over the host-map scan seconds, ``spill_split``
  bytes over writer seconds, dispatch record bytes (the packed
  ``1 + 3·cap`` uint32 layout) over dispatch-thread seconds, a2a wire
  bytes over collective seconds — plus the jitted merge fn's
  ``jax.stages`` ``cost_analysis()`` (captured by the driver into the
  manifest's ``merge_cost`` block) for device-merge intensity.
- **CLI** (``run_cli``): the jax-free ``prof`` subcommand — render a
  manifest's ``stats.profile``, export its collapsed stacks as a
  ``.folded`` file, and with ``--roofline`` attach the attribution.

Everything here is stdlib-only at module level.
"""

from __future__ import annotations

import json
import logging
import os
import time

log = logging.getLogger("mr.roofline")

MACHINE_SCHEMA = 1
DEFAULT_MACHINE_PATH = os.path.join(".bench", "machine.json")

# Published peak specs per device kind (HBM GB/s, bf16 TFLOP/s) — the
# roof for device-resident stages when the backend names real hardware.
# cpu backends fall back to the measured host memcpy roof.
DEVICE_PEAKS = {
    "TPU v4": (1228.0, 275.0),
    "TPU v5 lite": (819.0, 197.0),
    "TPU v5e": (819.0, 197.0),
    "TPU v5p": (2765.0, 459.0),
    "TPU v6 lite": (1640.0, 918.0),
}


# ---------------------------------------------------------------------------
# Calibration
# ---------------------------------------------------------------------------

def measure_host_memcpy_gbs(size_mb: int = 64, repeats: int = 3) -> float:
    """Best-of-N big-buffer copy: ``dst[:] = src`` over ``size_mb`` MB of
    bytearray counts one read + one write stream per byte, the classic
    STREAM-copy shape. Best-of (not mean): interference only ever slows
    a copy down, so the fastest repeat is the cleanest roof estimate."""
    n = max(int(size_mb), 1) << 20
    src = memoryview(bytearray(n))
    dst = bytearray(n)
    best_dt = float("inf")
    for _ in range(max(int(repeats), 1)):
        t0 = time.perf_counter()
        dst[:] = src
        best_dt = min(best_dt, time.perf_counter() - t0)
    return round(2.0 * n / best_dt / 1e9, 3)


def probe_device_peaks() -> list:
    """Device peaks from ``jax.local_devices()`` props — guarded like
    ``telemetry.platform_info``: probe ONLY a backend someone else
    already initialized, never trigger initialization from here."""
    import sys as _sys

    if "jax" not in _sys.modules:
        return []
    try:
        from jax._src import xla_bridge

        if not xla_bridge._backends:
            return []
        import jax

        out = []
        for d in jax.local_devices():
            kind = getattr(d, "device_kind", "") or ""
            peak = DEVICE_PEAKS.get(kind)
            row = {"id": d.id, "kind": kind, "platform": d.platform}
            if peak is not None:
                row["hbm_gbs"], row["tflops"] = peak
            out.append(row)
        return out
    except Exception:  # backend probe failed — calibration still writes
        return []


def load_machine(path: "str | None" = None) -> "dict | None":
    path = path or DEFAULT_MACHINE_PATH
    try:
        with open(path) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(m, dict) or m.get("schema") != MACHINE_SCHEMA:
        return None
    return m


def calibrate(path: "str | None" = None, force: bool = False,
              size_mb: int = 64, persist: bool = True) -> dict:
    """Load the cached calibration, or measure and (optionally) write it.
    The cache is the point: peaks are a property of the machine, not the
    run, so every bench round and doctor invocation reuses one probe."""
    path = path or DEFAULT_MACHINE_PATH
    if not force:
        m = load_machine(path)
        if m is not None:
            return m
    m = {
        "schema": MACHINE_SCHEMA,
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_memcpy_gbs": measure_host_memcpy_gbs(size_mb=size_mb),
        "probe_mb": int(size_mb),
        "devices": probe_device_peaks(),
    }
    if persist:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(m, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
    return m


def device_roof_gbs(machine: dict) -> "float | None":
    """Best known device HBM roof in the calibration, if any."""
    roofs = [d.get("hbm_gbs") for d in machine.get("devices") or []
             if isinstance(d.get("hbm_gbs"), (int, float))]
    return max(roofs) if roofs else None


# ---------------------------------------------------------------------------
# Attribution
# ---------------------------------------------------------------------------

def _row(stage: str, nbytes, seconds, roof_gbs, **extra) -> "dict | None":
    if not nbytes or not seconds or seconds <= 0:
        return None
    achieved = nbytes / seconds / 1e9
    row = {
        "stage": stage,
        "bytes": int(nbytes),
        "seconds": round(float(seconds), 6),
        "achieved_gbs": round(achieved, 3),
        "roof_gbs": round(roof_gbs, 3) if roof_gbs else None,
        "frac": round(achieved / roof_gbs, 4) if roof_gbs else None,
    }
    row.update(extra)
    return row


def stage_rows(manifest: dict, machine: dict) -> list:
    """Achieved-vs-roof per stage from bytes the stack already tracks.
    Seconds are PLANE-thread seconds (aggregate across that plane's
    threads), so achieved GB/s is a per-thread rate comparable against
    the single-stream memcpy roof."""
    stats = manifest.get("stats") or {}
    cfgd = manifest.get("config") or {}
    host_roof = machine.get("host_memcpy_gbs")
    rows = []

    hms = stats.get("host_map_split") or {}
    scan_s = hms.get("scan_s") or stats.get("host_map_s")
    rows.append(_row(
        "host-map-scan", stats.get("bytes_in"), scan_s, host_roof,
        workers=hms.get("workers"),
        # The scan reads every input byte once and writes compact
        # (hash, count) records: intensity ~0 flops/byte — a memory
        # stage, so the memcpy roof is the honest ceiling.
        roof="host-memcpy",
    ))

    sp = stats.get("spill_split") or {}
    rows.append(_row(
        "spill-write", sp.get("bytes"), sp.get("write_s"), host_roof,
        roof="host-memcpy",  # upper bound; the disk usually caps sooner
    ))

    dsp = stats.get("dispatch_split") or {}
    cap = cfgd.get("host_update_cap")
    if dsp.get("dispatches") and cap:
        # The packed-merge layout: 1 + 3·cap uint32 words per dispatch
        # (driver.make_packed_merge_fn), shipped whole each time.
        dispatch_bytes = dsp["dispatches"] * (1 + 3 * int(cap)) * 4
        rows.append(_row(
            "dispatch", dispatch_bytes, dsp.get("dispatch_s"), host_roof,
            dispatches=dsp["dispatches"], roof="host-memcpy",
        ))
        mc = manifest.get("merge_cost") or {}
        if mc.get("bytes_accessed"):
            flops = (mc.get("flops") or 0.0) * dsp["dispatches"]
            mbytes = mc["bytes_accessed"] * dsp["dispatches"]
            # No fallback roof here: the bytes are XLA's static estimate
            # of buffer traffic, only honest against a real device HBM
            # peak — against host memcpy it fabricates >100% fractions.
            droof = device_roof_gbs(machine)
            row = _row(
                "device-merge", mbytes, dsp.get("dispatch_s"), droof,
                roof="device-hbm" if droof else None,
            )
            if row is not None:
                row["flops"] = flops
                row["intensity_flops_per_byte"] = round(flops / mbytes, 4)
                rows.append(row)

    ici = stats.get("ici_split") or {}
    rows.append(_row(
        "a2a-shuffle", ici.get("wire_bytes"), ici.get("all_to_all_s"),
        device_roof_gbs(machine),
        rounds=ici.get("rounds"),
        roof="device-hbm" if device_roof_gbs(machine) else None,
    ))

    return [r for r in rows if r is not None]


def roofline_report(manifest: dict, machine: dict) -> dict:
    """The full attribution document. ``scan_achieved_gbs`` and
    ``roofline_frac`` (the host-map scan's achieved-vs-roof) are the two
    headline series bench history records and the doctor trend watches —
    both bad when they go down."""
    rows = stage_rows(manifest, machine)
    scan = next((r for r in rows if r["stage"] == "host-map-scan"), None)
    doc = {
        "machine": {
            "host_memcpy_gbs": machine.get("host_memcpy_gbs"),
            "device_hbm_gbs": device_roof_gbs(machine),
        },
        "stages": rows,
        "scan_achieved_gbs": scan["achieved_gbs"] if scan else None,
        "roofline_frac": scan["frac"] if scan else None,
    }
    if scan and scan.get("frac"):
        droof = device_roof_gbs(machine)
        base = droof if droof else machine.get("host_memcpy_gbs")
        if base:
            # Projected device-map gain (ROADMAP item 2 evidence): a
            # device-resident scan that reaches half the target roof vs
            # today's achieved host rate. Deliberately conservative —
            # the claim is headroom, not a promise.
            doc["device_map_projection_x"] = round(
                0.5 * base / scan["achieved_gbs"], 2)
    return doc


# ---------------------------------------------------------------------------
# prof CLI (jax-free)
# ---------------------------------------------------------------------------

def render_text(doc: dict, verbose: bool = False) -> str:
    out = []
    prof = doc.get("profile")
    if prof:
        out.append(f"profile: {prof['samples']} samples @ {prof['hz']:g} Hz "
                   f"over {prof['wall_s']:.2f}s wall")
        planes = prof.get("planes") or {}
        total = sum(p["self_s"] for p in planes.values()) or 1.0
        out.append("  per-plane self time:")
        for name, p in sorted(planes.items(),
                              key=lambda kv: -kv[1]["self_s"]):
            out.append(f"    {name:<10} {p['self_s']:>8.2f}s "
                       f"{100.0 * p['self_s'] / total:>5.1f}%  "
                       f"({p['samples']} samples)")
        out.append("  top frames (self):")
        for fr in (prof.get("top_frames") or [])[:10]:
            out.append(f"    {fr['pct']:>5.1f}%  {fr['frame']}")
        ft = prof.get("frame_table") or {}
        if ft.get("dropped"):
            out.append(f"  note: frame table capped "
                       f"({ft['dropped']} drops at {ft['cap']} entries)")
    else:
        out.append("profile: none in manifest (run with --profile / "
                   "MR_PROFILE=1)")
    rl = doc.get("roofline")
    if rl:
        mach = rl["machine"]
        out.append(f"roofline (host memcpy roof "
                   f"{mach['host_memcpy_gbs']:g} GB/s"
                   + (f", device HBM {mach['device_hbm_gbs']:g} GB/s"
                      if mach.get("device_hbm_gbs") else "") + "):")
        for r in rl["stages"]:
            frac = f"{r['frac']:.0%} of {r['roof']}" if r.get("frac") \
                else "no roof"
            out.append(f"    {r['stage']:<14} {r['achieved_gbs']:>9.3f} GB/s "
                       f"({frac})  [{r['bytes'] / 1e6:.1f} MB / "
                       f"{r['seconds']:.3f}s]")
        if rl.get("device_map_projection_x"):
            out.append(f"    device-map projection: "
                       f"~{rl['device_map_projection_x']:g}× on host-map-scan "
                       f"at half the target roof (ROADMAP item 2)")
    if doc.get("folded"):
        out.append(f"folded: {doc['folded_lines']} stacks → {doc['folded']}")
    return "\n".join(out)


def run_cli(args) -> int:
    """``prof <manifest> [--folded OUT] [--roofline] [--machine PATH]
    [--format json|text]`` — jax-free like lint/check/doctor/model."""
    try:
        with open(args.manifest) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        print(f"prof: cannot read manifest {args.manifest}: {e}")
        return 2
    stats = manifest.get("stats") or {}
    profile = stats.get("profile")
    # Flight-recorder partials carry the profile at the top level (the
    # metrics pattern): accept them too, so a SIGKILLed run's flamegraph
    # is one `prof trace.partial.json --folded out.folded` away.
    if profile is None and isinstance(manifest.get("profile"), dict):
        profile = manifest["profile"]
    doc: dict = {"manifest": os.path.abspath(args.manifest),
                 "profile": profile}

    folded_out = getattr(args, "folded", None)
    if folded_out:
        stacks = (profile or {}).get("stacks") or []
        if not stacks:
            print("prof: manifest has no profile stacks to export "
                  "(run with --profile / MR_PROFILE=1)")
            return 2
        d = os.path.dirname(os.path.abspath(folded_out))
        os.makedirs(d, exist_ok=True)
        with open(folded_out, "w") as f:
            f.write("\n".join(stacks) + "\n")
        doc["folded"] = os.path.abspath(folded_out)
        doc["folded_lines"] = len(stacks)

    if getattr(args, "roofline", False):
        machine = calibrate(getattr(args, "machine", None))
        doc["roofline"] = roofline_report(manifest, machine)

    if getattr(args, "format", "text") == "json":
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(render_text(doc, verbose=getattr(args, "verbose", False)))
    return 0
