"""Shared intraprocedural dataflow/CFG layer + package call graph for
mrlint (ISSUE 7 tentpole piece c).

The per-rule AST matchers (rules.py) prove *shape*: a call sits inside a
with-block, a kwarg is present. What they cannot prove is *flow* — that a
value assigned three statements ago reaches this loop, that every path
from the function entry to a device probe passes a guard, that a blocking
call is reachable from an ``async def`` through two helper frames. This
module gives rules those three primitives:

- :class:`CFG` — a statement-level control-flow graph per function
  (if/while/for/try/with/return/raise/break/continue modeled; every
  try-body statement also edges to its handlers, which is what makes
  guard-inside-try analysis sound for the shipped probe pattern).
- :func:`reaching_defs` — the classic worklist analysis over that CFG:
  which assignments reach each statement. :func:`origins` follows copy
  chains (``y = x``) through it, so a rule can ask "what expression did
  this name originally come from?".
- :func:`guarded_reach` — branch-sensitive guard analysis: is a target
  statement reachable only on paths where a test mentioning ``ident``
  held true? (The ``xla_bridge._backends`` early-return idiom.)
- :class:`Program` / :class:`CallGraph` — all linted files parsed once,
  functions indexed, call edges resolved conservatively by name (same
  class first, then module, then package-unique), with callables that are
  only *passed* to an executor (``run_in_executor``/``submit``/
  ``Thread(target=...)``) excluded from an async caller's edges — handing
  work to a pool thread is exactly how blocking code legally coexists
  with an event loop.

Pure ``ast`` + stdlib, like the rest of the analyzer: linting the whole
repo must stay tens of milliseconds.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator

from mapreduce_rust_tpu.analysis.lint import last_segment as _last_segment
from mapreduce_rust_tpu.analysis.lint import qualname


# ---------------------------------------------------------------------------
# Control-flow graph
# ---------------------------------------------------------------------------

class CFG:
    """Statement-level CFG of one function body.

    Nodes are ast statements (indexed); ``succ[i]`` holds (j, label)
    edges where label is "true"/"false" for an If's branch edges and ""
    otherwise. ``EXIT`` (-1) is the single sink (returns, raises, falling
    off the end)."""

    EXIT = -1

    def __init__(self, fn: "ast.FunctionDef | ast.AsyncFunctionDef") -> None:
        self.fn = fn
        self.nodes: list[ast.stmt] = []
        self.succ: dict[int, list[tuple[int, str]]] = {}
        self.index: dict[int, int] = {}  # id(stmt) -> node index
        self._loop_stack: list[tuple[list[int], list[int]]] = []
        frontier = self._build(fn.body, ["entry"])
        self._connect(frontier, self.EXIT)

    # frontier: list of (node, label) pairs awaiting their successor; the
    # sentinel "entry" stands for the function entry.

    def _add(self, stmt: ast.stmt) -> int:
        i = len(self.nodes)
        self.nodes.append(stmt)
        self.index[id(stmt)] = i
        self.succ[i] = []
        return i

    def _connect(self, frontier, target: int) -> None:
        for item in frontier:
            if item == "entry":
                continue  # entry's successor is implicit (first node)
            src, label = item
            self.succ[src].append((target, label))

    def _build(self, stmts: list[ast.stmt], frontier: list) -> list:
        for stmt in stmts:
            # Statements after a terminator (return/raise/...) leave an
            # empty frontier: they are still recorded so defs inside them
            # exist, but nothing flows in.
            i = self._add(stmt)
            self._connect(frontier, i)
            frontier = self._stmt(stmt, i)
        return frontier

    def _stmt(self, stmt: ast.stmt, i: int) -> list:
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self.succ[i].append((self.EXIT, ""))
            return []
        if isinstance(stmt, ast.Break):
            if self._loop_stack:
                self._loop_stack[-1][0].append(i)
            return []
        if isinstance(stmt, ast.Continue):
            if self._loop_stack:
                self._loop_stack[-1][1].append(i)
            return []
        if isinstance(stmt, ast.If):
            body_exit = self._build(stmt.body, [(i, "true")])
            if stmt.orelse:
                else_exit = self._build(stmt.orelse, [(i, "false")])
            else:
                else_exit = [(i, "false")]
            return body_exit + else_exit
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            self._loop_stack.append(([], []))
            label = "true" if isinstance(stmt, ast.While) else ""
            body_exit = self._build(stmt.body, [(i, label)])
            breaks, continues = self._loop_stack.pop()
            # Back edges: body exit + continues loop to the header.
            self._connect(body_exit, i)
            for c in continues:
                self.succ[c].append((i, ""))
            out = [(i, "false" if isinstance(stmt, ast.While) else "")]
            out += [(b, "") for b in breaks]
            if stmt.orelse:
                out = self._build(stmt.orelse, out)
            return out
        if isinstance(stmt, ast.Try):
            body_exit = self._build(stmt.body, [(i, "")])
            body_nodes = [
                j for j in range(i + 1, len(self.nodes))
                if any(self.nodes[j] is s or self._contains(s, self.nodes[j])
                       for s in stmt.body)
            ]
            out = []
            for handler in stmt.handlers:
                h_entry: list = [(i, "")]
                # An exception can surface at ANY statement of the try
                # body: every body node edges into every handler head.
                h_frontier = h_entry + [(j, "") for j in body_nodes]
                out += self._build(handler.body, h_frontier)
            if stmt.orelse:
                body_exit = self._build(stmt.orelse, body_exit)
            out += body_exit
            if stmt.finalbody:
                out = self._build(stmt.finalbody, out)
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._build(stmt.body, [(i, "")])
        # Plain statement (incl. nested function/class defs: opaque).
        return [(i, "")]

    @staticmethod
    def _contains(root: ast.stmt, node: ast.stmt) -> bool:
        return any(n is node for n in ast.walk(root))

    def node_of(self, sub: ast.AST) -> "int | None":
        """CFG node whose statement contains ``sub`` (None for nodes in
        nested function scopes, which get their own CFG)."""
        cur = sub
        while cur is not None:
            i = self.index.get(id(cur))
            if i is not None:
                return i
            cur = getattr(cur, "mr_parent", None)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and cur is not self.fn:
                return None
        return None

    def preds(self) -> dict[int, list[tuple[int, str]]]:
        p: dict[int, list[tuple[int, str]]] = {i: [] for i in self.succ}
        p[self.EXIT] = []
        for i, outs in self.succ.items():
            for j, label in outs:
                p.setdefault(j, []).append((i, label))
        return p


# ---------------------------------------------------------------------------
# Reaching definitions
# ---------------------------------------------------------------------------

def _def_targets(stmt: ast.stmt) -> Iterator[tuple[str, "ast.expr | None"]]:
    """(name, value-expr) pairs a statement defines. Value None = opaque
    (for-loop targets, with-as, aug-assign reads its own prior value)."""
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            for name in _target_names(t):
                yield name, stmt.value
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        for name in _target_names(stmt.target):
            yield name, stmt.value
    elif isinstance(stmt, ast.AugAssign):
        for name in _target_names(stmt.target):
            yield name, None
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        for name in _target_names(stmt.target):
            yield name, None
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                for name in _target_names(item.optional_vars):
                    yield name, None


def _target_names(t: ast.expr) -> Iterator[str]:
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for el in t.elts:
            yield from _target_names(el)
    elif isinstance(t, ast.Starred):
        yield from _target_names(t.value)


@dataclasses.dataclass
class Def:
    name: str
    node: int                  # CFG node of the defining statement
    value: "ast.expr | None"   # RHS when it is a simple binding


def reaching_defs(cfg: CFG) -> tuple[list[Def], dict[int, set[int]]]:
    """(all defs, node -> def-ids reaching its ENTRY) — the textbook
    worklist, at statement granularity. Parameters are def -1 (opaque)."""
    defs: list[Def] = []
    gen: dict[int, set[int]] = {}
    kill_names: dict[int, set[str]] = {}
    for i, stmt in enumerate(cfg.nodes):
        g: set[int] = set()
        names: set[str] = set()
        for name, value in _def_targets(stmt):
            d = len(defs)
            defs.append(Def(name, i, value))
            g.add(d)
            names.add(name)
        gen[i] = g
        kill_names[i] = names
    by_name: dict[str, set[int]] = {}
    for d_id, d in enumerate(defs):
        by_name.setdefault(d.name, set()).add(d_id)
    preds = cfg.preds()
    IN: dict[int, set[int]] = {i: set() for i in range(len(cfg.nodes))}
    OUT: dict[int, set[int]] = {}
    for i in range(len(cfg.nodes)):
        OUT[i] = set(gen[i])
    changed = True
    while changed:
        changed = False
        for i in range(len(cfg.nodes)):
            new_in: set[int] = set()
            for p, _label in preds.get(i, []):
                if p >= 0:
                    new_in |= OUT[p]
            if new_in != IN[i]:
                IN[i] = new_in
            survivors = {
                d for d in new_in if defs[d].name not in kill_names[i]
                # A def of the same name in the same statement kills it —
                # except the statement's own gen, added back below.
            }
            new_out = survivors | gen[i]
            if new_out != OUT[i]:
                OUT[i] = new_out
                changed = True
    return defs, IN


def origins(cfg: CFG, defs: list[Def], reach_in: dict[int, set[int]],
            name_node: ast.Name, max_hops: int = 8) -> list["ast.expr | None"]:
    """Origin expressions of a Name load: its reaching definitions'
    values, with copy chains (``y = x``) followed through further
    reaching definitions. ``None`` entries mean an opaque origin (loop
    target, parameter, augmented assignment)."""
    node = cfg.node_of(name_node)
    if node is None:
        return [None]
    out: list["ast.expr | None"] = []
    seen: set[tuple[int, str]] = set()

    def walk(at: int, name: str, hops: int) -> None:
        if (at, name) in seen or hops > max_hops:
            return
        seen.add((at, name))
        hit = False
        for d_id in reach_in.get(at, ()):
            d = defs[d_id]
            if d.name != name:
                continue
            hit = True
            if isinstance(d.value, ast.Name):
                walk(d.node, d.value.id, hops + 1)
            else:
                out.append(d.value)
        if not hit:
            out.append(None)  # parameter / nonlocal / global: opaque

    walk(node, name_node.id, 0)
    return out


# ---------------------------------------------------------------------------
# Branch-sensitive guard analysis
# ---------------------------------------------------------------------------

def _guard_polarity(test: ast.expr, ident: str) -> "str | None":
    """"true-means-present" / "true-means-absent" when the test is a
    simple (possibly negated) mention of ``ident``; None when the test is
    too complex to trust (conservative: no guard credit)."""
    neg = False
    while isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        neg = not neg
        test = test.operand
    # Only a BARE mention carries trustworthy polarity: a comparison or
    # call form (`len(x._backends) == 0`) mentions the ident but its
    # truth value means the opposite of the bare idiom — guessing would
    # block the wrong branch edge and flag correctly guarded code.
    if not isinstance(test, (ast.Name, ast.Attribute)):
        return None
    if ident not in (getattr(test, "attr", "") or "") \
            and ident not in (getattr(test, "id", "") or ""):
        return None
    return "true-means-absent" if neg else "true-means-present"


def guarded_reach(cfg: CFG, target: ast.AST, ident: str) -> bool:
    """True iff every entry path to ``target``'s statement passes a
    branch where a simple test on ``ident`` held TRUE (e.g. the
    ``if not xla_bridge._backends: return`` early exit, or nesting under
    ``if xla_bridge._backends:``). Reachability with the guarded edges
    removed decides it: still reachable -> unguarded."""
    t = cfg.node_of(target)
    if t is None:
        return False
    # Remove every guard-HOLDING edge (the branch taken when the test
    # proves the guard); if the target is then unreachable, every real
    # path needed one of those edges — i.e. the guard dominates it.
    blocked: set[tuple[int, int]] = set()
    for i, stmt in enumerate(cfg.nodes):
        if not isinstance(stmt, (ast.If, ast.While)):
            continue
        pol = _guard_polarity(stmt.test, ident)
        if pol is None:
            continue
        ok_label = "true" if pol == "true-means-present" else "false"
        for j, label in cfg.succ[i]:
            if label == ok_label:
                blocked.add((i, j))
    if not cfg.nodes:
        return False
    seen = {0}
    stack = [0]
    while stack:
        i = stack.pop()
        if i == t:
            return False  # reachable without the guard holding
        for j, _label in cfg.succ.get(i, []):
            if j >= 0 and (i, j) not in blocked and j not in seen:
                seen.add(j)
                stack.append(j)
    return True  # unreachable without the guard holding -> guarded


# ---------------------------------------------------------------------------
# Package-level program + call graph
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FunctionUnit:
    """One function/method in the linted program."""

    node: "ast.FunctionDef | ast.AsyncFunctionDef"
    name: str
    qualname: str        # "Class.method" or bare function name
    path: str            # repo-relative path
    is_async: bool
    _cfg: "CFG | None" = None
    _rd: "tuple | None" = None

    @property
    def cfg(self) -> CFG:
        if self._cfg is None:
            self._cfg = CFG(self.node)
        return self._cfg

    @property
    def rd(self) -> tuple:
        if self._rd is None:
            self._rd = reaching_defs(self.cfg)
        return self._rd


#: Callables whose function-valued ARGUMENTS are dispatched elsewhere
#: (another thread / a pool), not called in the enclosing context.
EXECUTOR_SINKS = frozenset({
    "run_in_executor", "submit", "map", "Thread", "Timer", "start_new_thread",
    "call_soon_threadsafe", "to_thread", "Process",
})


class Program:
    """Every linted file parsed once, functions indexed, call edges
    resolvable — the shared substrate the interprocedural rules run on."""

    def __init__(self, files: list[tuple[str, ast.Module]]) -> None:
        self.files = files
        self.functions: list[FunctionUnit] = []
        self.by_name: dict[str, list[FunctionUnit]] = {}
        self.by_qualname: dict[str, list[FunctionUnit]] = {}
        self._callees_cache: dict[int, list] = {}
        for path, tree in files:
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                cls = None
                cur = getattr(node, "mr_parent", None)
                while cur is not None and cls is None:
                    if isinstance(cur, ast.ClassDef):
                        cls = cur.name
                    cur = getattr(cur, "mr_parent", None)
                qn = f"{cls}.{node.name}" if cls else node.name
                fu = FunctionUnit(
                    node=node, name=node.name, qualname=qn, path=path,
                    is_async=isinstance(node, ast.AsyncFunctionDef),
                )
                self.functions.append(fu)
                self.by_name.setdefault(node.name, []).append(fu)
                self.by_qualname.setdefault(qn, []).append(fu)

    def _executor_arg_ids(self, fn: ast.AST) -> set[int]:
        """ids of nodes whose EVALUATION happens on another thread
        because they were handed to an executor sink: the callable
        reference itself, and the whole body of a lambda argument
        (``run_in_executor(None, lambda: heavy())`` defers ``heavy`` to
        the pool). Eagerly-evaluated argument calls stay in —
        ``submit(build_payload())`` runs ``build_payload`` on the
        CALLER's thread before the handoff ever happens, so it is a real
        callee of an async caller."""
        out: set[int] = set()
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            if _last_segment(qualname(n.func)) not in EXECUTOR_SINKS:
                continue
            for a in list(n.args) + [kw.value for kw in n.keywords]:
                if isinstance(a, ast.Lambda):
                    out.update(id(x) for x in ast.walk(a))
                elif not isinstance(a, ast.Call):
                    out.add(id(a))
        return out

    def callees(self, fu: FunctionUnit) -> list[tuple[ast.Call, "FunctionUnit | None"]]:
        """(call site, resolved target or None) for every call in ``fu``,
        excluding calls handed to executor sinks and calls inside nested
        function definitions (their bodies are separate units). Cached —
        every program rule traverses the same edges."""
        cached = self._callees_cache.get(id(fu.node))
        if cached is not None:
            return cached
        skip = self._executor_arg_ids(fu.node)
        out = []
        for n in self._own_walk(fu.node):
            if not isinstance(n, ast.Call) or id(n) in skip:
                continue
            if id(n.func) in skip:
                continue
            out.append((n, self.resolve(qualname(n.func), fu)))
        self._callees_cache[id(fu.node)] = out
        return out

    @staticmethod
    def _own_walk(fn: ast.AST) -> Iterator[ast.AST]:
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def resolve(self, call_qualname: str,
                caller: FunctionUnit) -> "FunctionUnit | None":
        """Conservative name resolution: ``self.m``/``cls.m`` binds to the
        caller's class first; a bare/attr name binds when the last segment
        is unique in the caller's file, else unique across the program.
        Ambiguity resolves to None (no edge) — precision over recall."""
        if not call_qualname:
            return None
        last = _last_segment(call_qualname)
        cands = self.by_name.get(last) or []
        if not cands:
            return None
        if call_qualname.startswith(("self.", "cls.")) \
                and "." not in call_qualname[5:]:
            own_cls = caller.qualname.split(".")[0] \
                if "." in caller.qualname else None
            if own_cls:
                same = [c for c in cands
                        if c.qualname == f"{own_cls}.{last}"
                        and c.path == caller.path]
                if len(same) == 1:
                    return same[0]
        same_file = [c for c in cands if c.path == caller.path]
        if len(same_file) == 1:
            return same_file[0]
        if not same_file and len(cands) == 1:
            return cands[0]
        return None

    def reachable(self, root: FunctionUnit,
                  max_depth: int = 6) -> list[tuple[FunctionUnit, list]]:
        """(unit, call path) for every function reachable from ``root``
        through resolved SYNC call edges (an awaited async callee is its
        own analysis root). The path is the chain of call sites — what a
        finding prints so the reader can follow the frames."""
        out: list[tuple[FunctionUnit, list]] = []
        seen = {id(root.node)}
        frontier: list[tuple[FunctionUnit, list]] = [(root, [])]
        for _ in range(max_depth):
            nxt: list[tuple[FunctionUnit, list]] = []
            for fu, path in frontier:
                for call, target in self.callees(fu):
                    if target is None or id(target.node) in seen:
                        continue
                    if target.is_async:
                        continue
                    seen.add(id(target.node))
                    entry = (target, path + [(fu, call)])
                    out.append(entry)
                    nxt.append(entry)
            frontier = nxt
            if not frontier:
                break
        return out
