"""Exhaustive control-plane schedule exploration (ISSUE 18).

mrcheck replays invariants over schedules that actually happened; chaos
samples a handful more. This module enumerates them: the **real**
``Coordinator``/``JobService`` grant/finish/expiry/readiness/speculation/
cancel logic — driven through its existing RPC entry points, never a
model rewrite — runs under a virtual clock and an explicit event queue,
and a bounded DFS explores every interleaving of worker and fault events
up to ``--depth``, DPOR-style pruning collapsing commuting pairs
(finishes/renewals/deregisters on distinct workers touching distinct
(phase, tid) machines) into one representative order.

Every explored schedule is validated per-step against mrcheck's
``INVARIANTS`` catalog (via :func:`mrcheck.check_stream` — pure
in-memory, no tempfile round-trips) and at the leaf against the
model-only invariants in :data:`MODEL_INVARIANTS`. A failing schedule is
shrunk (delta debugging: drop events while the same violation code
reproduces) and emitted two ways — a human-readable counterexample trace
and a seeded PR-6 chaos-grammar spec, so the counterexample replays on
the real OS-process cluster.

Event vocabulary (``(kind, *args)`` tuples; every apply also advances
the virtual clock one small tick, so timestamps order deterministically):

- ``("poll", wid)``      worker pulls its next task (map first, then
  reduce — the worker loop's order); a grant is remembered as held work
- ``("finish", wid)``    worker reports its held task (correct attempt +
  part_bytes vector — the pipelined-readiness input)
- ``("renew", wid)``     heartbeat for the held task, including the
  response-envelope revoke check (a revoked worker drops its work)
- ``("expire",)``        fault: the virtual clock jumps past the lease
  timeout and the real detector scan runs; workers do NOT learn — their
  later finishes become the duplicate/late-report races
- ``("deregister", wid)`` fault: graceful drain of an idle worker
- ``("cancel", jid)``    fault (service focus): cancel a queued or
  running job mid-schedule
- ``("replay",)``        fault: journal-truncate-and-replay — a fresh
  coordinator is rebuilt from the journal minus its torn tail and must
  still drain to completion (replay-convergence)
- ``("mutate",)``        armed by ``--mutate CLASS``: marks the point at
  which the corresponding in-memory artifact corruption (mirroring
  ``mrcheck.MUTATIONS``) is applied at leaf validation — the
  mutation-teeth gate's seeded fault event

No jax import, no sockets, no real sleeps: importable and runnable from
any analysis context (the jax-free CLI doctrine of mrcheck/mrlint).
"""

from __future__ import annotations

import contextlib
import json
import logging
import random
import sys
import time

from mapreduce_rust_tpu.analysis import chaos as chaos_mod
from mapreduce_rust_tpu.analysis.mrcheck import (
    INVARIANTS,
    JournalLine,
    MUTATIONS,
    Violation,
    check_lineage,
    check_service_journal,
    check_stream,
    check_trace,
    parse_journal,
)
from mapreduce_rust_tpu.config import Config
from mapreduce_rust_tpu.coordinator.server import Coordinator

MODEL_SCHEMA = 1

#: Model-only invariants — properties of SCHEDULES, not artifacts, so
#: they live here rather than in mrcheck.INVARIANTS (whose codes are the
#: artifact-replay catalog the README documents one-for-one).
MODEL_INVARIANTS: dict = {
    "no-grant-starvation": (
        "from any explored prefix, the deterministic drain loop (poll/"
        "finish every live worker, expire when wedged) must still reach "
        "done() — no schedule may paint the scheduler into a corner "
        "where work exists but is never grantable"
    ),
    "readiness-monotone-per-attempt": (
        "a part_retract for partition r is legal only when a map lease "
        "expiry (a dead attempt) intervened since r's part_ready — "
        "readiness never regresses while its establishing attempt is "
        "live (ISSUE 17's partial-order dispatch contract)"
    ),
    "replay-convergence": (
        "a fresh coordinator replaying ANY journal prefix (truncate-"
        "and-replay fault) must reach a state from which the drain loop "
        "still completes the job — the failover precondition of ROADMAP "
        "item 5"
    ),
}


@contextlib.contextmanager
def _quiet():
    """Model runs replay thousands of lease expiries on purpose — the
    control plane's own warn-level chatter would drown the report."""
    logging.disable(logging.CRITICAL)
    try:
        yield
    finally:
        logging.disable(logging.NOTSET)


class VirtualClock:
    """Deterministic monotonic stand-in: callable like time.monotonic,
    advanced explicitly by the explorer. Starts at a non-zero epoch so
    uptime arithmetic never special-cases 0."""

    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# Harnesses: the real state machines under the virtual clock
# ---------------------------------------------------------------------------

class _ModelCoordinator(Coordinator):
    """Real Coordinator with its journal captured in memory (same line
    format byte-for-byte, minus the header/fsync plumbing) — nothing
    else overridden: every grant/finish/expiry/speculation decision is
    the shipped code path."""

    def __init__(self, cfg: Config, now=None) -> None:
        self.journal_lines: list[str] = []
        super().__init__(cfg, resume=False, job_id=None, now=now)

    def _journal(self, phase_name: str, tid: int, attempt: int = 0,
                 wid: int = -1) -> None:
        self.journal_lines.append(
            f"{phase_name} {tid} a{attempt} w{wid} "
            f"t{self.report.uptime_s():.3f}"
        )


#: Per-event clock tick: small enough that no lease expires from event
#: flow alone (timeouts are >= 1s), large enough for distinct rounded
#: timestamps on every event row.
_TICK = 0.01


class CoordinatorHarness:
    """One schedule's worth of real-Coordinator state plus the worker
    fiction around it (who holds what, who drained). ``apply`` is total:
    an event that is not applicable in the current state is a no-op
    (``changed=False``) — what lets the shrinker drop arbitrary events
    and still replay."""

    kind = "coordinator"

    def __init__(self, cfg: Config, clock: "VirtualClock | None" = None):
        self.cfg = cfg
        self.clock = clock or VirtualClock()
        self.coord = _ModelCoordinator(cfg, now=self.clock)
        for _ in range(cfg.worker_n):
            self.coord.get_worker_id()
        self.held: dict[int, tuple] = {}    # wid -> (phase, tid, attempt)
        self.gone: set[int] = set()
        self.mutated = False
        self.replay_violations: list[Violation] = []

    # -- state --

    def finished(self) -> bool:
        return self.coord.done()

    def fingerprint(self) -> tuple:
        c = self.coord
        return (
            len(c.report._events), len(self.coord.journal_lines),
            tuple(sorted(self.held.items())),
            tuple(sorted(c.map.leases)), tuple(sorted(c.reduce.leases)),
            c.map.finished, c.reduce.finished,
            tuple(sorted(c.map.reported)), tuple(sorted(c.reduce.reported)),
            tuple(sorted(self.gone)), self.mutated,
            len(self.replay_violations),
        )

    def enabled(self, mutate: "str | None" = None) -> list[tuple]:
        evs: list[tuple] = []
        c = self.coord
        for wid in range(self.cfg.worker_n):
            if wid in self.gone:
                continue
            if wid in self.held:
                evs.append(("finish", wid))
                evs.append(("renew", wid))
            elif not c.done():
                evs.append(("poll", wid))
        if c.map.leases or c.reduce.leases:
            evs.append(("expire",))
        alive = [w for w in range(self.cfg.worker_n) if w not in self.gone]
        if len(alive) > 1:
            for wid in alive:
                if wid not in self.held:
                    evs.append(("deregister", wid))
        if self.coord.journal_lines:
            evs.append(("replay",))
        if mutate and not self.mutated:
            evs.append(("mutate",))
        return evs

    # -- event application --

    def apply(self, ev: tuple) -> dict:
        """Apply one event to the real state machine. Returns an info
        dict: ``changed`` (did any model-visible state move — the
        stutter-pruning input), ``task`` (the (phase, tid) the event
        touched, the DPOR commute key) and ``desc`` (human trace)."""
        self.clock.advance(_TICK)
        kind = ev[0]
        info = {"changed": False, "task": None, "desc": " ".join(
            str(x) for x in ev)}
        before = self.fingerprint()
        if kind == "poll":
            wid = ev[1]
            if wid in self.gone or wid in self.held:
                return info
            phase, tid = "map", self.coord.get_map_task(wid)
            if not (isinstance(tid, int) and tid >= 0):
                phase, tid = "reduce", self.coord.get_reduce_task(wid)
            if isinstance(tid, int) and tid >= 0:
                attempt = self.coord.report.attempts(phase, tid)
                self.held[wid] = (phase, tid, attempt)
                info["task"] = (phase, tid)
                info["desc"] = (
                    f"poll w{wid} -> grant {phase}:{tid} a{attempt}")
        elif kind == "finish":
            wid = ev[1]
            held = self.held.pop(wid, None)
            if held is None:
                return info
            phase, tid, attempt = held
            info["task"] = (phase, tid)
            late = tid in (self.coord.map if phase == "map"
                           else self.coord.reduce).reported
            if phase == "map":
                self.coord.report_map_task_finish(
                    tid, attempt=attempt, wid=wid,
                    part_bytes=[1] * self.cfg.reduce_n)
            else:
                self.coord.report_reduce_task_finish(
                    tid, attempt=attempt, wid=wid)
            info["late"] = late
            info["desc"] = (f"finish w{wid} {phase}:{tid} a{attempt}"
                            + (" (late)" if late else ""))
        elif kind == "renew":
            wid = ev[1]
            held = self.held.get(wid)
            if held is None:
                return info
            phase, tid, attempt = held
            info["task"] = (phase, tid)
            method = ("renew_map_lease" if phase == "map"
                      else "renew_reduce_lease")
            ok = getattr(self.coord, method)(tid, wid)
            resp: dict = {}
            self.coord._enrich_response(
                method, {"params": [tid, wid]}, ok, resp)
            if resp.get("revoked"):
                # The worker learned it lost the race: drop the work.
                self.held.pop(wid, None)
                info["revoked"] = True
                info["desc"] = f"renew w{wid} {phase}:{tid} -> revoked"
            else:
                info["desc"] = (f"renew w{wid} {phase}:{tid} "
                                f"-> {'ok' if ok else 'stale'}")
        elif kind == "expire":
            self.clock.advance(self.cfg.lease_timeout_s + _TICK)
            expired = self._live_leases()
            self.coord.check_lease()
            info["expired"] = expired
            info["desc"] = "expire " + (", ".join(
                f"{p}:{t}" for p, t in expired) or "(nothing live)")
            if expired:
                info["task"] = expired[0]
        elif kind == "deregister":
            wid = ev[1]
            if wid in self.gone or wid in self.held:
                return info
            alive = [w for w in range(self.cfg.worker_n)
                     if w not in self.gone]
            if len(alive) <= 1:
                return info  # never drain the last worker: starvation
                # by construction is not a scheduler bug
            self.coord.deregister_worker(wid)
            self.gone.add(wid)
            info["desc"] = f"deregister w{wid}"
        elif kind == "replay":
            self.replay_violations += self._check_replay()
            info["desc"] = (
                f"replay journal[:{max(len(self.coord.journal_lines) - 1, 0)}]"
                " -> fresh coordinator must still drain")
        elif kind == "mutate":
            if self.mutated:
                return info
            self.mutated = True
            info["desc"] = "mutate (arm artifact corruption)"
        info["changed"] = self.fingerprint() != before
        return info

    def _live_leases(self) -> list[tuple]:
        out = []
        for name, ph in (("map", self.coord.map),
                         ("reduce", self.coord.reduce)):
            if self.cfg.sched_pipeline or (
                    (ph is self.coord.reduce) == self.coord.map.finished):
                out += [(name, tid) for tid in sorted(ph.leases)]
        return out

    # -- model-only invariants --

    def drain(self) -> bool:
        """Deterministically run the job to completion from the current
        state: every live worker finishes held work or polls; when a
        round moves nothing, the detector runs. Bounded — a full round
        with no state motion twice in a row means wedged."""
        cap = 8 * (self.cfg.map_n + self.cfg.reduce_n) + 24
        for _ in range(cap):
            if self.coord.done():
                return True
            fp = self.fingerprint()
            for wid in range(self.cfg.worker_n):
                if wid in self.gone:
                    continue
                self.apply(("finish", wid) if wid in self.held
                           else ("poll", wid))
            if self.fingerprint() == fp:
                self.apply(("expire",))
                if self.fingerprint() == fp:
                    return self.coord.done()
        return self.coord.done()

    def _check_replay(self) -> list[Violation]:
        """Journal-truncate-and-replay: rebuild a fresh coordinator from
        the journal minus its last line (the torn tail the real replay
        drops) and prove the job still drains — replay-convergence."""
        prefix = self.coord.journal_lines[:-1]
        fresh = CoordinatorHarness(self.cfg, clock=VirtualClock(self.clock.t))
        fresh.coord._replay_journal_lines(prefix)
        if not fresh.drain():
            return [Violation(
                "replay-convergence",
                f"a coordinator replaying {len(prefix)} journal line(s) "
                "could not drain the job to completion — a restart at "
                "this point wedges the run",
                [{"ev": "journal-prefix", "lines": prefix},
                 {"ev": "drain-wedged"}],
            )]
        return []

    # -- validation --

    def step_violations(self) -> list[Violation]:
        """Cheap per-step pass: the event-log replay plus the model-only
        readiness monotonicity — what localizes a failure to its
        earliest step."""
        events = self.coord.report.events()
        return (check_stream(events)
                + _check_readiness_monotone(events)
                + self.replay_violations)

    def artifacts(self) -> dict:
        """Leaf snapshot in mrcheck's in-memory shapes."""
        report = self.coord.report.to_dict()
        journal = parse_journal(
            "".join(line + "\n" for line in self.coord.journal_lines))
        return {"events": report.get("events") or [], "journal": journal,
                "report": report, "rows": None, "trace": None}

    def leaf_violations(self) -> list[Violation]:
        a = self.artifacts()
        v = check_stream(a["events"], a["journal"], a["report"])
        v += _check_readiness_monotone(a["events"])
        v += self.replay_violations
        if not self.mutated:
            if not self.drain():
                v.append(Violation(
                    "no-grant-starvation",
                    "the deterministic drain loop could not complete the "
                    "job from this schedule's final state — grantable "
                    "work exists that no worker can obtain",
                    [{"ev": "drain-wedged"},
                     a["events"][-1] if a["events"]
                     else {"ev": "empty-log"}],
                ))
            v += self._check_replay() if self.coord.journal_lines else []
        return v


def _check_readiness_monotone(events: list) -> list[Violation]:
    """readiness-monotone-per-attempt: a part_retract for r requires a
    map lease expiry since r's latest part_ready — retracting readiness
    under live attempts would re-gate partitions whose inputs are final."""
    v: list[Violation] = []
    last_ready: dict = {}          # (job, r) -> index of latest part_ready
    last_map_expire: dict = {}     # job -> index of latest map expire
    for i, e in enumerate(events or []):
        ev, job = e.get("ev"), e.get("job")
        if ev == "part_ready" and e.get("phase") == "reduce":
            last_ready[(job, e.get("tid"))] = i
        elif ev == "expire" and e.get("phase") == "map":
            last_map_expire[job] = i
        elif ev == "part_retract" and e.get("phase") == "reduce":
            ready_i = last_ready.get((job, e.get("tid")))
            expire_i = last_map_expire.get(job)
            if ready_i is not None and (expire_i is None
                                        or expire_i < ready_i):
                v.append(Violation(
                    "readiness-monotone-per-attempt",
                    f"reduce {e.get('tid')} readiness retracted with no "
                    "map lease expiry since it was established — "
                    "readiness regressed under live attempts",
                    [events[ready_i], e],
                ))
    return v


class _ModelService:
    """JobService harness (focus=service): two submitted jobs over a one-
    worker fleet with service_max_jobs=1, so job B queues behind A — the
    mid-queue-cancel surface. Journals (service rows + per-job
    coordinator journals) captured in memory."""

    kind = "service"

    def __init__(self, cfg: Config, specs: list,
                 clock: "VirtualClock | None" = None):
        # Local import: service/server pulls the app registry — still
        # jax-free, but heavier than the coordinator path.
        from mapreduce_rust_tpu.service.server import JobService

        self.cfg = cfg
        self.specs = specs
        self.clock = clock or VirtualClock()
        self.rows: list[dict] = []
        self.job_journals: dict[str, list[str]] = {}
        harness = self

        class _Svc(JobService):
            def _journal(self, op, jid, **fields):
                row = {"op": op, "job": jid,
                       "t": round(self.report.uptime_s(), 3)}
                row.update({k: v for k, v in fields.items()
                            if isinstance(v, (str, int, float, bool))})
                harness.rows.append(row)

            def _admit(self, job):
                super()._admit(job)
                if job.coord is not None:
                    harness._capture_job_journal(job)

        self.svc = _Svc(cfg, resume=False, now=self.clock)
        self.svc.get_worker_id()
        self.jids = []
        for spec in specs:
            res = self.svc.submit_job(dict(spec))
            if not res.get("ok"):
                raise RuntimeError(f"model submit failed: {res}")
            self.jids.append(res["job"])
        self.held: dict[int, tuple] = {}  # wid -> (jid, phase, tid,
        #                                          attempt, reduce_n)
        self.mutated = False
        self.replay_violations: list[Violation] = []

    def _capture_job_journal(self, job) -> None:
        mem = self.job_journals.setdefault(job.jid, [])
        coord = job.coord

        def _mem_journal(phase_name, tid, attempt=0, wid=-1,
                         _coord=coord, _mem=mem):
            suffix = f" j{_coord.job_id}" if _coord.job_id else ""
            _mem.append(
                f"{phase_name} {tid} a{attempt} w{wid} "
                f"t{_coord.report.uptime_s():.3f}{suffix}")

        coord._journal = _mem_journal

    # -- state --

    def finished(self) -> bool:
        return all(j.state in ("done", "cancelled", "failed")
                   for j in self.svc.jobs.values())

    def fingerprint(self) -> tuple:
        leases = []
        for jid, job in sorted(self.svc.jobs.items()):
            if job.coord is not None:
                leases.append((jid, tuple(sorted(job.coord.map.leases)),
                               tuple(sorted(job.coord.reduce.leases)),
                               tuple(sorted(job.coord.map.reported)),
                               tuple(sorted(job.coord.reduce.reported))))
        return (
            len(self.rows), tuple(sorted(self.held.items())),
            tuple((jid, j.state) for jid, j in sorted(self.svc.jobs.items())),
            tuple(leases),
            tuple((jid, len(m)) for jid, m
                  in sorted(self.job_journals.items())),
            self.mutated,
        )

    def enabled(self, mutate: "str | None" = None) -> list[tuple]:
        evs: list[tuple] = []
        if 0 in self.held:
            evs.append(("finish", 0))
            evs.append(("renew", 0))
        elif not self.finished():
            evs.append(("poll", 0))
        if any(j.coord is not None
               and (j.coord.map.leases or j.coord.reduce.leases)
               for j in self.svc.running.values()):
            evs.append(("expire",))
        for jid in self.jids:
            job = self.svc.jobs.get(jid)
            if job is not None and job.state in ("queued", "joined",
                                                 "running"):
                evs.append(("cancel", jid))
        if mutate and not self.mutated:
            evs.append(("mutate",))
        return evs

    # -- event application --

    def apply(self, ev: tuple) -> dict:
        self.clock.advance(_TICK)
        kind = ev[0]
        info = {"changed": False, "task": None,
                "desc": " ".join(str(x) for x in ev)}
        before = self.fingerprint()
        if kind == "poll":
            wid = 0
            if wid in self.held:
                return info
            grant = self.svc.get_task(wid)
            if isinstance(grant, dict):
                jid, phase, tid = grant["job"], grant["phase"], grant["tid"]
                job = self.svc.jobs[jid]
                self.held[wid] = (jid, phase, tid, grant["attempt"],
                                  job.cfg.reduce_n)
                info["task"] = (phase, tid)
                info["desc"] = (f"poll w{wid} -> grant {jid} {phase}:{tid} "
                                f"a{grant['attempt']}")
        elif kind == "finish":
            held = self.held.pop(0, None)
            if held is None:
                return info
            jid, phase, tid, attempt, reduce_n = held
            info["task"] = (phase, tid)
            if phase == "map":
                self.svc.report_map_task_finish(
                    tid, attempt=attempt, wid=0, job=jid,
                    part_bytes=[1] * reduce_n)
            else:
                self.svc.report_reduce_task_finish(
                    tid, attempt=attempt, wid=0, job=jid)
            info["desc"] = f"finish w0 {jid} {phase}:{tid} a{attempt}"
        elif kind == "renew":
            held = self.held.get(0)
            if held is None:
                return info
            jid, phase, tid, attempt, _rn = held
            info["task"] = (phase, tid)
            method = ("renew_map_lease" if phase == "map"
                      else "renew_reduce_lease")
            ok = getattr(self.svc, method)(tid, 0, None, jid)
            resp: dict = {}
            self.svc._enrich_response(
                method, {"params": [tid, 0, None, jid]}, ok, resp)
            if resp.get("revoked"):
                self.held.pop(0, None)
                info["revoked"] = True
                info["desc"] = f"renew w0 {jid} {phase}:{tid} -> revoked"
        elif kind == "expire":
            self.clock.advance(self.cfg.lease_timeout_s + _TICK)
            for job in list(self.svc.running.values()):
                if job.coord is not None:
                    job.coord.check_lease()
            info["desc"] = "expire (all running jobs' detectors)"
        elif kind == "cancel":
            jid = ev[1]
            job = self.svc.jobs.get(jid)
            if job is None or job.state not in ("queued", "joined",
                                                "running"):
                return info
            st = job.state
            self.svc.cancel_job(jid)
            info["desc"] = f"cancel {jid} (was {st})"
        elif kind == "mutate":
            if self.mutated:
                return info
            self.mutated = True
            info["desc"] = "mutate (arm artifact corruption)"
        info["changed"] = self.fingerprint() != before
        return info

    # -- model-only invariants / validation --

    def drain(self) -> bool:
        cap = 16 * (len(self.jids) + 1) * (self.cfg.reduce_n + 2) + 32
        for _ in range(cap):
            if self.finished():
                return True
            fp = self.fingerprint()
            self.apply(("finish", 0) if 0 in self.held else ("poll", 0))
            if self.fingerprint() == fp:
                self.apply(("expire",))
                if self.fingerprint() == fp:
                    return self.finished()
        return self.finished()

    def step_violations(self) -> list[Violation]:
        v = check_service_journal(self.rows)
        for jid, job in sorted(self.svc.jobs.items()):
            rep = (job.coord.report.to_dict() if job.coord is not None
                   else job.report_dict)
            if rep:
                v += check_stream(rep.get("events") or [])
        return v

    def artifacts(self) -> dict:
        events: list = []
        journal: list = []
        report = None
        for jid, job in sorted(self.svc.jobs.items()):
            rep = (job.coord.report.to_dict() if job.coord is not None
                   else job.report_dict)
            if rep:
                events += rep.get("events") or []
                if report is None:
                    report = rep
            journal += parse_journal("".join(
                line + "\n" for line in self.job_journals.get(jid, [])))
        return {"events": events, "journal": journal, "report": report,
                "rows": list(self.rows), "trace": None}

    def leaf_violations(self) -> list[Violation]:
        v = check_service_journal(self.rows)
        for jid, job in sorted(self.svc.jobs.items()):
            rep = (job.coord.report.to_dict() if job.coord is not None
                   else job.report_dict)
            journal = parse_journal("".join(
                line + "\n" for line in self.job_journals.get(jid, [])))
            if rep:
                v += check_stream(rep.get("events") or [], journal, rep)
        if not self.mutated and not self.drain():
            v.append(Violation(
                "no-grant-starvation",
                "the service drain loop could not settle every job from "
                "this schedule's final state",
                [{"ev": "drain-wedged"},
                 {"ev": "jobs", "states": {
                     jid: j.state
                     for jid, j in sorted(self.svc.jobs.items())}}],
            ))
        return v


# ---------------------------------------------------------------------------
# In-memory mutation table (mirrors mrcheck.MUTATIONS file mutators)
# ---------------------------------------------------------------------------

def _last(rows: list, ev: str) -> "dict | None":
    for e in reversed(rows):
        if e.get("ev") == ev:
            return e
    return None


def _row(ev: str, base: dict, **over) -> dict:
    row = {k: base[k] for k in ("t", "job", "phase", "tid", "attempt",
                                "wid") if k in base}
    row["ev"] = ev
    row.update(over)
    return row


def _mut_double_win(a: dict) -> bool:
    f = _last(a["events"], "finish")
    if f is None:
        return False
    a["events"].append(_row("finish", f,
                            attempt=(f.get("attempt") or 1) + 1))
    return True


def _mut_report_after_revoke(a: dict) -> bool:
    for i, e in enumerate(a["events"]):
        if e.get("ev") == "finish":
            a["events"].insert(i, _row("revoke", e))
            return True
    return False


def _mut_grant_over_live_lease(a: dict) -> bool:
    for i, e in enumerate(a["events"]):
        if e.get("ev") == "grant":
            a["events"].insert(
                i + 1, _row("grant", e, attempt=(e.get("attempt") or 1) + 1))
            return True
    return False


def _mut_expire_without_lease(a: dict) -> bool:
    for i, e in enumerate(a["events"]):
        if e.get("ev") == "finish":
            a["events"].insert(i + 1, _row("expire", e))
            return True
    return False


def _mut_finish_without_grant(a: dict) -> bool:
    g = _last(a["events"], "grant") or _last(a["events"], "finish")
    if g is None:
        return False
    a["events"].append(_row("finish", g, tid=(g.get("tid") or 0) + 9001))
    return True


def _mut_grant_after_deregister(a: dict) -> bool:
    for i, e in enumerate(a["events"]):
        if e.get("ev") == "grant" and e.get("wid") is not None:
            a["events"].insert(i, {"t": e.get("t"), "ev": "deregister",
                                   "wid": e["wid"]})
            return True
    return False


def _mut_truncate_event_log(a: dict) -> bool:
    if a.get("report") is None:
        return False
    a["report"] = dict(a["report"])
    a["report"]["events_dropped"] = (
        a["report"].get("events_dropped") or 0) + 3
    return True


def _mut_journal_without_finish(a: dict) -> bool:
    rep = a.get("report") or {}
    for phase, tasks in sorted((rep.get("tasks") or {}).items()):
        for tid_s, entry in sorted(tasks.items()):
            if not entry.get("reports", 0):
                try:
                    tid = int(tid_s)
                except ValueError:
                    continue
                raw = f"{phase} {tid} a1 w0 t9.999"
                a["journal"] = list(a["journal"] or []) + [JournalLine(
                    phase, tid, 1, 0, 9.999,
                    len(a["journal"] or []) + 1, raw)]
                return True
    return False


def _mut_finish_without_journal(a: dict) -> bool:
    if not a.get("journal"):
        return False
    a["journal"] = list(a["journal"])[:-1]
    return True


def _mut_grant_across_jobs(a: dict) -> bool:
    if not a.get("journal") or a.get("report") is None:
        return False
    a["report"] = dict(a["report"])
    a["report"]["job"] = "jA"
    a["journal"] = list(a["journal"])
    ln = a["journal"][-1]
    a["journal"][-1] = JournalLine(ln.phase, ln.tid, ln.attempt, ln.wid,
                                   ln.t, ln.line, ln.raw, job="jB")
    return True


def _mut_job_lifecycle(a: dict) -> bool:
    rows = a.get("rows")
    if not rows:
        return False
    for row in rows:
        if row.get("op") in ("done", "cancel"):
            a["rows"] = list(rows) + [dict(row)]
            return True
    return False


def _mut_drop_terminator(a: dict) -> bool:
    for ln in reversed(a.get("journal") or []):
        if ln.attempt:
            fid = f"{ln.phase}:{ln.tid}:{ln.attempt}"
            if ln.job:
                fid = f"{ln.job}:{fid}"
            a["trace"] = [{
                "name": "task", "ph": "s", "id": fid, "ts": 1,
                "pid": 1, "tid": 1,
                "args": {"phase": ln.phase, "tid": ln.tid},
            }]
            return True
    return False


def _mut_write_race(a: dict) -> bool:
    ln = (a.get("journal") or [None])[-1]
    if ln is None:
        return False
    args = {"phase": ln.phase, "tid": ln.tid}
    a["trace"] = [
        {"name": "coordinator.journal", "ph": "i", "ts": 1, "pid": 1,
         "tid": 1, "args": dict(args)},
        {"name": "coordinator.journal", "ph": "i", "ts": 1, "pid": 2,
         "tid": 1, "args": dict(args)},
    ]
    return True


def _mut_early_reduce_grant(a: dict) -> bool:
    # Mirrors mrcheck.mutate_early_reduce_grant: clone a reduce grant to
    # BEFORE the first map finish (no part_ready can cover it there),
    # with a matching expire so the recording's real grant of the same
    # tid doesn't cross-fire grant-over-live-lease. Needs a schedule
    # that reached both a map finish and a reduce grant.
    events = a["events"]
    mf = next(((i, e) for i, e in enumerate(events)
               if e.get("ev") == "finish" and e.get("phase") == "map"),
              None)
    g = next((e for e in events
              if e.get("ev") == "grant" and e.get("phase") == "reduce"),
             None)
    if mf is None or g is None:
        return False
    i, first_map_fin = mf
    t = max((first_map_fin.get("t") or 0.0) - 0.002, 0.0)
    ghost = dict(g)
    ghost["t"] = t
    exp = {"t": t + 0.001, "ev": "expire", "phase": "reduce",
           "tid": g.get("tid"), "attempt": g.get("attempt")}
    if g.get("job") is not None:
        exp["job"] = g["job"]
    a["events"] = events[:i] + [ghost, exp] + events[i:]
    return True


def _mut_lineage_claim(a: dict) -> bool:
    # Mirrors mrcheck.mutate_lineage_conservation: a partition claims a
    # chunk digest no scan or attempt ever produced. The model has no
    # data plane, so the ledger is synthesized in load_ledger's parsed
    # shape (the file mutator's synthesize precedent) — one honestly
    # scanned chunk plus a part record smuggling a ghost digest into its
    # claim. Any leaf can host it, so the shrunk schedule is just the
    # arming event.
    a["lineage"] = {
        "chunks": [{"t": "chunk", "seq": 0, "doc": 0, "bytes": 64,
                    "dg": "ab" * 16, "parts": [0]}],
        "attempts": [],
        "parts": [{"t": "part", "r": 0, "bytes": 64,
                   "chunks": ["ab" * 16, "deadbeef" * 4]}],
    }
    return True


#: In-memory corruption per mrcheck.MUTATIONS class: same keys, same
#: violation codes, applied to a leaf's captured artifacts instead of
#: files on disk. A mutator returns False when the schedule cannot host
#: the corruption yet (e.g. no journal line to drop) — exploration keeps
#: looking for one that can.
MODEL_MUTATORS: dict = {
    "double-win": _mut_double_win,
    "report-after-revoke": _mut_report_after_revoke,
    "grant-over-live-lease": _mut_grant_over_live_lease,
    "expire-without-lease": _mut_expire_without_lease,
    "finish-without-grant": _mut_finish_without_grant,
    "grant-after-deregister": _mut_grant_after_deregister,
    "truncated-event-log": _mut_truncate_event_log,
    "journal-without-finish": _mut_journal_without_finish,
    "finish-without-journal": _mut_finish_without_journal,
    "grant-across-jobs": _mut_grant_across_jobs,
    "job-lifecycle": _mut_job_lifecycle,
    "missing-terminator": _mut_drop_terminator,
    "write-race": _mut_write_race,
    "early-reduce-grant": _mut_early_reduce_grant,
    "lineage-conservation": _mut_lineage_claim,
}

#: Which focus hosts each mutation class (the teeth test's routing):
#: service-journal classes need the JobService harness, the readiness
#: class needs the pipelined scheduler, everything else the lease focus.
MUTATION_FOCUS: dict = {
    "job-lifecycle": "service",
    "early-reduce-grant": "pipeline",
}


def _validate_mutated(a: dict) -> list[Violation]:
    v = check_stream(a["events"], a.get("journal"), a.get("report"))
    v += _check_readiness_monotone(a["events"])
    if a.get("rows") is not None:
        v += check_service_journal(a["rows"])
    if a.get("trace") is not None:
        try:
            v += check_trace(a["trace"], a.get("journal"))
        except ValueError:
            pass
    if a.get("lineage") is not None:
        v += check_lineage(a["lineage"])
    return v


# ---------------------------------------------------------------------------
# Explorer: bounded DFS with DPOR-style pruning
# ---------------------------------------------------------------------------

#: Canonical event order — the DPOR representative: of two adjacent
#: commuting events, only the canonically-ordered interleaving is
#: explored; the transposed one is pruned (same Mazurkiewicz trace).
_KIND_RANK = {"poll": 0, "finish": 1, "renew": 2, "deregister": 3,
              "cancel": 4, "expire": 5, "replay": 6, "mutate": 7}
_COMMUTING = ("finish", "renew", "deregister")


def _key(ev: tuple) -> tuple:
    return (_KIND_RANK.get(ev[0], 9), *[str(x) for x in ev[1:]])


def _commutes(last_ev: tuple, last_task, cand: tuple, cand_task) -> bool:
    """Two worker-local operations on distinct workers touching distinct
    (phase, tid) machines commute: both orders yield the same state and
    the same Mazurkiewicz trace. Anything global (poll's grant counter,
    expire's clock jump, cancel/replay/mutate) commutes with nothing."""
    if last_ev[0] not in _COMMUTING or cand[0] not in _COMMUTING:
        return False
    if last_ev[1:2] == cand[1:2]:
        return False  # same worker: program order
    if last_task is not None and cand_task is not None \
            and last_task == cand_task:
        return False  # same (phase, tid): first-wins races don't commute
    return True


class _Budget(Exception):
    pass


class _Explorer:
    def __init__(self, make_harness, budget: int, depth: int, seed: int,
                 mutate: "str | None"):
        self.make_harness = make_harness
        self.budget = budget
        self.depth = depth
        self.rng = random.Random(seed)
        self.mutate = mutate
        self.explored = 0
        self.pruned = 0
        self.steps = 0
        self.counterexample: "dict | None" = None

    def replay(self, schedule: list):
        h = self.make_harness()
        infos = []
        for ev in schedule:
            infos.append(h.apply(ev))
            self.steps += 1
        return h, infos

    def run(self) -> None:
        try:
            self._explore([])
        except _Budget:
            pass

    def _leaf(self, h, schedule: list) -> None:
        self.explored += 1
        violations = h.leaf_violations()
        if self.mutate:
            if h.mutated:
                a = h.artifacts()
                if MODEL_MUTATORS[self.mutate](a):
                    mv = [x for x in _validate_mutated(a)
                          if x.code == self.mutate]
                    if mv:
                        self._record(schedule, mv[0])
                        return
        elif violations:
            self._record(schedule, violations[0])
            return
        if self.explored >= self.budget:
            raise _Budget

    def _record(self, schedule: list, violation: Violation) -> None:
        self.counterexample = {"schedule": list(schedule),
                               "violation": violation}
        raise _Budget

    def _explore(self, prefix: list, last: "tuple | None" = None,
                 last_task=None) -> None:
        h, _infos = self.replay(prefix)
        if not self.mutate:
            v = h.step_violations()
            if v:
                self._record(prefix, v[0])
        if len(prefix) >= self.depth or h.finished():
            self._leaf(h, prefix)
            return
        cands = sorted(h.enabled(mutate=self.mutate), key=_key)
        if not cands:
            self._leaf(h, prefix)
            return
        # Seeded rotation: the canonical candidate SET is explored in
        # full either way; the starting point only decides which
        # subtrees a truncated budget reaches first.
        rot = self.rng.randrange(len(cands))
        cands = cands[rot:] + cands[:rot]
        for ev in cands:
            cand_task = None
            if ev[0] in _COMMUTING and len(ev) > 1:
                held = h.held.get(ev[1])
                cand_task = held[-4:-2] if h.kind == "service" and held \
                    else (held[0], held[1]) if held else None
            if last is not None and _commutes(last, last_task, ev,
                                              cand_task) \
                    and _key(ev) < _key(last):
                # The transposed order was (or will be) explored from
                # this node's parent — same Mazurkiewicz trace.
                self.pruned += 1
                continue
            h2, infos = self.replay(prefix + [ev])
            if not infos[-1]["changed"]:
                # Stutter pruning: the event moved nothing, so the
                # subtree duplicates this node's other branches.
                self.pruned += 1
                continue
            self._explore(prefix + [ev], ev, infos[-1]["task"])


# ---------------------------------------------------------------------------
# Focus configurations
# ---------------------------------------------------------------------------

def _lease_cfg() -> Config:
    return Config(map_n=2, reduce_n=2, worker_n=2, lease_timeout_s=5.0,
                  speculate=True, speculate_after_frac=0.5,
                  metrics_enabled=False)


def _pipeline_cfg() -> Config:
    return Config(map_n=2, reduce_n=2, worker_n=2, lease_timeout_s=5.0,
                  sched="pipeline", metrics_enabled=False)


def _service_setup(workdir: str):
    """(cfg, specs) for the service focus: a tiny real corpus (submit
    scans it), one worker, max_jobs=1 so the second submission queues."""
    import os

    corpus = os.path.join(workdir, "model-corpus")
    os.makedirs(corpus, exist_ok=True)
    doc = os.path.join(corpus, "doc-0.txt")
    if not os.path.exists(doc):
        with open(doc, "w") as f:
            f.write("alpha beta beta gamma\n")
    cfg = Config(map_n=1, reduce_n=2, worker_n=1, lease_timeout_s=5.0,
                 service_max_jobs=1, metrics_enabled=False,
                 input_dir=corpus,
                 work_dir=os.path.join(workdir, "model-work"),
                 output_dir=os.path.join(workdir, "model-out"))
    specs = [
        {"app": "word_count", "input_dir": corpus, "reduce_n": 2},
        {"app": "grep", "app_args": {"query": ["beta"]},
         "input_dir": corpus, "reduce_n": 2},
    ]
    return cfg, specs


def make_harness_factory(focus: str, workdir: "str | None" = None):
    """A zero-arg callable minting a fresh harness (one per explored
    schedule). Configs are built once; service corpus written once."""
    if focus == "lease":
        cfg = _lease_cfg()
        return lambda: CoordinatorHarness(cfg)
    if focus == "pipeline":
        cfg = _pipeline_cfg()
        return lambda: CoordinatorHarness(cfg)
    if focus == "service":
        if workdir is None:
            raise ValueError("service focus needs a workdir "
                             "(run_model provides one)")
        cfg, specs = _service_setup(workdir)
        return lambda: _ModelService(cfg, specs)
    raise ValueError(f"unknown focus {focus!r} "
                     "(choose pipeline, lease or service)")


# ---------------------------------------------------------------------------
# Counterexample shrinking + chaos export
# ---------------------------------------------------------------------------

def shrink(schedule: list, fails) -> list:
    """Delta debugging by single-event removal to a 1-minimal sequence:
    every remaining event is necessary (dropping any one of them makes
    the violation vanish). ``fails(candidate)`` replays from scratch."""
    changed = True
    while changed:
        changed = False
        i = 0
        while i < len(schedule):
            cand = schedule[:i] + schedule[i + 1:]
            if fails(cand):
                schedule = cand
                changed = True
            else:
                i += 1
    return schedule


def chaos_spec(seed: int, infos: list, lease_timeout_s: float) -> str:
    """Render a shrunk schedule's fault content in the PR-6 chaos
    grammar, so the counterexample replays on the real OS-process
    cluster: an expired task maps to ``kill`` (the attempt dies and the
    detector recovers it), a revoked renewal to ``wedge_renewal`` (the
    heartbeat goes quiet under a live task), a late duplicate finish to
    ``delay_finish`` past the lease window. Validated by round-tripping
    through ChaosPlan.parse."""
    faults: list[str] = []

    def add(f: str) -> None:
        if f not in faults:
            faults.append(f)

    for info in infos:
        for phase, tid in info.get("expired") or []:
            add(f"kill:{phase}:{tid}")
        if info.get("revoked") and info.get("task"):
            phase, tid = info["task"]
            add(f"wedge_renewal:{phase}:{tid}")
        if info.get("late") and info.get("task"):
            phase, tid = info["task"]
            add(f"delay_finish:{phase}:{tid}:{lease_timeout_s * 1.5:.1f}"
                ":attempt=*")
    if not faults:
        # Schedule-only counterexample (ordering, cancel, mutation):
        # anchor the repro with a benign straggler pause so the spec
        # still parses and perturbs the same schedule region.
        faults.append("pause:map:0:0.1")
    return chaos_mod.build_spec(seed, faults)


# ---------------------------------------------------------------------------
# Driver + CLI
# ---------------------------------------------------------------------------

def run_model(focus: str = "lease", budget: int = 5000, depth: int = 12,
              seed: int = 0, mutate: "str | None" = None,
              workdir: "str | None" = None) -> dict:
    """Explore one focus. Returns the mrmodel document; deterministic
    for a given (focus, budget, depth, seed, mutate) except the timing
    fields (``elapsed_s``/``schedules_per_s``)."""
    if mutate is not None and mutate not in MODEL_MUTATORS:
        raise ValueError(
            f"unknown mutation class {mutate!r} "
            f"(have: {', '.join(sorted(MODEL_MUTATORS))})")
    if mutate is not None and mutate not in MUTATIONS:
        raise ValueError(f"{mutate!r} not in mrcheck.MUTATIONS")
    tmp = None
    if focus == "service" and workdir is None:
        import shutil
        import tempfile

        tmp = workdir = tempfile.mkdtemp(prefix="mrmodel-")
    try:
        factory = make_harness_factory(focus, workdir=workdir)
        ex = _Explorer(factory, budget=budget, depth=depth, seed=seed,
                       mutate=mutate)
        t0 = time.perf_counter()
        with _quiet():
            ex.run()
        elapsed = time.perf_counter() - t0

        counterexamples = []
        if ex.counterexample is not None:
            sched = ex.counterexample["schedule"]
            target = ex.counterexample["violation"].code

            def fails(cand: list) -> bool:
                h, _infos = ex.replay(cand)
                if mutate:
                    if not h.mutated:
                        return False
                    a = h.artifacts()
                    if not MODEL_MUTATORS[mutate](a):
                        return False
                    return any(x.code == target for x in _validate_mutated(a))
                v = h.step_violations() + h.leaf_violations()
                return any(x.code == target for x in v)

            with _quiet():
                minimal = shrink(sched, fails)
                h, infos = ex.replay(minimal)
                if mutate:
                    a = h.artifacts()
                    MODEL_MUTATORS[mutate](a)
                    violation = next(x for x in _validate_mutated(a)
                                     if x.code == target)
                else:
                    violation = next(x for x in h.step_violations()
                                     + h.leaf_violations()
                                     if x.code == target)
            lease_s = h.cfg.lease_timeout_s
            counterexamples.append({
                "code": violation.code,
                "message": violation.message,
                "events": violation.events,  # the offending pair
                "schedule": [list(ev) for ev in minimal],
                "length": len(minimal),
                "trace": [i["desc"] for i in infos],
                "chaos_spec": chaos_spec(seed, infos, lease_s),
            })

        return {
            "tool": "mrmodel",
            "schema": MODEL_SCHEMA,
            "focus": focus,
            "budget": budget,
            "depth": depth,
            "seed": seed,
            "mutate": mutate,
            "explored": ex.explored,
            "pruned": ex.pruned,
            "steps": ex.steps,
            "elapsed_s": round(elapsed, 3),
            "schedules_per_s": round(ex.explored / elapsed, 1) if elapsed > 0
            else None,
            "ok": not counterexamples,
            "counterexamples": counterexamples,
            "invariants": sorted(INVARIANTS),
            "model_invariants": sorted(MODEL_INVARIANTS),
        }
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def format_doc(doc: dict) -> str:
    lines = [
        f"mrmodel: focus={doc['focus']} explored {doc['explored']} "
        f"schedule(s), pruned {doc['pruned']}, {doc['steps']} step(s) "
        f"in {doc['elapsed_s']}s"
        + (f" ({doc['schedules_per_s']}/s)"
           if doc.get("schedules_per_s") else "")
        + (f" [mutate={doc['mutate']}]" if doc.get("mutate") else ""),
    ]
    for ce in doc["counterexamples"]:
        lines.append(f"COUNTEREXAMPLE [{ce['code']}] {ce['message']}")
        for step, desc in enumerate(ce["trace"], start=1):
            lines.append(f"  {step:2d}. {desc}")
        for e in ce["events"]:
            lines.append(f"  offending: {json.dumps(e, sort_keys=True)}")
        lines.append(f"  chaos repro: {ce['chaos_spec']}")
    lines.append(
        f"mrmodel: {'ok' if doc['ok'] else 'FAILED'} "
        f"({len(doc['counterexamples'])} counterexample(s), "
        f"{len(doc['invariants'])} + {len(doc['model_invariants'])} "
        "invariants checked)")
    return "\n".join(lines)


def run_cli(args) -> int:
    """``model`` subcommand body. Exit 0 = every explored schedule
    conformant, 1 = counterexample found, 2 = unusable arguments."""
    try:
        doc = run_model(
            focus=getattr(args, "focus", "lease"),
            budget=getattr(args, "budget", 5000),
            depth=getattr(args, "depth", 12),
            seed=getattr(args, "seed", 0),
            mutate=getattr(args, "mutate", None),
        )
    except (ValueError, RuntimeError, OSError) as e:
        print(f"mrmodel: {e}", file=sys.stderr)
        return 2
    if getattr(args, "format", "text") == "json":
        print(json.dumps(doc, indent=2, sort_keys=True, default=str))
    else:
        print(format_doc(doc))
    return 0 if doc["ok"] else 1
