"""mrcheck: distributed protocol conformance checker + happens-before
race detector (ISSUE 7 tentpole).

The chaos harness (PR 6) proves recovery *end-to-end* but judges only
final bytes — a protocol violation that happens to produce correct output
(a double-granted lease, a report accepted after revocation, a journal
line racing a re-execution) sails through silently. This module replays
the control-plane artifacts a run already writes — the coordinator
journal, the job report's ordered event log (PR 7), the coordinator
manifest and (optionally) a merged trace — against an explicit model of
the coordinator protocol, and reports every violation with the offending
event pair and its wall-clock context.

**The protocol model.** Per (phase, tid) the lease/attempt machine is::

    granted -> renewed* -> { finished | expired | revoked | drained }

with the invariants below (the catalog README's "Correctness tooling"
section documents, each traced to the bug class that motivated it):

- ``double-win``            at most one winner per (phase, tid): the
                            journal holds exactly one line, the event log
                            exactly one journaling finish (the idempotent-
                            finish bug class of PR 4).
- ``report-after-revoke``   a revoked attempt never journals: revocation
                            means another attempt already won (PR 6
                            speculation); its report may land only as a
                            late report, never as the winner.
- ``grant-over-live-lease`` no grant while a live lease holds the tid —
                            except a speculation grant, which SHARES the
                            existing lease (never forks a second one).
- ``expire-without-lease``  an expiry needs a live lease: a second expiry
                            for one tid, or an expiry after its finish, is
                            how a forked speculation lease (or a lease
                            surviving its task) shows up in the log.
- ``finish-without-grant``  a completion for a task never granted.
- ``grant-after-deregister`` a drained (deregistered) worker is never
                            granted again (PR 6 SIGTERM drain).
- ``truncated-event-log``   the report's event log hit its cap and
                            dropped rows — a replay against an incomplete
                            log must never read as fully conformant.
- ``journal-without-finish`` a journal line whose task the report says
                            never completed (the journal-line-racing-a-
                            re-execution class).
- ``finish-without-journal`` a completed task with no journal line (a
                            winner that never journaled cannot seed a
                            resume).
- ``missing-terminator``    the journal-winning attempt's flow chain must
                            be terminated in the trace (a dropped "f" is a
                            finish report the timeline never saw land).
- ``write-race``            two writes to the same (phase, tid)
                            journal/report state with no happens-before
                            path between them — flagged even when the
                            idempotence guard made the outcome benign.

**The happens-before model** (``--trace``, a merged or per-process trace):
program order within each (pid, tid) thread; flow chains ``s -> t -> f``
(grant -> task -> finish, PR 4); and RPC request/response pairs — the
client's ``rpc.send``/``rpc.recv`` instants bracket the coordinator's
``rpc.*`` span through a shared call id (``cid``), giving send ≤ handle
and handle-end ≤ recv. Writes are the events that mutate authoritative
(phase, tid) completion state: ``coordinator.journal`` instants and
non-revoked flow terminators (a worker's report *send* is a message, and
a revoked terminator mutates nothing). Vector clocks over that DAG decide
concurrency. In today's single-threaded coordinator every write is
program-ordered, so a conformant run can never race — the detector exists
for corrupted/reordered artifacts and for the multi-tenant,
multi-threaded coordinator ROADMAP item 2 will make real.

**Seeded-violation fixtures.** ``MUTATIONS`` corrupts a recorded run's
artifacts — double-win, report-after-revoke, grant-over-live-lease,
dropped flow terminator, write race — so every invariant has a known-bad
fixture proving it fires (tests/test_mrcheck.py), while the fault-free
run and the full chaos matrix prove zero false positives
(tests/test_check_clean.py, bench.py --chaos).

Pure stdlib, no jax — importable from any control-plane process
(package rule).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys

CHECK_SCHEMA = 1

#: code -> (source artifacts, one-line description). The catalog is data,
#: not prose, so tests can assert every invariant has a seeded-violation
#: fixture and README can render it without drifting.
INVARIANTS: dict[str, tuple[str, str]] = {
    "double-win": (
        "journal+events",
        "at most one winner per (phase, tid): one journal line, one "
        "journaling finish",
    ),
    "report-after-revoke": (
        "events+trace",
        "a revoked attempt never journals — its report lands late or not "
        "at all",
    ),
    "grant-over-live-lease": (
        "events",
        "no grant while a live lease holds the tid (speculation shares "
        "the lease, never forks one)",
    ),
    "expire-without-lease": (
        "events",
        "an expiry needs a live lease: double expiry / expiry-after-"
        "finish means a forked or leaked lease",
    ),
    "finish-without-grant": (
        "events",
        "a completion for a task never granted",
    ),
    "grant-after-deregister": (
        "events",
        "a deregistered (drained) worker is never granted again",
    ),
    "truncated-event-log": (
        "events",
        "the event log hit its cap and dropped rows — every event-backed "
        "invariant was checked against an incomplete log",
    ),
    "journal-without-finish": (
        "journal+report",
        "every journal line names a task the report saw complete",
    ),
    "finish-without-journal": (
        "journal+report",
        "every completed task journaled exactly once (resume depends on "
        "it)",
    ),
    "missing-terminator": (
        "trace+journal",
        "the journal-winning attempt's flow chain is terminated (a "
        "dropped 'f' is a finish the timeline never saw)",
    ),
    "write-race": (
        "trace",
        "two journal/report-state writes for one (phase, tid) with no "
        "happens-before path between them",
    ),
    "grant-across-jobs": (
        "events+journal",
        "a lease granted under job A is never renewed/finished/expired "
        "under job B — job state is strictly per-job (ISSUE 14: the "
        "multi-tenant service's cross-job misroute class)",
    ),
    "job-lifecycle": (
        "service-journal",
        "every job's service-journal rows follow the lifecycle machine: "
        "submit before start/done/cancel, at most one terminal row, no "
        "rows after a terminal (double start = restart re-admission and "
        "done-without-start = cache hit are legal)",
    ),
    "early-reduce-grant": (
        "events",
        "a reduce task granted before the map barrier opens must be "
        "covered by a live part_ready — every map task reported bytes "
        "for its partition, net of part_retract (ISSUE 17: the pipelined "
        "per-partition release can never hand a reducer a partition "
        "whose inputs are still being written)",
    ),
    "lineage-conservation": (
        "lineage",
        "provenance conserves data: every partition's claimed chunk set "
        "⊆ the chunks some finished attempt (or the driver's scan) "
        "actually digested — an output claiming an unscanned chunk is "
        "fabricated provenance — and a re-executed attempt's chunk list "
        "equals its expired predecessor's (ISSUE 20: determinism is what "
        "makes re-execution a recovery, not a different job)",
    ),
}


@dataclasses.dataclass
class Violation:
    """One invariant violation, with the offending event pair."""

    code: str
    message: str
    events: list  # the offending pair (journal lines / event-log rows /
                  # trace events), each rendered as a dict with context

    def format(self) -> str:
        lines = [f"VIOLATION [{self.code}] {self.message}"]
        for e in self.events:
            lines.append(f"  - {_fmt_event(e)}")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {"code": self.code, "message": self.message,
                "events": self.events}


def _fmt_event(e) -> str:
    if not isinstance(e, dict):
        return repr(e)
    if "raw" in e:  # journal line
        return f"journal:{e.get('line', '?')} {e['raw']!r}"
    if "ev" in e:   # report event-log row
        ctx = " ".join(
            f"{k}={e[k]}" for k in ("job", "phase", "tid", "attempt", "wid")
            if k in e
        )
        return f"event t={e.get('t', '?')}s {e['ev']} {ctx}".rstrip()
    if "ph" in e:   # trace event
        args = e.get("args") or {}
        ctx = " ".join(f"{k}={v}" for k, v in sorted(args.items()))
        return (f"trace ts={e.get('ts', '?')}us pid={e.get('pid', '?')} "
                f"{e.get('ph')}:{e.get('name')} {ctx}").rstrip()
    return repr(e)


# ---------------------------------------------------------------------------
# Artifact loading
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class JournalLine:
    phase: str
    tid: int
    attempt: "int | None"
    wid: "int | None"
    t: "float | None"
    line: int      # 1-based line number in the journal file
    raw: str
    job: "str | None" = None  # service jobs annotate ``j<id>`` (ISSUE 14)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_journal(text: str) -> list[JournalLine]:
    """Task-completion lines of a coordinator journal. Annotation fields
    (``a2 w1 t12.345 jj3``) are optional — a pre-annotation journal
    parses with them None, exactly like ``_replay_journal`` ignores
    them. The ``j`` annotation is the owning job id of a multi-tenant
    service's per-job journal."""
    out: list[JournalLine] = []
    lines = text.splitlines()
    if text and not text.endswith("\n") and lines:
        lines.pop()  # torn tail — the coordinator distrusts it too
    for i, line in enumerate(lines, start=1):
        parts = line.split()
        if len(parts) < 2 or parts[0] not in ("map", "reduce"):
            continue  # header / corrupt record
        try:
            tid = int(parts[1])
        except ValueError:
            continue
        attempt = wid = t = job = None
        for p in parts[2:]:
            try:
                if p.startswith("a"):
                    attempt = int(p[1:])
                elif p.startswith("w"):
                    wid = int(p[1:])
                elif p.startswith("t"):
                    t = float(p[1:])
                elif p.startswith("j") and len(p) > 1:
                    job = p[1:]
            except ValueError:
                pass  # annotation noise never invalidates the record
        out.append(JournalLine(parts[0], tid, attempt, wid, t, i, line, job))
    return out


def _validate_report(rep, src: str) -> None:
    """A torn or hand-corrupted report must map to exit 2 (unusable
    target), never an AttributeError traceback — which exits 1 and reads
    as 'violations found' to a CI gate that treats 1 and 2 differently."""
    if not isinstance(rep, dict):
        raise ValueError(f"{src}: job report is not a JSON object")
    tasks = rep.get("tasks")
    if tasks is not None:
        if not isinstance(tasks, dict):
            raise ValueError(f"{src}: report 'tasks' is not an object")
        for phase, ts in tasks.items():
            if not isinstance(ts, dict):
                raise ValueError(
                    f"{src}: report tasks[{phase!r}] is not an object")
            for tid_s, entry in ts.items():
                if not isinstance(entry, dict):
                    raise ValueError(
                        f"{src}: report tasks[{phase!r}][{tid_s!r}] is "
                        "not an object")
                try:
                    int(tid_s)
                except (TypeError, ValueError):
                    # Multi-job WRITERS (a ServiceWorker's report spans
                    # every job it served) key task slots "job:tid".
                    _job, sep, tail = str(tid_s).rpartition(":")
                    if not (sep and tail.isdigit()):
                        raise ValueError(
                            f"{src}: report tasks[{phase!r}] key {tid_s!r} "
                            "is not a task id") from None
    events = rep.get("events")
    if events is not None and (
            not isinstance(events, list)
            or not all(isinstance(e, dict) for e in events)):
        raise ValueError(f"{src}: report 'events' is not a list of objects")


def load_artifacts(target: str, journal: "str | None" = None,
                   job_report: "str | None" = None) -> dict:
    """Resolve (journal lines, report dict, source names) from a work dir
    or a manifest/job_report JSON file. Raises FileNotFoundError/ValueError
    on an unusable target — the CLI maps those to exit 2."""
    art: dict = {"journal": None, "report": None, "sources": {},
                 "authoritative": True}
    # EXPLICIT paths must exist: a mistyped --journal/--job-report that
    # silently drops its artifact would skip those invariants and pass as
    # clean — the exact failure mode exit 2 exists to prevent. Only the
    # derived defaults (work-dir / manifest-config lookups) are optional.
    for label, p in (("--journal", journal), ("--job-report", job_report)):
        if p and not os.path.exists(p):
            raise FileNotFoundError(f"{p}: explicit {label} path not found")
    explicit_report = job_report
    if os.path.isdir(target):
        journal = journal or os.path.join(target, "coordinator.journal")
        job_report = job_report or os.path.join(target, "job_report.json")
    else:
        with open(target) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            # A JSON array (e.g. a raw trace fed as the target) must map
            # to exit 2, not an AttributeError traceback — which exits 1
            # and reads as "violations found" to a CI gate.
            raise ValueError(
                f"{target}: not a manifest/job_report object (traces go "
                "under --trace)"
            )
        rep = None
        if doc.get("kind") == "job_report":
            rep = doc.get("report")
        elif "job_report" in doc:        # coordinator manifest
            rep = doc["job_report"]
        elif "report" in doc:            # worker manifest
            rep = doc["report"]
            # A worker's report is its LOCAL view, not the protocol
            # authority: it logs a finish even when the report RPC was
            # dropped (chaos) and a re-granted task as a second
            # grant/finish pair — all legal, none journaling. The
            # state-machine replay and journal cross-checks only run
            # against coordinator-side artifacts; a worker target still
            # gets the journal's internal checks and the trace passes.
            art["authoritative"] = False
        if rep is None and explicit_report is None:
            raise ValueError(
                f"{target}: no job report inside (expected a work dir, a "
                "job_report.json, or a manifest embedding one)"
            )
        if rep is not None:
            art["report"] = rep
            art["sources"]["report"] = target
        work = (doc.get("config") or {}).get("work_dir")
        if journal is None and work \
                and os.path.exists(os.path.join(work, "coordinator.journal")):
            journal = os.path.join(work, "coordinator.journal")
    if journal and os.path.exists(journal):
        with open(journal) as f:
            art["journal"] = parse_journal(f.read())
        art["sources"]["journal"] = journal
    # An EXPLICIT --job-report always wins over whatever the target
    # embedded (its validated path was named on the command line to be
    # checked — silently preferring the manifest's copy would be the
    # skipped-artifact failure mode again), and it is the coordinator's
    # own artifact, so it restores protocol authority even when the
    # target was a worker manifest.
    if job_report and os.path.exists(job_report) and (
            art["report"] is None or explicit_report):
        with open(job_report) as f:
            doc = json.load(f)
        if not isinstance(doc, dict):
            raise ValueError(f"{job_report}: job report is not a JSON object")
        art["report"] = doc.get("report", doc)
        art["sources"]["report"] = job_report
        if explicit_report:
            art["authoritative"] = True
    if art["report"] is None and art["journal"] is None:
        raise FileNotFoundError(
            f"{target}: neither a coordinator.journal nor a job report "
            "found — nothing to check"
        )
    if art["report"] is not None:
        _validate_report(art["report"], art["sources"].get("report", target))
    return art


# ---------------------------------------------------------------------------
# (a) Lease/attempt state-machine conformance
# ---------------------------------------------------------------------------

def check_events(events: list) -> list[Violation]:
    """Replay the ordered event log against the protocol model. Every
    event must be legal in its machine's current state. Machines are
    keyed ``(job, phase, tid)`` (ISSUE 14): a multi-job service's rows
    carry a ``job`` field and two jobs' task 0 are DIFFERENT machines —
    while a continuation event whose job differs from the job holding
    the (phase, tid) grant is the cross-job misroute the
    ``grant-across-jobs`` invariant names. Single-job logs (no job
    field) replay exactly as before: every key shares job None."""
    v: list[Violation] = []
    lease: dict = {}      # (job, phase, tid) -> grant event holding the lease
    spec_armed: dict = {} # (job, phase, tid) -> pending speculate event
    finished: dict = {}   # (job, phase, tid) -> first (journaling) finish
    revoked: dict = {}    # (job, phase, tid) -> [revoke events]
    deregistered: dict = {}  # wid -> deregister event
    granted: dict = {}    # (job, phase, tid) -> last grant event
    granted_pt: dict = {} # (phase, tid) -> {job: last grant event}
    ready: dict = {}      # job -> reduce tids ready (net of part_retract)

    # Pre-pass (ISSUE 17): per job, the log position of the LAST first
    # map finish. A reduce grant positioned before it provably preceded
    # the barrier opening — the only schedule that makes that legal is
    # the per-partition release, so a live part_ready must cover it.
    # Late/duplicate map reports (ev "late_finish", or a repeated tid)
    # don't extend the window: the barrier opened at the first reports.
    evs = list(events or [])
    last_map_first_finish: dict = {}
    _seen_map_fin: set = set()
    for i, e in enumerate(evs):
        if (e.get("ev") == "finish" and e.get("phase") == "map"
                and (e.get("job"), e.get("tid")) not in _seen_map_fin):
            _seen_map_fin.add((e.get("job"), e.get("tid")))
            last_map_first_finish[e.get("job")] = i

    def _cross_job(key, pt) -> "dict | None":
        """The other-job grant a job-mismatched continuation event points
        at: a live lease on (phase, tid) under a DIFFERENT job wins;
        any other job's grant is the fallback evidence."""
        by_job = granted_pt.get(pt) or {}
        for other_job, g in by_job.items():
            if other_job != key[0] and (other_job, *pt) in lease:
                return g
        for other_job, g in by_job.items():
            if other_job != key[0]:
                return g
        return None

    for i, e in enumerate(evs):
        ev = e.get("ev")
        job = e.get("job")
        pt = (e.get("phase"), e.get("tid"))
        key = (job, *pt)
        label = f"{pt[0]} {pt[1]}" + (f" [job {job}]" if job else "")
        if ev == "speculate":
            spec_armed[key] = e
        elif ev == "part_ready":
            if pt[0] == "reduce":
                ready.setdefault(job, set()).add(pt[1])
        elif ev == "part_retract":
            if pt[0] == "reduce":
                ready.setdefault(job, set()).discard(pt[1])
        elif ev == "grant":
            if (pt[0] == "reduce"
                    and i < last_map_first_finish.get(job, -1)
                    and pt[1] not in ready.get(job, ())):
                v.append(Violation(
                    "early-reduce-grant",
                    f"{label} granted before readiness — map finish "
                    "reports were still landing and no live part_ready "
                    "covers the partition (its inputs may still be "
                    "written)",
                    [e, evs[last_map_first_finish[job]]],
                ))
            wid = e.get("wid")
            if wid in deregistered:
                v.append(Violation(
                    "grant-after-deregister",
                    f"{label} granted to worker {wid} after it "
                    "deregistered (drained workers are out of the fleet)",
                    [deregistered[wid], e],
                ))
            if key in lease:
                spec = spec_armed.pop(key, None)
                if spec is None:
                    v.append(Violation(
                        "grant-over-live-lease",
                        f"{label} granted while attempt "
                        f"{lease[key].get('attempt')} still holds a live "
                        "lease (only a speculation may share it)",
                        [lease[key], e],
                    ))
                # Shared lease either way: the model keeps ONE entry.
            else:
                spec_armed.pop(key, None)
                lease[key] = e
            granted[key] = e
            granted_pt.setdefault(pt, {})[job] = e
        elif ev == "expire":
            if key not in lease:
                other = _cross_job(key, pt)
                if other is not None:
                    v.append(Violation(
                        "grant-across-jobs",
                        f"{label} lease expired under a job that never "
                        f"granted it — job {other.get('job')!r} holds "
                        "(phase, tid): job state misrouted across "
                        "tenants",
                        [other, e],
                    ))
                else:
                    prior = finished.get(key) or e
                    v.append(Violation(
                        "expire-without-lease",
                        f"{label} lease expired with no live lease "
                        "— a forked speculation lease or an expiry after "
                        "the task finished",
                        [prior, e],
                    ))
            lease.pop(key, None)
        elif ev == "finish":
            if key not in granted:
                other = _cross_job(key, pt)
                if other is not None:
                    v.append(Violation(
                        "grant-across-jobs",
                        f"{label} reported finished under a job that "
                        f"never granted it — job {other.get('job')!r} "
                        "owns the (phase, tid) lease: a lease granted "
                        "under job A must never be finished under job B",
                        [other, e],
                    ))
                else:
                    v.append(Violation(
                        "finish-without-grant",
                        f"{label} reported finished but was never "
                        "granted in this log",
                        [e],
                    ))
            if key in finished:
                v.append(Violation(
                    "double-win",
                    f"{label} journaled twice — attempt "
                    f"{finished[key].get('attempt')} already won",
                    [finished[key], e],
                ))
            else:
                finished[key] = e
                for r in revoked.get(key, []):
                    v.append(Violation(
                        "report-after-revoke",
                        f"{label} accepted a journaling report "
                        "after the attempt was revoked — the winner must "
                        "be decided before any revocation",
                        [r, e],
                    ))
            lease.pop(key, None)
        elif ev == "revoke":
            revoked.setdefault(key, []).append(e)
        elif ev == "deregister":
            if e.get("wid") is not None:
                deregistered[e["wid"]] = e
        # "late_finish" is legal anywhere after a finish: the idempotence
        # guard's whole point. A late finish with NO prior finish would be
        # a first finish — the coordinator cannot emit that.
    return v


def check_journal(journal: list, report: "dict | None") -> list[Violation]:
    """Cross-check the journal against the report's per-task view."""
    v: list[Violation] = []
    # Job-scoped journals (ISSUE 14): every line of a service job's
    # journal is annotated with the OWNING job id, and the report says
    # whose report it is — a line claiming another job is a completion
    # journaled into the wrong tenant's resume state.
    report_job = (report or {}).get("job")
    if report_job:
        for ln in journal or []:
            if ln.job and ln.job != report_job:
                v.append(Violation(
                    "grant-across-jobs",
                    f"{ln.phase} {ln.tid} journaled under job {ln.job!r} "
                    f"inside job {report_job!r}'s journal — a completion "
                    "written into the wrong tenant's resume state",
                    [ln.to_dict(), {"ev": "report-job", "job": report_job}],
                ))
    seen: dict = {}
    for ln in journal or []:
        key = (ln.phase, ln.tid)
        if key in seen:
            v.append(Violation(
                "double-win",
                f"{ln.phase} {ln.tid} journaled twice (resume would "
                "replay a task two coordinators both claim to own)",
                [seen[key].to_dict(), ln.to_dict()],
            ))
        else:
            seen[key] = ln
    tasks = (report or {}).get("tasks") or {}
    for key, ln in seen.items():
        entry = tasks.get(key[0], {}).get(str(key[1]))
        if entry is not None and not entry.get("reports", 0):
            v.append(Violation(
                "journal-without-finish",
                f"{key[0]} {key[1]} has a journal line but the report "
                "never saw it complete — a journal write raced the task "
                "state",
                [ln.to_dict(), {"ev": "report-entry", **entry,
                                "phase": key[0], "tid": key[1]}],
            ))
    if journal is not None:
        for phase, ts in tasks.items():
            for tid_s, entry in ts.items():
                if entry.get("reports", 0) and \
                        (phase, int(tid_s)) not in seen:
                    v.append(Violation(
                        "finish-without-journal",
                        f"{phase} {tid_s} completed but never journaled — "
                        "a restart would re-run a task whose outputs "
                        "already exist",
                        [{"ev": "report-entry", **entry, "phase": phase,
                          "tid": int(tid_s)}],
                    ))
    return v


# ---------------------------------------------------------------------------
# (b) Happens-before race detection over a (merged) trace
# ---------------------------------------------------------------------------

def _hb_vector_clocks(events: list) -> "tuple[list, list] | None":
    """(nodes, vector clocks) for a trace-event list, or None when a
    cycle prevents the topological pass (broken artifact — the caller
    reports it instead of guessing).

    Nodes are the real events plus one synthetic end-node per cid-carrying
    RPC span (the response leaves AFTER the handler body, so the recv edge
    must originate at span end — an edge from span start would lose the
    journal append that happened inside the handler)."""
    nodes: list[dict] = []
    for seq, ev in enumerate(events):
        if ev.get("ph") == "M":
            continue
        n = dict(ev)
        n["_seq"] = seq
        nodes.append(n)
        if ev.get("ph") == "X" and (ev.get("args") or {}).get("cid"):
            nodes.append({
                "name": ev["name"], "ph": "_span_end",
                "ts": ev["ts"] + ev.get("dur", 0),
                "pid": ev["pid"], "tid": ev["tid"],
                "args": ev.get("args"), "_seq": seq,
            })
    # Program order per (pid, tid).
    threads: dict = {}
    for i, n in enumerate(nodes):
        threads.setdefault((n["pid"], n["tid"]), []).append(i)
    for idxs in threads.values():
        idxs.sort(key=lambda i: (nodes[i]["ts"], nodes[i]["_seq"]))
    tindex = {key: t for t, key in enumerate(sorted(threads, key=str))}

    edges: dict[int, list[int]] = {i: [] for i in range(len(nodes))}
    indeg = [0] * len(nodes)

    def add_edge(a: int, b: int) -> None:
        edges[a].append(b)
        indeg[b] += 1

    for idxs in threads.values():
        for a, b in zip(idxs, idxs[1:]):
            add_edge(a, b)
    # RPC pairs: send -> span start; span end -> recv.
    spans: dict = {}
    ends: dict = {}
    sends: dict = {}
    recvs: dict = {}
    for i, n in enumerate(nodes):
        cid = (n.get("args") or {}).get("cid")
        if not cid:
            continue
        if n.get("ph") == "X":
            spans[cid] = i
        elif n.get("ph") == "_span_end":
            ends[cid] = i
        elif n.get("name") == "rpc.send":
            sends[cid] = i
        elif n.get("name") == "rpc.recv":
            recvs[cid] = i
    for cid, s in sends.items():
        if cid in spans:
            add_edge(s, spans[cid])
    for cid, r in recvs.items():
        if cid in ends:
            add_edge(ends[cid], r)
    # Flow chains: consecutive s -> t -> f order each chain's events.
    order = {"s": 0, "t": 1, "f": 2}
    chains: dict = {}
    for i, n in enumerate(nodes):
        if n.get("ph") in ("s", "t", "f"):
            chains.setdefault(n.get("id"), []).append(i)
    for idxs in chains.values():
        idxs.sort(key=lambda i: (
            nodes[i]["ts"], order[nodes[i]["ph"]], nodes[i]["_seq"]
        ))
        for a, b in zip(idxs, idxs[1:]):
            add_edge(a, b)

    # Kahn + vector clocks: vc[b] = max over preds, then tick own thread.
    from collections import deque

    T = len(tindex)
    vcs: list = [None] * len(nodes)
    counters = [0] * T  # per-thread event count = the clock tick
    ready = deque(sorted(
        (i for i in range(len(nodes)) if indeg[i] == 0),
        key=lambda i: (nodes[i]["ts"], nodes[i]["_seq"]),
    ))
    done = 0
    while ready:
        i = ready.popleft()
        t = tindex[(nodes[i]["pid"], nodes[i]["tid"])]
        # Incoming joins were folded into vcs[i] as predecessors finished;
        # ticking the own component makes this node's clock.
        vc = vcs[i] if vcs[i] is not None else [0] * T
        vcs[i] = vc
        counters[t] += 1
        vc[t] = max(vc[t], counters[t])
        for j in edges[i]:
            if vcs[j] is None:
                vcs[j] = [0] * T
            vcs[j] = [max(a, b) for a, b in zip(vcs[j], vc)]
            indeg[j] -= 1
            if indeg[j] == 0:
                ready.append(j)
        done += 1
    if done != len(nodes):
        return None  # cycle: corrupted artifact
    for i, n in enumerate(nodes):
        n["_thread"] = tindex[(n["pid"], n["tid"])]
        n["_vc"] = vcs[i]
    return nodes, vcs


def _happens_before(a: dict, b: dict) -> bool:
    return b["_vc"][a["_thread"]] >= a["_vc"][a["_thread"]]


def _strip_internal(n: dict) -> dict:
    return {k: v for k, v in n.items() if not k.startswith("_")}


def check_trace(events: list, journal: "list | None" = None) -> list[Violation]:
    """Happens-before race detection + flow-terminator conformance over a
    trace-event list (merged or per-process)."""
    v: list[Violation] = []
    built = _hb_vector_clocks(events)
    if built is None:
        # A cyclic happens-before graph is an UNUSABLE artifact, not a
        # race: reporting it under an invariant code would let a broken
        # trace masquerade as a detector finding. ValueError maps to the
        # CLI's exit 2 (same class as a torn report), and bench counts an
        # uncheckable leg as failed.
        raise ValueError(
            "trace happens-before graph contains a cycle — the artifact "
            "is corrupt; race analysis impossible"
        )
    nodes, _vcs = built

    # Writes to (phase, tid) journal/report state: the journal append and
    # the non-revoked flow terminator (report acceptance). A revoked
    # terminator mutates nothing; a worker's report SEND is a message.
    writes: dict = {}
    for n in nodes:
        args = n.get("args") or {}
        # Job-scoped (ISSUE 14): service events carry a ``job`` arg, and
        # two jobs' writes to their own task 0 are DISJOINT state — only
        # same-job (phase, tid) pairs can race. Single-job traces have no
        # job arg; every key shares None, exactly the old behavior.
        key = (args.get("job"), args.get("phase"), args.get("tid"))
        if key[1] is None or key[2] is None:
            continue
        if n.get("name") == "coordinator.journal" or (
            n.get("ph") == "f" and not args.get("revoked")
        ):
            writes.setdefault(key, []).append(n)
    for key, ws in sorted(writes.items(), key=str):
        for i in range(len(ws)):
            for j in range(i + 1, len(ws)):
                a, b = ws[i], ws[j]
                if not (_happens_before(a, b) or _happens_before(b, a)):
                    v.append(Violation(
                        "write-race",
                        f"{key[1]} {key[2]}"
                        + (f" [job {key[0]}]" if key[0] else "")
                        + ": two journal/report-state "
                        "writes with no happens-before path between them "
                        "(benign under today's idempotence guard, but a "
                        "real race)",
                        [_strip_internal(a), _strip_internal(b)],
                    ))

    # Dropped flow terminator: the journal-winning attempt's chain must
    # carry an "f". Non-winning chains may legally stay unterminated (a
    # crashed attempt looks exactly like that).
    if journal:
        chains: dict = {}
        starts: dict = {}
        for n in nodes:
            if n.get("ph") in ("s", "t", "f"):
                chains.setdefault(n.get("id"), set()).add(n["ph"])
                starts.setdefault(n.get("id"), _strip_internal(n))
        for ln in journal:
            if not ln.attempt:  # 0/None = unattributed (pre-annotation)
                continue
            # Service chains carry the job prefix (Coordinator._fid).
            fid = f"{ln.phase}:{ln.tid}:{ln.attempt}"
            if ln.job:
                fid = f"{ln.job}:{fid}"
            phs = chains.get(fid)
            # Only chains whose START ("s") is in THIS artifact owe a
            # terminator: the coordinator emits both s and f, so a start
            # without a finish is a dropped terminator — while a
            # worker-side per-process trace legally carries only the "t"
            # steps of chains it participated in.
            if phs and "s" in phs and "f" not in phs:
                v.append(Violation(
                    "missing-terminator",
                    f"{ln.phase} {ln.tid} attempt {ln.attempt} won the "
                    "journal but its flow chain was never terminated — "
                    "the finish report this line records never appears "
                    "in the timeline",
                    [ln.to_dict(), starts[fid]],
                ))
    return v


# ---------------------------------------------------------------------------
# Driver + CLI
# ---------------------------------------------------------------------------

def load_service_journal(path: str) -> list:
    """Rows of a JobService admission journal (JSONL). Torn tail and
    non-row lines are skipped — the service's own replay distrusts them
    the same way."""
    with open(path) as f:
        text = f.read()
    lines = text.splitlines()
    if text and not text.endswith("\n") and lines:
        lines.pop()  # torn tail
    rows = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict) and row.get("job") and row.get("op"):
            rows.append(row)
    return rows


def check_service_journal(rows: list) -> "list[Violation]":
    """Job-lifecycle state machine over a service admission journal
    (ISSUE 16): submit -> start -> done|cancel per job, in file order.

    Legal shapes the machine must NOT flag: a second ``start`` (service
    restart re-admits a requeued job), ``done`` without ``start`` (cache
    hit / joined twin settles a job straight from the queue), ``cancel``
    from the queue. Violations: any row for a job never submitted, a
    second terminal row, and any row after a terminal one.
    """
    violations: list[Violation] = []
    state: dict = {}  # jid -> "queued" | "running" | terminal op

    def _ev(row: dict) -> dict:
        return {"ev": "service-journal", "op": row.get("op"),
                "job": row.get("job"), "t": row.get("t")}

    first: dict = {}  # jid -> first row (for violation context)
    for row in rows:
        jid, op = row["job"], row["op"]
        st = state.get(jid)
        if st in ("done", "cancel"):
            violations.append(Violation(
                "job-lifecycle",
                f"job {jid}: '{op}' row after terminal '{st}' — a settled "
                "job's lifecycle is closed (no grants, no re-settling)",
                [first[jid], _ev(row)],
            ))
            continue
        if op == "submit":
            if st is not None:
                violations.append(Violation(
                    "job-lifecycle",
                    f"job {jid}: duplicate submit — job ids are single-"
                    "use",
                    [first[jid], _ev(row)],
                ))
                continue
            state[jid] = "queued"
            first[jid] = _ev(row)
        elif op in ("start", "done", "cancel"):
            if st is None:
                violations.append(Violation(
                    "job-lifecycle",
                    f"job {jid}: '{op}' without a prior submit — the "
                    "admission journal is the single source of job "
                    "existence",
                    [_ev(row)],
                ))
                continue
            state[jid] = "running" if op == "start" else op
            # first[] already set by submit
    return violations


def _service_journal_pass(target: str, checked: dict,
                          violations: list) -> None:
    """Run the job-lifecycle machine over ``<target>/service.journal``
    when present (service root, or a single-job dir checked alongside
    the service journal that admitted it). Appends Violation dicts and
    records the row count under ``checked['service_journal_lines']`` —
    a separate counter, so per-job ``journal_lines`` stays comparable
    across single-job and service runs."""
    spath = os.path.join(target, "service.journal")
    if not os.path.isfile(spath):
        return
    rows = load_service_journal(spath)
    checked["service_journal_lines"] = len(rows)
    checked["sources"]["service_journal"] = spath
    violations.extend(x.to_dict() for x in check_service_journal(rows))


def check_lineage(led: dict) -> list[Violation]:
    """Conservation pass over one parsed ledger (ISSUE 20): claims must
    be scanned, re-executions must agree. Takes analysis.lineage's
    load_ledger dict so mrcheck and the query CLI share one parser."""
    out: list[Violation] = []
    scanned: set = {c.get("dg") for c in led["chunks"] if c.get("dg")}
    for a in led["attempts"]:
        scanned.update(a.get("chunks") or [])
    for p in led["parts"]:
        ghost = sorted(set(p.get("chunks") or []) - scanned)
        if ghost:
            out.append(Violation(
                "lineage-conservation",
                f"partition {p.get('r')} claims {len(ghost)} chunk(s) no "
                "attempt or scan ever digested (fabricated provenance): "
                f"{ghost[:3]}{'…' if len(ghost) > 3 else ''}",
                [p],
            ))
    by_task: dict = {}
    for a in led["attempts"]:
        by_task.setdefault((a.get("phase"), a.get("tid")), []).append(a)
    for (phase, tid), atts in by_task.items():
        base = atts[0]
        for a in atts[1:]:
            if a.get("chunks") != base.get("chunks"):
                out.append(Violation(
                    "lineage-conservation",
                    f"{phase} {tid}: attempt {a.get('attempt')} scanned a "
                    f"different chunk list than attempt "
                    f"{base.get('attempt')} — re-execution diverged from "
                    "its predecessor (nondeterministic ingest or wrong "
                    "inputs)",
                    [base, a],
                ))
    return out


def _lineage_pass(target: str, checked: dict, violations: list) -> None:
    """Run the lineage-conservation invariant over ``<target>/
    lineage.jsonl`` when present (a --lineage run's work dir — driver or
    cluster). Appends Violation dicts; torn/partial ledgers check
    whatever records survived (the recorder's crash-durability contract
    means a SIGKILLed run's ledger is still a valid, shorter ledger)."""
    lpath = os.path.join(target, "lineage.jsonl")
    if not os.path.isfile(lpath):
        return
    from mapreduce_rust_tpu.analysis.lineage import LineageError, load_ledger

    try:
        led = load_ledger(lpath)
    except LineageError:
        return  # unreadable ledger — nothing checkable
    checked["lineage_records"] = (len(led["chunks"]) + len(led["attempts"])
                                  + len(led["parts"]))
    checked["sources"]["lineage"] = lpath
    violations.extend(x.to_dict() for x in check_lineage(led))


def _service_job_dirs(target: str) -> list:
    """job-* subdirs of a JobService work root that hold checkable
    artifacts (per-job journal or job report)."""
    import glob as _glob

    return sorted(
        d for d in _glob.glob(os.path.join(target, "job-*"))
        if os.path.isdir(d) and (
            os.path.exists(os.path.join(d, "coordinator.journal"))
            or os.path.exists(os.path.join(d, "job_report.json"))
        )
    )


def _violation_job(x: dict) -> "str | None":
    """Best-effort job attribution of a trace-pass violation: the job id
    its offending events carry (event-log rows and journal lines hold it
    top-level, trace events under args)."""
    for e in x.get("events") or []:
        if not isinstance(e, dict):
            continue
        job = e.get("job") or (e.get("args") or {}).get("job")
        if job:
            return str(job)
    return None


def run_check_service(target: str, job_dirs: list,
                      trace: "str | None" = None) -> dict:
    """Multi-job conformance (ISSUE 14): replay every job's artifacts
    under the SAME invariant catalog — each job dir is one machine set
    (its rows are job-stamped, so the cross-job invariant stays armed) —
    and aggregate into one document. A shared service trace is checked
    ONCE, against the union of every job's journal lines (flow ids and
    write keys are job-scoped, so chains never alias): per-job re-scans
    would report each trace violation N times and stamp it with every
    innocent job's id."""
    violations: list[dict] = []
    jobs: dict = {}
    checked: dict = {"events": 0, "journal_lines": 0, "jobs": len(job_dirs),
                     "sources": {"service_root": target}}
    all_journal: list = []
    for d in job_dirs:
        jid = os.path.basename(d)[len("job-"):]
        doc = run_check(d)
        jobs[jid] = {"ok": doc["ok"],
                     "violations": len(doc["violations"])}
        for x in doc["violations"]:
            violations.append({**x, "job": jid})
        checked["events"] += doc["checked"]["events"]
        checked["journal_lines"] += doc["checked"]["journal_lines"]
        jpath = os.path.join(d, "coordinator.journal")
        if os.path.exists(jpath):
            with open(jpath) as f:
                all_journal.extend(parse_journal(f.read()))
    if trace:
        with open(trace) as f:
            doc = json.load(f)
        trace_events = doc.get("traceEvents") if isinstance(doc, dict) \
            else doc
        if not isinstance(trace_events, list):
            raise ValueError(f"{trace}: no traceEvents list")
        for x in check_trace(trace_events, all_journal):
            row = x.to_dict()
            job = _violation_job(row)
            if job is not None:
                row["job"] = job
                if job in jobs:
                    jobs[job]["ok"] = False
                    jobs[job]["violations"] += 1
            violations.append(row)
        checked["trace_events"] = len(trace_events)
        checked["sources"]["trace"] = trace
    _service_journal_pass(target, checked, violations)
    return {
        "tool": "mrcheck",
        "schema": CHECK_SCHEMA,
        "kind": "service",
        "ok": not violations,
        "violations": violations,
        "invariants": sorted(INVARIANTS),
        "jobs": jobs,
        "checked": checked,
    }


def check_stream(events: list, journal: "list | None" = None,
                 report: "dict | None" = None,
                 events_dropped: "int | None" = None) -> list[Violation]:
    """Schedule-callable invariant pass over IN-MEMORY artifacts (ISSUE
    18): the ordered event rows, optionally the parsed journal lines and
    the report dict — no files, no tempfile round-trips, so mrmodel can
    validate every explored prefix per-step. run_check's authoritative
    file-backed pass routes through here; ``events_dropped`` defaults to
    the report's own counter."""
    violations = check_events(events or [])
    violations += check_journal(journal, report)
    if events_dropped is None:
        events_dropped = (report or {}).get("events_dropped") or 0
    if events_dropped:
        # The cap's contract is "counted, never silent" — and mrcheck
        # is the counter's one consumer. A truncated log means any
        # event-backed violation AFTER the cap is invisible, so an
        # exit-0 here would be the oracle silently not running.
        violations.append(Violation(
            "truncated-event-log",
            f"the event log dropped {events_dropped} row(s) at its cap — "
            "the event-backed invariants were replayed against an "
            "incomplete log (a violation past the cap is invisible)",
            [{"ev": "events_dropped", "count": events_dropped},
             events[-1] if events else {"ev": "empty-log"}],
        ))
    return violations


def run_check(target: str, trace: "str | None" = None,
              journal: "str | None" = None,
              job_report: "str | None" = None) -> dict:
    """Full conformance document for one run's artifacts. A JobService
    work root (job-* subdirs, no top-level coordinator.journal) fans out
    to every job's artifact set — see run_check_service."""
    if (os.path.isdir(target) and journal is None and job_report is None
            and not os.path.exists(
                os.path.join(target, "coordinator.journal"))):
        job_dirs = _service_job_dirs(target)
        if job_dirs:
            return run_check_service(target, job_dirs, trace=trace)
    art = load_artifacts(target, journal=journal, job_report=job_report)
    report = art["report"] or {}
    violations: list[Violation] = []
    events = report.get("events") or []
    dropped = report.get("events_dropped") or 0
    if art["authoritative"]:
        violations += check_stream(events, art["journal"], report,
                                   events_dropped=dropped)
    else:
        # Worker-side target: its local event log is not the protocol
        # authority (see load_artifacts) — replaying it would call a
        # dropped-RPC retry a double-win. The journal keeps its internal
        # invariant; the report-backed cross-checks stand down.
        violations += check_journal(art["journal"], None)
    trace_events = None
    if trace:
        with open(trace) as f:
            doc = json.load(f)
        trace_events = doc.get("traceEvents") if isinstance(doc, dict) else doc
        if not isinstance(trace_events, list):
            raise ValueError(f"{trace}: no traceEvents list")
        try:
            violations += check_trace(trace_events, art["journal"])
        except ValueError as e:
            raise ValueError(f"{trace}: {e}") from None
        art["sources"]["trace"] = trace
    vdicts = [x.to_dict() for x in violations]
    checked = {
        "events": len(events),
        "events_dropped": dropped,
        "authoritative": art["authoritative"],
        "journal_lines": len(art["journal"] or []),
        "trace_events": len(trace_events) if trace_events is not None
        else None,
        "sources": art["sources"],
    }
    if os.path.isdir(target):
        # A single-job work dir can carry the admission journal that
        # admitted it (mutation fixtures, copied service legs) — the
        # lifecycle machine runs wherever the artifact lands. Same for a
        # --lineage run's provenance ledger.
        _service_journal_pass(target, checked, vdicts)
        _lineage_pass(target, checked, vdicts)
    return {
        "tool": "mrcheck",
        "schema": CHECK_SCHEMA,
        "ok": not vdicts,
        "violations": vdicts,
        "invariants": sorted(INVARIANTS),
        "checked": checked,
    }


def run_cli(args) -> int:
    """``check`` subcommand body. Exit 0 = conformant, 1 = violations,
    2 = unusable target (a mistyped path must not pass as clean)."""
    try:
        doc = run_check(
            args.target,
            trace=getattr(args, "trace", None),
            journal=getattr(args, "journal", None),
            job_report=getattr(args, "job_report", None),
        )
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"mrcheck: {e}", file=sys.stderr)
        return 2
    if getattr(args, "format", "text") == "json":
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0 if doc["ok"] else 1
    c = doc["checked"]
    srcs = ", ".join(f"{k}={v}" for k, v in sorted(c["sources"].items()))
    print(f"mrcheck: {c['events']} event(s), {c['journal_lines']} journal "
          f"line(s)"
          + (f", {c['jobs']} job(s)" if c.get("jobs") is not None else "")
          + (f", {c['trace_events']} trace event(s)"
             if c.get("trace_events") is not None else "")
          + f" [{srcs}]")
    for x in doc["violations"]:
        print(Violation(x["code"], x["message"], x["events"]).format())
    print(f"mrcheck: {'ok' if doc['ok'] else 'FAILED'} "
          f"({len(doc['violations'])} violation(s), "
          f"{len(doc['invariants'])} invariants checked)")
    return 0 if doc["ok"] else 1


# ---------------------------------------------------------------------------
# Seeded-violation mutation harness
# ---------------------------------------------------------------------------

def _load_json(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _dump_json(path: str, doc: dict) -> None:
    with open(path, "w") as f:
        json.dump(doc, f)


def _report_doc(workdir: str) -> tuple[str, dict, dict]:
    """(path, document, report-dict-inside) of a work dir's job report."""
    path = os.path.join(workdir, "job_report.json")
    doc = _load_json(path)
    return path, doc, doc.get("report", doc)


def mutate_double_win(workdir: str) -> str:
    """Duplicate the last completion line of the journal — two winners."""
    path = os.path.join(workdir, "coordinator.journal")
    with open(path) as f:
        lines = f.read().splitlines()
    task_lines = [ln for ln in lines if ln.startswith(("map ", "reduce "))]
    dup = task_lines[-1].split()
    # The duplicate claims the NEXT attempt: the classic double-win is the
    # re-executed attempt's report also journaling.
    if len(dup) >= 3 and dup[2].startswith("a"):
        dup[2] = f"a{int(dup[2][1:] or 0) + 1}"
    with open(path, "a") as f:
        f.write(" ".join(dup) + "\n")
    return "double-win"


def mutate_report_after_revoke(workdir: str) -> str:
    """Insert a revocation of the winning attempt BEFORE its finish in
    the event log — the checker must refuse the finish."""
    path, doc, rep = _report_doc(workdir)
    events = rep.get("events") or []
    i, fin = next(
        (i, e) for i, e in enumerate(events) if e.get("ev") == "finish"
    )
    revoke = {"t": max(fin.get("t", 0.0) - 0.001, 0.0), "ev": "revoke",
              "phase": fin.get("phase"), "tid": fin.get("tid")}
    rep["events"] = events[:i] + [revoke] + events[i:]
    _dump_json(path, doc)
    return "report-after-revoke"


def mutate_grant_over_live_lease(workdir: str) -> str:
    """Insert a second, non-speculative grant of a tid while its first
    lease is live (between grant and finish)."""
    path, doc, rep = _report_doc(workdir)
    events = rep.get("events") or []
    i, g = next((i, e) for i, e in enumerate(events) if e.get("ev") == "grant")
    dup = dict(g)
    dup["attempt"] = (g.get("attempt") or 1) + 1
    dup["t"] = g.get("t", 0.0) + 0.001
    rep["events"] = events[:i + 1] + [dup] + events[i + 1:]
    _dump_json(path, doc)
    return "grant-over-live-lease"


def mutate_drop_terminator(workdir: str, trace_path: str) -> str:
    """Remove the flow terminator of a journal-winning attempt from the
    trace — the finish the journal records never lands in the timeline."""
    with open(os.path.join(workdir, "coordinator.journal")) as f:
        journal = parse_journal(f.read())
    winners = {
        f"{ln.phase}:{ln.tid}:{ln.attempt}" for ln in journal if ln.attempt
    }
    doc = _load_json(trace_path)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    victim = next(
        e for e in events if e.get("ph") == "f" and e.get("id") in winners
    )
    events.remove(victim)
    _dump_json(trace_path, doc)
    return "missing-terminator"


def mutate_write_race(workdir: str, trace_path: str) -> str:
    """Clone a journal-state write onto a foreign thread with no
    happens-before edges — the duplicate-write race the idempotence guard
    would silently absorb."""
    doc = _load_json(trace_path)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    src = next(
        e for e in events
        if e.get("name") == "coordinator.journal" and e.get("ph") == "i"
    )
    ghost = dict(src)
    ghost["pid"] = 999999  # a process the trace has no edges to
    ghost["tid"] = 1
    events.append(ghost)
    _dump_json(trace_path, doc)
    return "write-race"


def mutate_expire_without_lease(workdir: str) -> str:
    """Insert a lease expiry AFTER a task's finish — the leaked/forked
    lease signature (a finish settles the lease; nothing is left to
    expire)."""
    path, doc, rep = _report_doc(workdir)
    events = rep.get("events") or []
    i, fin = next(
        (i, e) for i, e in enumerate(events) if e.get("ev") == "finish"
    )
    exp = {"t": fin.get("t", 0.0) + 0.001, "ev": "expire",
           "phase": fin.get("phase"), "tid": fin.get("tid"),
           "attempt": fin.get("attempt")}
    rep["events"] = events[:i + 1] + [exp] + events[i + 1:]
    _dump_json(path, doc)
    return "expire-without-lease"


def mutate_finish_without_grant(workdir: str) -> str:
    """Insert a completion for a tid the log never granted — a report the
    coordinator should have had no lease to accept."""
    path, doc, rep = _report_doc(workdir)
    events = rep.get("events") or []
    i, fin = next(
        (i, e) for i, e in enumerate(events) if e.get("ev") == "finish"
    )
    ghost = dict(fin)
    ghost["tid"] = 999999  # never granted anywhere in the log
    rep["events"] = events[:i + 1] + [ghost] + events[i + 1:]
    _dump_json(path, doc)
    return "finish-without-grant"


def mutate_grant_after_deregister(workdir: str) -> str:
    """Deregister a worker BEFORE its grant in the event log — a drained
    worker handed a lease anyway."""
    path, doc, rep = _report_doc(workdir)
    events = rep.get("events") or []
    i, g = next(
        (i, e) for i, e in enumerate(events)
        if e.get("ev") == "grant" and e.get("wid") is not None
    )
    dereg = {"t": max(g.get("t", 0.0) - 0.001, 0.0), "ev": "deregister",
             "wid": g["wid"]}
    rep["events"] = events[:i] + [dereg] + events[i:]
    _dump_json(path, doc)
    return "grant-after-deregister"


def mutate_truncate_event_log(workdir: str) -> str:
    """Drop the event log's tail and count it in events_dropped — the
    EVENT_CAP overflow signature (telemetry.record_event drops rows past
    the cap and only counts them). A checker that trusts a truncated log
    calls an incomplete replay conformant."""
    path, doc, rep = _report_doc(workdir)
    events = rep.get("events") or []
    # The recorded run ends with the two deregisters: dropping exactly
    # those simulates the cap without tripping any OTHER invariant (the
    # cross-fire test depends on that).
    rep["events"] = events[:-2]
    rep["events_dropped"] = (rep.get("events_dropped") or 0) + 2
    _dump_json(path, doc)
    return "truncated-event-log"


def mutate_journal_without_finish(workdir: str) -> str:
    """Zero a journaled task's report count — the journal line now races
    a completion the report never saw (the journal-write-racing-task-state
    class)."""
    path, doc, rep = _report_doc(workdir)
    with open(os.path.join(workdir, "coordinator.journal")) as f:
        ln = parse_journal(f.read())[0]
    entry = rep["tasks"][ln.phase][str(ln.tid)]
    entry["reports"] = 0
    # The matching event-log rows must go too, or the corruption would be
    # (correctly) self-inconsistent rather than the targeted violation.
    rep["events"] = [
        e for e in rep.get("events") or []
        if not (e.get("ev") in ("finish", "late_finish")
                and (e.get("phase"), e.get("tid")) == (ln.phase, ln.tid))
    ]
    _dump_json(path, doc)
    return "journal-without-finish"


def mutate_grant_across_jobs(workdir: str) -> str:
    """Re-stamp a finish event's ``job`` field to a foreign job id — the
    cross-job misroute: the (phase, tid) lease was granted under one job
    and its completion lands under another (ISSUE 14). The grant keeps
    its own job (None on a single-job recording — still a mismatch: the
    machines are keyed by job, and a finish arriving under job 'j999'
    for a lease job None holds fires exactly this invariant)."""
    path, doc, rep = _report_doc(workdir)
    events = rep.get("events") or []
    i, fin = next(
        (i, e) for i, e in enumerate(events) if e.get("ev") == "finish"
    )
    fin["job"] = "j999"
    _dump_json(path, doc)
    return "grant-across-jobs"


def mutate_finish_without_journal(workdir: str) -> str:
    """Drop a completed task's journal line — a restart would re-run a
    task whose outputs already exist."""
    path = os.path.join(workdir, "coordinator.journal")
    with open(path) as f:
        lines = f.read().splitlines()
    victim = next(
        ln for ln in lines if ln.startswith(("map ", "reduce "))
    )
    lines.remove(victim)
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return "finish-without-journal"


def mutate_job_lifecycle(workdir: str) -> str:
    """Synthesize a corrupt service admission journal beside the run's
    artifacts: a 'start' row for a job never submitted, then a row after
    the job settles. The single-job fixture has no service.journal of its
    own — the lifecycle machine runs wherever the artifact lands, so a
    planted one exercises it end to end."""
    rows = [
        {"op": "start", "job": "ghost", "t": 0.5},       # never submitted
        {"op": "submit", "job": "j1", "t": 1.0},
        {"op": "start", "job": "j1", "t": 1.1},
        {"op": "done", "job": "j1", "t": 2.0, "state": "done"},
        {"op": "start", "job": "j1", "t": 2.5},          # after terminal
    ]
    with open(os.path.join(workdir, "service.journal"), "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return "job-lifecycle"


def mutate_early_reduce_grant(workdir: str) -> str:
    """Clone a reduce grant to BEFORE the first map finish — a reduce
    task handed out while its partition's map inputs were still being
    written (no part_ready can cover it at that position, and map finish
    reports are provably still landing after it). A matching expire
    follows the ghost so the recording's real grant of the same tid
    doesn't cross-fire grant-over-live-lease."""
    path, doc, rep = _report_doc(workdir)
    events = rep.get("events") or []
    i, first_map_fin = next(
        (i, e) for i, e in enumerate(events)
        if e.get("ev") == "finish" and e.get("phase") == "map"
    )
    g = next(e for e in events
             if e.get("ev") == "grant" and e.get("phase") == "reduce")
    t = max(first_map_fin.get("t", 0.0) - 0.002, 0.0)
    ghost = dict(g)
    ghost["t"] = t
    exp = {"t": t + 0.001, "ev": "expire", "phase": "reduce",
           "tid": g.get("tid"), "attempt": g.get("attempt")}
    if g.get("job") is not None:
        exp["job"] = g["job"]
    rep["events"] = events[:i] + [ghost, exp] + events[i:]
    _dump_json(path, doc)
    return "early-reduce-grant"


def mutate_lineage_conservation(workdir: str) -> str:
    """Corrupt (or synthesize) the work dir's provenance ledger so a
    partition claims a chunk digest nothing ever scanned — the
    fabricated-provenance half of the invariant. Runs on recordings made
    without --lineage too (the job-lifecycle synthesize precedent): the
    pass arms on the file's presence, not on how the run was configured."""
    path = os.path.join(workdir, "lineage.jsonl")
    ghost = "deadbeef" * 4  # 32 hex chars no scan could have produced
    rows = []
    if os.path.exists(path):
        with open(path) as f:
            rows = [line.rstrip("\n") for line in f if line.strip()]
    if not rows:
        rows = [
            json.dumps({"t": "start", "schema": 1,
                        "corpus_meta_digest": "0" * 16,
                        "corpus_bytes": 64, "reduce_n": 1,
                        "inputs": ["doc0.txt"], "pid": 0}),
            json.dumps({"t": "chunk", "seq": 0, "doc": 0, "bytes": 64,
                        "dg": "ab" * 16, "parts": [0]}),
        ]
    rows.append(json.dumps({"t": "part", "r": 0, "bytes": 64,
                            "chunks": [ghost]}))
    with open(path, "w") as f:
        f.write("\n".join(rows) + "\n")
    return "lineage-conservation"


#: name -> (needs_trace, mutator). The seeded-violation fixture table:
#: every entry corrupts a RECORDED run's artifacts so the named invariant
#: fires with the offending event pair — proving the checker detects it —
#: while the unmutated run proves zero false positives.
#: tests/test_mrcheck.py asserts this table covers EVERY invariant in the
#: catalog: an invariant without a known-bad fixture is an invariant
#: nobody has proven fires.
MUTATIONS: dict = {
    "double-win": (False, mutate_double_win),
    "report-after-revoke": (False, mutate_report_after_revoke),
    "grant-over-live-lease": (False, mutate_grant_over_live_lease),
    "expire-without-lease": (False, mutate_expire_without_lease),
    "finish-without-grant": (False, mutate_finish_without_grant),
    "grant-after-deregister": (False, mutate_grant_after_deregister),
    "truncated-event-log": (False, mutate_truncate_event_log),
    "journal-without-finish": (False, mutate_journal_without_finish),
    "finish-without-journal": (False, mutate_finish_without_journal),
    "missing-terminator": (True, mutate_drop_terminator),
    "write-race": (True, mutate_write_race),
    "grant-across-jobs": (False, mutate_grant_across_jobs),
    "job-lifecycle": (False, mutate_job_lifecycle),
    "early-reduce-grant": (False, mutate_early_reduce_grant),
    "lineage-conservation": (False, mutate_lineage_conservation),
}
