"""Opt-in thread-ownership sanitizer — the dynamic half of mrlint.

The static rules (rules.py) prove structure: a pool-submitted function
contains no stats write, an executor reaches its shutdown. What they can't
prove is aliasing — a callable that REACHES shared state through a closure
chain, a Dictionary handed to a thread that wasn't supposed to own it, a
scan arena crossing a fork. This module catches those at runtime:

- ``SanitizedJobStats``: every attribute write asserts the writing thread
  is registered (creator + explicitly registered writers, e.g. the ingest
  producer). A scan worker mutating stats — the PR 2 bug class — raises
  ``SanitizerError`` at the write site instead of corrupting counters.
- ``SanitizedDictionary``: mutating methods assert the owner thread — the
  fold-on-one-thread contract of the ingest/host-map engines, enforced.
- native arena check (native/host.py calls ``check_arena_owner``): per-
  thread scan scratch must never be observed by a different (pid, tid) —
  the fork/handoff hazard thread-locals can't express.

Enabled by ``Config.sanitize=True`` or ``MR_SANITIZE=1`` in the
environment; the factories below return plain instances when disabled, so
the hot path pays nothing. No jax import here (package rule).
"""

from __future__ import annotations

import os
import threading

from mapreduce_rust_tpu.runtime.dictionary import Dictionary
from mapreduce_rust_tpu.runtime.metrics import JobStats

_TRUTHY = ("1", "true", "on", "yes")


class SanitizerError(RuntimeError):
    """A thread-ownership invariant was violated (this is a bug in the
    calling code, not a recoverable condition — it fires at the exact
    write that would have raced)."""


def sanitize_enabled(cfg=None) -> bool:
    """True when the sanitizer is on for this process: ``MR_SANITIZE`` in
    the environment (so a whole test suite can opt in without touching
    configs) or ``Config.sanitize`` on the job."""
    if os.environ.get("MR_SANITIZE", "").strip().lower() in _TRUTHY:
        return True
    return bool(cfg is not None and getattr(cfg, "sanitize", False))


class SanitizedJobStats(JobStats):
    """JobStats whose attribute writes are gated on a registered-writer set.

    The creator thread is registered at construction; a legitimately
    concurrent writer (the ingest producer, which owns bytes_in/chunks/
    forced_cuts by design) announces itself with ``register_writer()`` —
    the base JobStats carries the same method as a no-op, so production
    code calls it unconditionally. Everything else that writes from an
    unregistered thread is exactly the orphaned-pool-thread race the
    PR 2 teardown fix buried, and raises here.

    Still a real dataclass instance: ``dataclasses.asdict`` (the manifest
    path) and ``stats.phase(...)`` work unchanged — ``_writers`` is not a
    dataclass field, so it never leaks into telemetry.
    """

    def __init__(self) -> None:
        object.__setattr__(self, "_writers", {threading.get_ident()})
        super().__init__()

    def register_writer(self) -> None:
        self._writers.add(threading.get_ident())

    def __setattr__(self, name, value):
        writers = getattr(self, "_writers", None)
        if writers is not None and threading.get_ident() not in writers:
            raise SanitizerError(
                f"JobStats.{name} written from thread "
                f"{threading.current_thread().name!r}, which never "
                "registered as a writer — stats are owned by the consumer "
                "thread; pool-submitted work must return values, not "
                "mutate shared state (mrlint rule: stats-ownership)"
            )
        object.__setattr__(self, name, value)


class SanitizedDictionary(Dictionary):
    """Dictionary whose mutating methods assert the owner thread.

    The ingest and host-map engines fold scan results into the dictionary
    on exactly one consumer thread (driver docstrings state it; this
    enforces it). ``set_owner()`` hands the instance to another thread
    explicitly — the only sanctioned transfer.
    """

    def __init__(self, *args, **kwargs) -> None:
        self._owner = threading.get_ident()
        super().__init__(*args, **kwargs)

    def set_owner(self, ident: int | None = None) -> None:
        self._owner = threading.get_ident() if ident is None else ident

    def _assert_owner(self, what: str) -> None:
        if threading.get_ident() != self._owner:
            raise SanitizerError(
                f"Dictionary.{what} called from thread "
                f"{threading.current_thread().name!r}, but the dictionary "
                "is owned by another thread — scan workers return results; "
                "only the consumer thread folds them (use set_owner() for "
                "an explicit handoff)"
            )

    def add_words(self, words):
        self._assert_owner("add_words")
        return super().add_words(words)

    def add_scanned(self, words, keys):
        self._assert_owner("add_scanned")
        return super().add_scanned(words, keys)

    def add_scanned_raw(self, raw, ends, keys):
        self._assert_owner("add_scanned_raw")
        return super().add_scanned_raw(raw, ends, keys)

    def add_text(self, normalized):
        self._assert_owner("add_text")
        return super().add_text(normalized)

    def merge(self, other):
        self._assert_owner("merge")
        return super().merge(other)


def new_job_stats(cfg=None) -> JobStats:
    """JobStats, sanitized when enabled — the driver/worker construction
    point (one factory so the enablement check lives in one place)."""
    return SanitizedJobStats() if sanitize_enabled(cfg) else JobStats()


def new_dictionary(cfg=None, **kwargs) -> Dictionary:
    """Dictionary, sanitized when enabled; kwargs pass through (budgets)."""
    cls = SanitizedDictionary if sanitize_enabled(cfg) else Dictionary
    return cls(**kwargs)


def check_shard_route(keys, n_shards: int, shard_index: int) -> None:
    """Called by the fold plane's shard threads when sanitizing: every key
    handed to fold shard ``shard_index`` must actually route there
    (``shard_of_packed(packed, S) == shard_index``). The per-shard
    dictionary's owner-thread assert catches a fold from the WRONG THREAD;
    this catches the complementary bug — a router that sends a key to the
    wrong shard's queue, where the right thread would fold it into the
    wrong shard and silently split that key's dedup/collision state across
    two dictionaries. Vectorized (one numpy pass per routed slice), and
    only ever called under the sanitizer."""
    import numpy as np

    from mapreduce_rust_tpu.runtime.dictionary import shard_ids_of_packed

    if len(keys) == 0:
        return
    keys = np.asarray(keys)
    packed = (
        keys[:, 0].astype(np.uint64) << np.uint64(32)
    ) | keys[:, 1].astype(np.uint64)
    routed = shard_ids_of_packed(packed, n_shards)
    wrong = routed != np.uint64(shard_index)
    if wrong.any():
        i = int(np.nonzero(wrong)[0][0])
        raise SanitizerError(
            f"fold shard {shard_index} received key "
            f"({int(keys[i, 0])}, {int(keys[i, 1])}) which routes to shard "
            f"{int(routed[i])} of {n_shards} — the "
            "router mis-partitioned a scan result; that key's dedup and "
            "collision state would silently split across two shard "
            "dictionaries"
        )


def check_arena_owner(owner_pid: int, owner_tid: int) -> None:
    """Called by native/host._buffers on arena reuse when sanitizing: a
    scratch arena observed under a different (pid, tid) than the one that
    allocated it means thread-local state crossed a fork or a handoff —
    its contents are another context's scan results."""
    if (os.getpid(), threading.get_ident()) != (owner_pid, owner_tid):
        raise SanitizerError(
            f"native scan arena allocated by (pid={owner_pid}, "
            f"tid={owner_tid}) observed from (pid={os.getpid()}, "
            f"tid={threading.get_ident()}) — arenas are per-thread scratch "
            "and must never cross a fork or thread handoff"
        )
