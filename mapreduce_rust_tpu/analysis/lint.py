"""mrlint framework: file discovery, rule driver, suppressions, output.

Design constraints:

- **Backend-free and fast.** Pure ``ast`` + stdlib; linting the whole repo
  is tens of milliseconds, so it can gate tier-1 (tests/test_lint_clean.py)
  without moving the suite's runtime.
- **Zero findings is the contract.** The shipped tree lints clean with an
  EMPTY baseline; anything that must stay gets an inline
  ``# mrlint: ignore[rule] -- reason`` (the reason is mandatory — a bare
  ignore is itself a finding) or a ``.mrlint.json`` baseline entry with a
  ``reason`` field. Suppression without a recorded why is how the PR-2
  class of bug got re-shipped; the format forbids it.
- **Machine-readable.** ``--format json`` emits one stable document
  (findings + suppression accounting) so CI can diff runs; the baseline
  file is itself JSON with the same vocabulary.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
import sys
import tokenize
from typing import Iterable, Iterator, Sequence


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str           # repo-relative posix path
    line: int           # 1-based
    col: int            # 0-based (ast convention)
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class LintReport:
    findings: list[Finding]
    suppressed: int = 0              # inline-ignored findings
    baselined: int = 0               # baseline-suppressed findings
    files_checked: int = 0
    unused_baseline: list[dict] = dataclasses.field(default_factory=list)
    parse_errors: list[Finding] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors

    def to_dict(self) -> dict:
        from mapreduce_rust_tpu.analysis.rules import ALL_RULES, PROGRAM_RULES

        return {
            "tool": "mrlint",
            "schema": 1,
            "ok": self.ok,
            "files_checked": self.files_checked,
            "rules": sorted(r.name for r in [*ALL_RULES, *PROGRAM_RULES]),
            "findings": [f.to_dict() for f in self.findings + self.parse_errors],
            "suppressed_inline": self.suppressed,
            "suppressed_baseline": self.baselined,
            "unused_baseline_entries": self.unused_baseline,
        }


# ---------------------------------------------------------------------------
# AST helpers shared by the rules
# ---------------------------------------------------------------------------

def attach_parents(tree: ast.AST) -> None:
    """Annotate every node with ``.mr_parent`` so rules can walk upward
    (enclosing with/try/loop/function) without re-deriving the spine."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.mr_parent = node  # type: ignore[attr-defined]


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "mr_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "mr_parent", None)


def qualname(node: ast.AST) -> str:
    """Dotted source name of a Name/Attribute chain ('' for anything else):
    ``jax.jit`` → "jax.jit", ``self.pool.submit`` → "self.pool.submit"."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def last_segment(name: str) -> str:
    """Last dotted segment of a qualname (``"a.b.c"`` → ``"c"``) — the
    ONE suffix-matching helper rules and the dataflow call graph share,
    so their notion of "same callable name" can never drift."""
    return name.rsplit(".", 1)[-1]


def enclosing_function(node: ast.AST) -> "ast.FunctionDef | ast.AsyncFunctionDef | None":
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def enclosing_class(node: ast.AST) -> "ast.ClassDef | None":
    for anc in ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
    return None


# ---------------------------------------------------------------------------
# Inline suppressions
# ---------------------------------------------------------------------------

# `# mrlint: ignore[rule-a, rule-b] -- reason` (the `--` is optional but the
# reason text is not: an unreasoned ignore does not suppress and is reported).
_IGNORE_RE = re.compile(
    r"#\s*mrlint:\s*ignore\[([A-Za-z0-9_\-, ]+)\]\s*(?:--\s*)?(.*)"
)


def _inline_ignores(src: str, path: str) -> tuple[dict[int, set[str]], list[Finding]]:
    """line → suppressed rule names, plus findings for unreasoned ignores.

    Comments are read with ``tokenize`` (not a line regex) so string
    literals containing the marker don't suppress anything.
    """
    ignores: dict[int, set[str]] = {}
    bad: list[Finding] = []
    try:
        tokens = tokenize.generate_tokens(iter(src.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _IGNORE_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            reason = m.group(2).strip()
            if not reason:
                bad.append(Finding(
                    "bad-suppression", path, tok.start[0], tok.start[1],
                    "inline ignore without a reason — write "
                    "'# mrlint: ignore[rule] -- why it is safe'",
                ))
                continue
            ignores.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        pass  # the parse error is reported by the main loop
    return ignores, bad


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> list[dict]:
    """``.mrlint.json``: {"suppressions": [{"rule", "path", "reason"}]}.
    Every entry needs all three fields — a reasonless or pathless entry is
    a config error, raised loudly (CI must not silently suppress)."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(
            f"{path}: baseline must be an object with a 'suppressions' list"
        )
    entries = data.get("suppressions", [])
    if not isinstance(entries, list):
        raise ValueError(f"{path}: 'suppressions' must be a list")
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not all(
            isinstance(e.get(k), str) and e.get(k) for k in ("rule", "path", "reason")
        ):
            raise ValueError(
                f"{path}: suppression #{i} needs non-empty string fields "
                f"'rule', 'path' and 'reason' (got {e!r})"
            )
    return entries


def _baseline_match(entry: dict, finding: Finding) -> bool:
    return (
        (entry["rule"] == "*" or entry["rule"] == finding.rule)
        and fnmatch.fnmatch(finding.path, entry["path"])
    )


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", ".bench", "node_modules"}


def discover_files(paths: Sequence[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                out.extend(
                    os.path.join(root, f) for f in sorted(files) if f.endswith(".py")
                )
        elif p.endswith(".py") and os.path.exists(p):
            out.append(p)
    # De-dup while keeping order (a file reachable via two roots).
    seen: set[str] = set()
    uniq = []
    for p in out:
        rp = os.path.realpath(p)
        if rp not in seen:
            seen.add(rp)
            uniq.append(p)
    return uniq


def default_roots() -> list[str]:
    """What ``lint`` checks with no path arguments: the package itself plus
    the repo-root siblings that ship with it (tests, bench, graft entry) —
    derived from the package location, not the CWD, so the gate test checks
    the same tree no matter where pytest runs."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(pkg)
    roots = [pkg]
    for sib in ("tests", "bench.py", "__graft_entry__.py"):
        p = os.path.join(repo, sib)
        if os.path.exists(p):
            roots.append(p)
    return roots


def _rel(path: str) -> str:
    """Repo-relative posix path (stable across machines for baselines)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo = os.path.dirname(pkg)
    try:
        rel = os.path.relpath(os.path.abspath(path), repo)
    except ValueError:  # different drive (windows) — keep as-is
        rel = path
    if rel.startswith(".."):
        rel = path
    return rel.replace(os.sep, "/")


@dataclasses.dataclass
class ParsedFile:
    """One linted file, parsed exactly once: the per-file rules, the
    program rules (via dataflow.Program) and the suppression pass all
    consume this instead of re-reading the source."""

    path: str
    rel: str
    tree: ast.Module
    src: str
    ignores: dict[int, set[str]]


def parse_file(path: str) -> tuple["ParsedFile | None", list[Finding]]:
    """(parsed file, parse/suppression errors). None on a parse failure —
    the error Finding is the record of it."""
    rel = _rel(path)
    try:
        with open(path, "rb") as f:
            src = f.read().decode("utf-8", errors="replace")
        tree = ast.parse(src, filename=path)
    except (OSError, SyntaxError) as e:
        return None, [Finding("parse-error", rel, getattr(e, "lineno", 1) or 1,
                              0, f"cannot lint: {e}")]
    attach_parents(tree)
    ignores, bad_ignores = _inline_ignores(src, rel)
    return ParsedFile(path, rel, tree, src, ignores), bad_ignores


def _suppress(findings: Iterable[Finding],
              ignores: dict[int, set[str]]) -> tuple[list[Finding], int]:
    kept: list[Finding] = []
    suppressed = 0
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        # An ignore suppresses on its own line or the line directly below
        # (comment-above style) — never file-wide.
        cov = ignores.get(f.line, set()) | ignores.get(f.line - 1, set())
        if f.rule in cov or "*" in cov:
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed


def lint_file(path: str, rules: Iterable | None = None) -> tuple[list[Finding], list[Finding], int]:
    """(findings, parse/suppression errors, inline-suppressed count).

    Per-file rules only: the interprocedural program rules need the whole
    file set and run from :func:`lint_paths`."""
    from mapreduce_rust_tpu.analysis.rules import ALL_RULES

    rules = list(rules) if rules is not None else ALL_RULES
    pf, errors = parse_file(path)
    if pf is None:
        return [], errors, 0
    findings: list[Finding] = []
    for rule in rules:
        findings.extend(rule.check(pf.tree, pf.src, pf.rel))
    kept, suppressed = _suppress(findings, pf.ignores)
    return kept, errors, suppressed


def lint_paths(paths: Sequence[str] | None = None,
               baseline: list[dict] | None = None) -> LintReport:
    from mapreduce_rust_tpu.analysis.rules import ALL_RULES, PROGRAM_RULES

    files = discover_files(list(paths) if paths else default_roots())
    report = LintReport(findings=[], files_checked=len(files))
    parsed: list[ParsedFile] = []
    raw: dict[str, list[Finding]] = {}
    for path in files:
        pf, errors = parse_file(path)
        report.parse_errors.extend(errors)
        if pf is None:
            continue
        parsed.append(pf)
        fs = raw.setdefault(pf.rel, [])
        for rule in ALL_RULES:
            fs.extend(rule.check(pf.tree, pf.src, pf.rel))
    if PROGRAM_RULES and parsed:
        # The interprocedural pass: one Program over every parsed file, so
        # the call graph sees helper frames in other modules. Program
        # findings land on their file and obey the SAME inline ignores and
        # baseline as per-file findings.
        from mapreduce_rust_tpu.analysis.dataflow import Program

        program = Program([(pf.rel, pf.tree) for pf in parsed])
        for rule in PROGRAM_RULES:
            for f in rule.run_program(program):
                raw.setdefault(f.path, []).append(f)
    ignores_by_rel = {pf.rel: pf.ignores for pf in parsed}
    used = [0] * len(baseline or [])
    for rel in sorted(raw):
        kept, suppressed = _suppress(raw[rel], ignores_by_rel.get(rel, {}))
        report.suppressed += suppressed
        for f in kept:
            hit = None
            for i, entry in enumerate(baseline or []):
                if _baseline_match(entry, f):
                    hit = i
                    break
            if hit is None:
                report.findings.append(f)
            else:
                used[hit] += 1
                report.baselined += 1
    report.unused_baseline = [
        e for i, e in enumerate(baseline or []) if not used[i]
    ]
    return report


# ---------------------------------------------------------------------------
# CLI (dispatched from mapreduce_rust_tpu.__main__)
# ---------------------------------------------------------------------------

def run_cli(args) -> int:
    """The ``lint`` subcommand body. Exit 0 = clean (suppressions counted,
    not failing); 1 = findings; 2 = config error (bad baseline)."""
    if getattr(args, "check_trace", None):
        return _check_trace(args.check_trace)

    baseline_path = getattr(args, "baseline", None)
    if baseline_path is None and os.path.exists(".mrlint.json"):
        baseline_path = ".mrlint.json"
    try:
        baseline = load_baseline(baseline_path) if baseline_path else None
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"mrlint: bad baseline: {e}", file=sys.stderr)
        return 2

    paths = getattr(args, "paths", None) or None
    if paths and not discover_files(list(paths)):
        # Explicit targets resolving to nothing is a config error, not a
        # clean tree — a mistyped CI path must not pass as "0 findings".
        print(
            f"mrlint: no .py files under {list(paths)!r} — nothing checked",
            file=sys.stderr,
        )
        return 2

    report = lint_paths(paths, baseline)
    # Resolved BEFORE the document prints: under --strict-baseline a
    # stale entry IS the failure, and the JSON "ok" field must agree with
    # the exit code (a CI pipeline gating on the archived document would
    # otherwise record a pass for a failed invocation).
    strict_stale = bool(
        getattr(args, "strict_baseline", False) and report.unused_baseline
    )

    if getattr(args, "format", "text") == "json":
        doc = report.to_dict()
        if strict_stale:
            doc["ok"] = False
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        for f in report.findings + report.parse_errors:
            print(f.format())
        for e in report.unused_baseline:
            print(
                f"mrlint: warning: unused baseline entry "
                f"{e['rule']} @ {e['path']} ({e['reason']})",
                file=sys.stderr,
            )
        n = len(report.findings) + len(report.parse_errors)
        print(
            f"mrlint: {report.files_checked} files, {n} finding(s), "
            f"{report.suppressed} inline-suppressed, "
            f"{report.baselined} baselined"
        )
    if strict_stale:
        # Stale suppressions are debt with interest: an entry nothing
        # matches today will happily swallow a REAL finding at that path
        # tomorrow. --strict-baseline turns the warning into the failure
        # it deserves so CI prunes them at the source.
        print(
            f"mrlint: --strict-baseline: {len(report.unused_baseline)} "
            "unused baseline entr(y/ies) — remove them from the baseline "
            "file",
            file=sys.stderr,
        )
        return 1
    return 0 if report.ok else 1


def _check_trace(path: str) -> int:
    """--check-trace: run the trace validator on a written trace file, so
    trace artifacts are checkable the same way source is (ISSUE 3
    satellite — validate_events rejects unbalanced B/E pairs and
    non-numeric counter samples)."""
    from mapreduce_rust_tpu.runtime.trace import validate_events

    try:
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        validate_events(events)
    except (OSError, KeyError, ValueError, json.JSONDecodeError) as e:
        print(f"mrlint: {path}: INVALID trace — {e}", file=sys.stderr)
        return 1
    print(f"mrlint: {path}: valid trace ({len(events)} events)")
    return 0
