"""``doctor``: automated run diagnosis over the telemetry the framework
already emits — the interpretation layer PRs 1/2/4 never had.

A run manifest (and optionally its job report and trace) goes in; a ranked
diagnosis comes out:

- **Bottleneck attribution** — the same scan/stall/glue/device split the
  manifest's ``bottleneck`` field encodes, extended with the two
  components JobStats.bottleneck cannot see: interconnect time
  (``all_to_all_s``) and XLA compile time. The primary name is computed
  with JobStats' exact formula, so doctor and manifest always agree on the
  legacy four; compile/ICI dominance is reported as a finding on top.
- **Percentiles** — the histogram blocks (host-map windows, a2a rounds,
  drains, RPC latencies, task attempts) rendered as p50/p95/p99/max.
- **Skew** — reduce-partition output bytes and mesh shard fill counts
  scored as max/mean; reduce-task duration imbalance from the job report.
- **Stragglers** — per-worker attempt-duration histograms (the ``wid``
  attribution satellite): a worker whose p50 exceeds the fleet median by
  ``straggler_factor`` is flagged.
- **Lease tuning** — observed task p99 vs the configured lease timeout.
- **Crash forensics** — incomplete attempt chains (granted, never
  finished) from the job report and from unterminated trace flow chains;
  a crashed run's partial telemetry yields a diagnosis, never a stack
  trace.
- **Regression gate** — ``--baseline`` compares watched metrics against a
  prior run's manifest with per-metric thresholds and exits non-zero, so
  CI can gate on it. The same watched table backs ``stats <a> <b>``'s
  new exit code.

Pure stdlib, no jax (package rule: analysis tools run in any process in
milliseconds).
"""

from __future__ import annotations

import json

from mapreduce_rust_tpu.runtime.histogram import Histogram
# One flattener for both consumers of manifest paths: diff_manifests
# (stats CLI) and the regression gate here must agree on metric naming.
from mapreduce_rust_tpu.runtime.telemetry import _flatten

DOCTOR_SCHEMA = 1

_SEV_RANK = {"error": 0, "warn": 1, "info": 2}

#: The regression gate's watched metrics: flattened manifest path →
#: (direction, relative threshold). "up" = an increase beyond the
#: threshold is a regression, "down" = a decrease is. Thresholds are
#: deliberately loose (these gate CI on real, noisy timings); scale them
#: with --threshold-scale.
WATCHED_METRICS: dict = {
    "stats.gb_per_s": ("down", 0.10),
    "stats.wall_seconds": ("up", 0.25),
    "stats.ingest_wait_s": ("up", 0.50),
    "stats.device_wait_s": ("up", 0.50),
    "stats.host_glue_s": ("up", 0.50),
    "stats.fold_stall_s": ("up", 0.50),
    "stats.spill_stall_s": ("up", 0.50),
    "stats.dispatch_stall_s": ("up", 0.50),
    "stats.scan_wait_s": ("up", 0.50),
    "stats.all_to_all_s": ("up", 0.50),
    "stats.compile.total_s": ("up", 1.00),
    "stats.partial_overflow_replays": ("up", 0.00),
    "stats.bucket_skew_replays": ("up", 0.00),
    "stats.spilled_keys": ("up", 1.00),
    "stats.histograms.host_map.scan_s.p95": ("up", 0.50),
    "stats.histograms.host_map.glue_s.p95": ("up", 0.50),
    "stats.histograms.host_map.fold_s.p95": ("up", 0.50),
    "stats.histograms.spill.write_s.p95": ("up", 0.50),
    "stats.histograms.dispatch.submit_s.p95": ("up", 0.50),
    "stats.histograms.a2a.round_s.p95": ("up", 0.50),
    "stats.histograms.device.drain_s.p95": ("up", 0.50),
}


def compare_manifests(baseline: dict, current: dict,
                      threshold_scale: float = 1.0) -> list[dict]:
    """Watched-metric regressions of ``current`` vs ``baseline`` — the
    ``--baseline`` CI gate's engine, shared with ``stats <a> <b>``.
    Returns one entry per tripped metric; [] = no regression. A metric
    absent from either side is skipped (older manifests predate the
    histogram fields); zero baselines gate on any increase for count
    metrics (threshold 0) and are skipped for ratio metrics."""
    fb, fc = _flatten(baseline), _flatten(current)
    regressions: list[dict] = []
    for metric, (direction, rel) in sorted(WATCHED_METRICS.items()):
        b, c = fb.get(metric), fc.get(metric)
        if not isinstance(b, (int, float)) or not isinstance(c, (int, float)) \
                or isinstance(b, bool) or isinstance(c, bool):
            continue
        threshold = rel * threshold_scale
        if b == 0:
            # No baseline signal to scale by: only the exact count metrics
            # (threshold 0: "any increase regresses") stay armed.
            if threshold == 0 and c > b:
                delta = float("inf")
            else:
                continue
        else:
            change = (c - b) / abs(b)
            worse = change > threshold if direction == "up" \
                else change < -threshold
            if not worse:
                continue
            delta = change
        regressions.append({
            "metric": metric,
            "baseline": b,
            "current": c,
            "change": None if delta == float("inf") else round(delta, 4),
            "direction": direction,
            "threshold": threshold,
        })
    return regressions


# ---------------------------------------------------------------------------
# Diagnosis
# ---------------------------------------------------------------------------

def _hist(d: "dict | None") -> "Histogram | None":
    if not d or not d.get("count"):
        return None
    return Histogram.from_dict(d)


def _skew_score(values: "list | None") -> "dict | None":
    vals = [v for v in (values or []) if isinstance(v, (int, float))]
    if len(vals) < 2 or sum(vals) <= 0:
        return None
    mean = sum(vals) / len(vals)
    return {
        "n": len(vals),
        "max": max(vals),
        "mean": round(mean, 3),
        # 1.0 = perfectly balanced; 2.0 = the hottest slot carries twice
        # its fair share.
        "score": round(max(vals) / mean, 3) if mean else None,
    }


def _bottleneck_attribution(stats: dict) -> dict:
    """JobStats.bottleneck's exact formula over the manifest's stats dict,
    extended with the ICI and compile components it cannot express."""
    workers = stats.get("host_map_workers") or 0
    scan = stats.get("host_map_s", 0.0) if workers <= 1 \
        else stats.get("scan_wait_s", 0.0)
    legacy = {
        "host-ingest": stats.get("ingest_wait_s", 0.0) or 0.0,
        "device": stats.get("device_wait_s", 0.0) or 0.0,
        "host-map": scan or 0.0,
        "host-glue": stats.get("host_glue_s", 0.0) or 0.0,
    }
    # Sharded fold (ISSUE 9): with S > 1 fold threads own the dictionary
    # fold, so "the fold is the ceiling" reads as router backpressure
    # (fold_stall_s), exactly mirroring JobStats.bottleneck. Live
    # fleet-aggregated stats carry no fold_shards field — there the mere
    # presence of fold stall arms the component.
    if (stats.get("fold_shards") or 0) > 1 or (
        "fold_shards" not in stats and (stats.get("fold_stall_s") or 0) > 0
    ):
        legacy["host-fold"] = stats.get("fold_stall_s", 0.0) or 0.0
    # Async spill plane (ISSUE 11): writes run off the hot threads, so the
    # disk component reads as owner-side writer backpressure — mirrors
    # JobStats.bottleneck's arm exactly. Live fleet aggregates carry the
    # fields only when a worker actually spilled, which is the same
    # engagement test.
    if (stats.get("spill_s") or 0) > 0 or (stats.get("spill_stall_s") or 0) > 0:
        legacy["spill"] = stats.get("spill_stall_s", 0.0) or 0.0
    # Async dispatch plane (ISSUE 13): the device hop runs off the router,
    # so "the dispatch is the ceiling" reads as router backpressure —
    # mirrors JobStats.bottleneck's arm exactly. Sync mode keeps the hop
    # in glue (the PR 10 attribution), so the arm stays off there. Live
    # fleet aggregates carry no dispatch_mode — the mere presence of
    # dispatch stall arms the component, the fold/spill pattern.
    mode = stats.get("dispatch_mode")
    if (isinstance(mode, str) and mode.startswith("async")) or (
        mode is None and (stats.get("dispatch_stall_s") or 0) > 0
    ):
        legacy["merge-dispatch"] = stats.get("dispatch_stall_s", 0.0) or 0.0
    name, val = max(legacy.items(), key=lambda kv: kv[1])
    primary = name if val > 0 else "balanced"
    extended = dict(legacy)
    extended["ici"] = stats.get("all_to_all_s", 0.0) or 0.0
    extended["compile"] = (stats.get("compile") or {}).get("total_s", 0.0)
    total = sum(extended.values())
    ranked = [
        {
            "component": comp,
            "seconds": round(secs, 6),
            "share": round(secs / total, 4) if total else None,
        }
        for comp, secs in sorted(
            extended.items(), key=lambda kv: kv[1], reverse=True
        )
    ]
    return {
        "name": primary,
        "recorded": stats.get("bottleneck"),
        "agrees_with_stats": (
            stats.get("bottleneck") is None or primary == stats.get("bottleneck")
        ),
        "attribution": ranked,
    }


def _flow_chains(events: list) -> dict:
    chains: dict = {}
    for e in events:
        if e.get("ph") in ("s", "t", "f"):
            chains.setdefault(e.get("id"), set()).add(e["ph"])
    return chains


def _load_trace_events(path: str) -> list:
    from mapreduce_rust_tpu.runtime.trace import load_trace

    events, _md = load_trace(path)
    return events


def diagnose(manifest: dict, job_report: "dict | None" = None,
             trace_events: "list | None" = None,
             straggler_factor: float = 2.0) -> dict:
    """The diagnosis pass. ``manifest`` is a loaded run/coordinator/bench
    manifest (stats optional — a control-plane manifest diagnoses from its
    embedded job report); ``job_report`` overrides/augments the manifest's
    embedded report; ``trace_events`` enables flow-chain forensics.
    Total-function by design: partial telemetry from a crashed run yields
    a partial diagnosis plus findings, never an exception."""
    findings: list[dict] = []

    def find(severity: str, code: str, message: str,
             key: "str | None" = None) -> None:
        """``key`` is the finding's stable identity across re-evaluations
        (defaults to the code): the streaming doctor dedups on it, so a
        straggler's p50 drifting between ticks updates ONE finding with
        one first-seen timestamp instead of minting a new row per tick."""
        f = {"severity": severity, "code": code, "message": message}
        if key is not None:
            f["key"] = key
        findings.append(f)

    stats = manifest.get("stats") or {}
    report = job_report if job_report is not None \
        else manifest.get("job_report") or manifest.get("report")
    diag: dict = {"schema": DOCTOR_SCHEMA, "kind": manifest.get("kind")}

    if manifest.get("error"):
        find("error", "run-error",
             f"run recorded an error: {manifest['error']} — diagnosis is of "
             "the partial telemetry a crashed run left behind")

    # ---- bottleneck ----
    if stats:
        bn = _bottleneck_attribution(stats)
        diag["bottleneck"] = bn
        if not bn["agrees_with_stats"]:
            find("warn", "bottleneck-mismatch",
                 f"doctor attributes the run to {bn['name']!r} but the "
                 f"manifest recorded {bn['recorded']!r} — the manifest was "
                 "written by a different stats formula; trust the raw parts")
        top = bn["attribution"][0] if bn["attribution"] else None
        if top and top["component"] in ("ici", "compile") and top["seconds"] > 0:
            find("warn", f"{top['component']}-bound",
                 f"{top['component']} time ({top['seconds']:.3f}s) exceeds "
                 f"every host/device wait component — the legacy bottleneck "
                 f"field ({bn['name']!r}) cannot express this; "
                 + ("a persistent compilation cache or longer run amortizes it"
                    if top["component"] == "compile"
                    else "fewer/fatter all_to_all rounds would"))
        if bn["name"] == "spill":
            sp = stats.get("spill_split") or {}
            find("warn", "spill-bound",
                 f"spill-writer backpressure ({stats.get('spill_stall_s', 0):.3f}s "
                 "blocked on full writer queues) exceeds every other wait "
                 "component — the disk tier is the ceiling: raise "
                 "dictionary_budget_words / host_accum_budget_mb (fewer, "
                 "larger runs), add fold_shards (one spill writer per "
                 "shard), or move work_dir to faster storage"
                 + (f" [{sp.get('bytes', 0) / 1e6:.0f} MB over "
                    f"{sp.get('dict_runs', 0)}+{sp.get('accum_runs', 0)} "
                    "runs]" if sp else ""))
        if bn["name"] == "merge-dispatch":
            dp = stats.get("dispatch_split") or {}
            find("warn", "merge-dispatch-bound",
                 f"dispatch backpressure ({stats.get('dispatch_stall_s', 0):.3f}s "
                 "blocked on the full dispatch queue) exceeds every other "
                 "wait component — the per-merge device hop is the "
                 "ceiling: raise dispatch_fill_frac (more cross-window "
                 "coalescing per dispatch), raise host_update_cap (fewer, "
                 "fatter merges), or check the device link"
                 + (f" [{dp.get('dispatches', 0)} dispatches at mean fill "
                    f"{dp.get('fill_frac', 0):.2f}]" if dp else ""))
        dp = stats.get("dispatch_split") or {}
        if (
            dp.get("dispatches", 0) >= 8
            and (dp.get("fill_frac") or 0) < 0.10
            and (dp.get("dispatch_s") or 0) > 0.2
        ):
            # Raise-cap-vs-threshold guidance (ISSUE 13): mostly-empty
            # fixed-shape updates mean the 1+3·cap transfer is sentinel
            # padding and the per-dispatch fixed cost dominates.
            find("info", "dispatch-low-fill",
                 f"merge dispatches ran {dp.get('fill_frac', 0):.0%} full "
                 f"on average over {dp.get('dispatches')} dispatches — the "
                 "fixed-shape update is mostly sentinel padding: raise "
                 "dispatch_fill_frac (coalesce more windows per dispatch) "
                 "if latency allows, or lower host_update_cap so the "
                 "compiled merge shape matches the real update size "
                 "(one-time recompile, smaller transfers thereafter)")
        wall = stats.get("wall_seconds") or 0.0
        comp = stats.get("compile") or {}
        if comp and wall and comp.get("total_s", 0.0) > 0.5 * wall:
            find("warn", "compile-dominates",
                 f"XLA compiles took {comp['total_s']:.2f}s of a "
                 f"{wall:.2f}s run ({comp.get('cache_hits', 0)} cache hits, "
                 f"{comp.get('cache_misses', 0)} misses) — warm the "
                 "persistent cache or measure a longer run")

        # ---- roofline attribution (ISSUE 19) ----
        # "The scan is slow" gets a headroom number: achieved GB/s vs the
        # calibrated machine roof for the host-map scan. Uses the cached
        # .bench/machine.json when one exists; otherwise a quick in-memory
        # memcpy probe (no file written — the doctor is read-only).
        if stats.get("host_map_split") and stats.get("bytes_in"):
            try:
                from mapreduce_rust_tpu.analysis import roofline as _roofline

                machine = _roofline.load_machine() or _roofline.calibrate(
                    persist=False, size_mb=16)
                rl = _roofline.roofline_report(manifest, machine)
            except Exception:
                rl = None
            if rl and rl.get("roofline_frac"):
                diag["roofline"] = rl
                frac = rl["roofline_frac"]
                ach = rl["scan_achieved_gbs"]
                roof = rl["machine"]["host_memcpy_gbs"]
                proj = rl.get("device_map_projection_x")
                if frac >= 0.6:
                    find("warn", "bandwidth-bound",
                         f"host-map scan runs at {ach:.2f} GB/s = {frac:.0%} "
                         f"of the {roof:.2f} GB/s host memcpy roof — the "
                         "host wire is nearly saturated; no same-engine "
                         "tuning buys much, only the device-resident map "
                         "(ROADMAP item 2) takes these bytes off the host "
                         "path"
                         + (f" (projected ~{proj:g}× at half the device "
                            "roof)" if proj else ""))
                else:
                    find("info", "compute-headroom",
                         f"host-map scan achieves {ach:.2f} GB/s = {frac:.0%} "
                         f"of the {roof:.2f} GB/s host memcpy roof — the "
                         f"scan is compute-limited with ~{1.0 / frac:.1f}× "
                         "bandwidth headroom on this wire; a device-resident "
                         "map (ROADMAP item 2) is the lever"
                         + (f" (projected ~{proj:g}× at half the target "
                            "roof)" if proj else ""))

        # ---- lineage / incremental opportunity (ISSUE 20) ----
        # A stamped blast-radius diff turns "rerun everything" into a
        # measured number: memo_hit_frac of bytes whose chunk digests are
        # unchanged — exactly the fraction a memoizing re-run (ROADMAP
        # item 4) would skip.
        lin = stats.get("lineage") or {}
        if lin.get("chunks"):
            diag["lineage"] = lin
            hit = lin.get("memo_hit_frac")
            if hit is not None:
                find("info", "incremental-opportunity",
                     f"provenance ledger covers {lin['chunks']} chunks "
                     f"({lin.get('bytes', 0)} bytes) and the stamped diff "
                     f"shows {hit:.1%} of input bytes unchanged since the "
                     f"baseline ({lin.get('changed_chunks', 0)} chunks "
                     "changed) — incremental re-execution (ROADMAP item 4) "
                     f"could memo-skip ~{hit:.0%} of the map work")
            else:
                find("info", "incremental-opportunity",
                     f"provenance ledger covers {lin['chunks']} chunks "
                     f"({lin.get('bytes', 0)} bytes); run `mapreduce_rust_tpu "
                     "lineage diff <old> <new> --stamp` against a prior run "
                     "to measure the recompute blast radius incremental "
                     "re-execution (ROADMAP item 4) would avoid")

    # ---- percentiles ----
    hists = {
        name: h.summary(scale=1e3, digits=3)  # seconds → ms
        for name, hd in sorted((stats.get("histograms") or {}).items())
        if name.endswith("_s") and (h := _hist(hd)) is not None
    }
    for name, hd in sorted((stats.get("histograms") or {}).items()):
        if not name.endswith("_s") and (h := _hist(hd)) is not None:
            hists[name] = h.summary(scale=1.0, digits=1)
    if hists:
        diag["histograms_ms"] = hists
    if report and report.get("rpc"):
        diag["rpc_ms"] = {
            m: {k: r.get(k) for k in
                ("count", "p50_ms", "p95_ms", "p99_ms", "max_ms")}
            for m, r in sorted(report["rpc"].items())
        }

    # ---- skew ----
    skew = {}
    parts = _skew_score(stats.get("partition_bytes"))
    if parts is not None:
        skew["reduce_partition_bytes"] = parts
        if stats.get("partition_mode") == "range":
            # Range-partitioned run (sort, ISSUE 15): partition_bytes
            # measures SPLITTER quality, not hash mixing — the realized
            # per-partition bytes vs the ideal R-way split. The fix is
            # the sampler's knob, not reduce_n: more samples per file
            # flatten the quantile estimate on skewed corpora.
            if parts["score"] and parts["score"] > 1.5 and parts["n"] >= 2:
                n_samp = stats.get("splitter_samples") or 0
                find("warn", "splitter-quality",
                     f"hottest range partition holds {parts['score']:.1f}x "
                     f"its fair share of output bytes ({parts['max']} of "
                     f"ideal {parts['mean']:.0f}) — the {n_samp} sampled "
                     "keys under-resolved the key distribution; raise "
                     "--split-samples (Config.split_samples) so the "
                     "derived splitters track the real quantiles")
        elif parts["score"] and parts["score"] > 2.0 and parts["n"] >= 4:
            find("warn", "reduce-skew",
                 f"hottest reduce partition holds {parts['score']:.1f}x its "
                 f"fair share of output bytes ({parts['max']} of mean "
                 f"{parts['mean']:.0f}) — keys hash-route unevenly; raise "
                 "reduce_n or revisit the partition key")
    fold_split = stats.get("fold_split") or {}
    fsk = _skew_score(fold_split.get("per_shard_s"))
    if fsk is not None:
        skew["fold_shard_s"] = fsk
        if (
            fsk["score"] and fsk["score"] > 1.75
            and (fold_split.get("fold_s") or 0.0) > 0.2
        ):
            find("warn", "fold-shard-skew",
                 f"hottest fold shard spent {fsk['score']:.1f}x the mean "
                 f"fold time ({fsk['max']:.2f}s of mean {fsk['mean']:.2f}s "
                 f"across {fold_split.get('shards')} shards) — the key-hash "
                 "load is imbalanced, so one fold thread carries the egress "
                 "fold serially; more fold_shards won't help until the hot "
                 "keys spread (check for a dominant window or a skewed "
                 "vocabulary)")
    shards = _skew_score(stats.get("mesh_shard_rows"))
    if shards is not None:
        skew["mesh_shard_rows"] = shards
        if shards["score"] and shards["score"] > 2.0:
            find("warn", "shard-skew",
                 f"hottest mesh shard holds {shards['score']:.1f}x the mean "
                 "distinct-key load — one chip's merge/egress carries the "
                 "job (hash-class imbalance)")
    if report:
        durs = [
            t.get("duration_s")
            for t in (report.get("tasks") or {}).get("reduce", {}).values()
            if t.get("duration_s")
        ]
        rd = _skew_score(durs)
        if rd is not None:
            skew["reduce_task_duration_s"] = rd
            if rd["score"] and rd["score"] > 2.0 and rd["n"] >= 3:
                find("warn", "reduce-duration-skew",
                     f"slowest reduce task ran {rd['score']:.1f}x the mean "
                     "duration — partition skew or a straggling worker")
    if skew:
        diag["skew"] = skew

    # ---- stragglers ----
    if report and report.get("workers"):
        per_worker = {}
        p50s = {}
        for wid, w in report["workers"].items():
            h = _hist(w.get("task_s"))
            per_worker[wid] = {
                "reports": w.get("reports", 0),
                "grants": w.get("grants", 0),
                "task_p50_s": h.percentile(0.5) if h else None,
                "task_p99_s": h.percentile(0.99) if h else None,
            }
            if h is not None:
                p50s[wid] = h.percentile(0.5)
        flagged = []
        if len(p50s) >= 2:
            # LOWER median: with two workers the reference must be the
            # faster one, or the slow worker would be its own yardstick
            # and a 2-fleet straggler could never be flagged.
            med = sorted(p50s.values())[(len(p50s) - 1) // 2]
            if med > 0:
                flagged = sorted(
                    wid for wid, p in p50s.items()
                    if p > straggler_factor * med
                )
        diag["stragglers"] = {
            "factor": straggler_factor,
            "workers": per_worker,
            "flagged": flagged,
        }
        for wid in flagged:
            find("warn", "straggler",
                 f"worker {wid}: task p50 {p50s[wid]:.3f}s exceeds "
                 f"{straggler_factor:.1f}x the fleet median — a slow host, "
                 "an oversubscribed core, or skewed inputs",
                 key=f"straggler:w{wid}")

    # ---- speculation effectiveness (ISSUE 6) ----
    if report:
        spec_tot = {"attempts": 0, "won": 0, "wasted": 0, "time_saved_s": 0.0}
        for tot in (report.get("totals") or {}).values():
            s = tot.get("speculation")
            if s:
                for k in spec_tot:
                    spec_tot[k] += s.get(k, 0) or 0
        if spec_tot["attempts"]:
            spec_tot["time_saved_s"] = round(spec_tot["time_saved_s"], 4)
            diag["speculation"] = spec_tot
            find("info", "speculation-effectiveness",
                 f"{spec_tot['won']} of {spec_tot['attempts']} speculative "
                 f"attempt(s) won the race ({spec_tot['wasted']} wasted), "
                 f"~{spec_tot['time_saved_s']:.2f}s saved vs lease-expiry-"
                 "only recovery")
            if spec_tot["attempts"] >= 3 and spec_tot["won"] == 0:
                find("warn", "speculation-wasteful",
                     f"all {spec_tot['attempts']} speculative attempts lost "
                     "their race — the originals finish first; raise "
                     "--speculate-after-frac or the slow factor so only "
                     "genuine stragglers get duplicated")

    # ---- lease tuning ----
    lease_s = (manifest.get("config") or {}).get("lease_timeout_s")
    if report and lease_s:
        p99s = [
            h.percentile(0.99)
            for tot in (report.get("totals") or {}).values()
            if (h := _hist(tot.get("task_s"))) is not None
        ]
        expiries = sum(
            tot.get("expiries", 0)
            for tot in (report.get("totals") or {}).values()
        )
        if p99s:
            p99 = max(p99s)
            advice = None
            if p99 >= 0.8 * lease_s:
                advice = (
                    f"task p99 ({p99:.2f}s) crowds the {lease_s:.1f}s lease "
                    "timeout — healthy tasks risk expiry; raise "
                    "--lease-timeout or shrink tasks"
                )
                find("warn" if expiries else "info", "lease-tight", advice)
            elif lease_s > 20 * p99:
                advice = (
                    f"lease timeout ({lease_s:.1f}s) is {lease_s / p99:.0f}x "
                    f"the task p99 ({p99:.2f}s) — a dead worker blocks its "
                    "task that long; a lower --lease-timeout recovers faster"
                )
                find("info", "lease-loose", advice)
            diag["lease"] = {
                "timeout_s": lease_s,
                "task_p99_s": round(p99, 4),
                "expiries": expiries,
                "advice": advice,
            }

    # ---- compile / device memory ----
    comp = stats.get("compile")
    if comp:
        diag["compile"] = comp
    if stats.get("device_mem_high_bytes"):
        diag["device_memory"] = {
            "high_water_bytes": stats["device_mem_high_bytes"]
        }

    # ---- crash forensics: incomplete attempt chains ----
    incomplete_tasks = []
    if report:
        for phase, tasks in (report.get("tasks") or {}).items():
            for tid, t in tasks.items():
                if t.get("grants", 0) > 0 and not t.get("completed"):
                    incomplete_tasks.append(f"{phase}:{tid}")
        expiries = sum(
            tot.get("expiries", 0)
            for tot in (report.get("totals") or {}).values()
        )
        reexecs = sum(
            tot.get("re_executions", 0)
            for tot in (report.get("totals") or {}).values()
        )
        if expiries or reexecs:
            find("info", "re-execution",
                 f"{expiries} lease expirie(s), {reexecs} re-execution(s) — "
                 "a worker died or stalled mid-task; the timeline's forked "
                 "attempt chains name which")
    incomplete_flows = []
    if trace_events:
        chains = _flow_chains(trace_events)
        incomplete_flows = sorted(
            fid for fid, phs in chains.items() if fid and "f" not in phs
        )
    if incomplete_tasks or incomplete_flows:
        diag["incomplete"] = {
            "tasks": sorted(incomplete_tasks),
            "flows": incomplete_flows,
        }
        for label, items in (("task", incomplete_tasks),
                             ("attempt chain", incomplete_flows)):
            if items:
                find("error" if label == "task" else "warn",
                     "incomplete-" + ("task" if label == "task" else "chain"),
                     f"{len(items)} {label}(s) started but never finished "
                     f"({', '.join(items[:6])}"
                     + (", …" if len(items) > 6 else "") + ") — a crashed "
                     "or SIGKILLed attempt; the flight-recorder partial "
                     "holds its last events")

    if not stats and not report:
        find("error", "no-telemetry",
             "manifest carries neither stats nor a job report — nothing to "
             "diagnose (was this a bench-harness or sweep manifest?)")

    findings.sort(key=lambda f: _SEV_RANK.get(f["severity"], 9))
    diag["findings"] = findings
    return diag


# ---------------------------------------------------------------------------
# Streaming doctor (ISSUE 8): the same finding catalog, evaluated against
# a RUNNING job's live telemetry instead of its corpse.
# ---------------------------------------------------------------------------

#: Finding codes that only make sense post-mortem: mid-run, every
#: in-flight task is "granted but not completed" by construction and
#: every open flow chain is unterminated — those are a live job's normal
#: state, not a diagnosis.
_POST_MORTEM_CODES = frozenset({
    "incomplete-task", "incomplete-chain", "no-telemetry", "run-error",
})

#: Renewal-envelope series that sum fleet-wide into the wait-split fields
#: _bottleneck_attribution understands (worker series are prefixed;
#: strip to the JobStats field name).
_WAIT_FIELDS = ("ingest_wait_s", "device_wait_s", "host_map_s",
                "host_glue_s", "fold_s", "fold_stall_s", "spill_s",
                "spill_stall_s", "dispatch_s", "dispatch_stall_s",
                "scan_wait_s", "all_to_all_s")


def diagnose_live(stats_rpc: dict, lease_timeout_s: "float | None" = None,
                  straggler_factor: float = 2.0,
                  fleet: "dict | None" = None) -> dict:
    """One streaming-doctor evaluation over a coordinator ``stats`` RPC
    response (which IS a job-report dict plus ``progress``) and the
    fleet's latest renewal-envelope samples. Reuses :func:`diagnose` —
    the catalog is shared, not forked — then drops the post-mortem-only
    codes and adds the live host-glue/stall bottleneck attribution when
    the fleet samples carry wait-split series. Pure function: the
    coordinator's tick and ``doctor --live`` both call it."""
    manifest: dict = {"kind": "live"}
    if lease_timeout_s:
        manifest["config"] = {"lease_timeout_s": lease_timeout_s}
    agg: dict = {}
    for s in (fleet or {}).values():
        for k, v in (s.get("v") or {}).items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            for field in _WAIT_FIELDS:
                if str(k).endswith(field):
                    agg[field] = agg.get(field, 0.0) + v
    if any(agg.values()):
        manifest["stats"] = agg
    diag = diagnose(manifest, job_report=stats_rpc,
                    straggler_factor=straggler_factor)
    diag["kind"] = "live"
    findings = [
        f for f in diag["findings"] if f["code"] not in _POST_MORTEM_CODES
    ]
    bn = diag.get("bottleneck")
    if bn and bn.get("name") not in (None, "balanced"):
        top = (bn.get("attribution") or [{}])[0]
        findings.append({
            "severity": "info", "code": "live-bottleneck",
            "key": "live-bottleneck",
            "message": (
                f"fleet-aggregated wait split currently names "
                f"{bn['name']!r} ({top.get('seconds', 0):.3f}s, "
                f"{(top.get('share') or 0):.0%} of attributed time)"
            ),
        })
    diag["findings"] = findings
    return diag


def fold_live_findings(registry: dict, findings: list, now: float,
                       prefix: str = "", on_new=None) -> set:
    """Fold one tick's findings into a live-findings registry (key →
    finding with first/last-seen stamps + active flag) — the streaming
    doctor's dedup, shared by the Coordinator's tick and the
    JobService's (which prefixes per-job keys). ``on_new(key, finding)``
    fires exactly once per key's first appearance (the callers' log +
    trace-instant hook). Returns the keys seen THIS tick; after folding
    every source, pass their union to :func:`deactivate_stale_findings`
    — a finding kept with its first_seen is history, not a gauge: a
    straggler that recovered still happened."""
    current: set = set()
    for f in findings or []:
        key = prefix + (f.get("key") or f["code"])
        current.add(key)
        known = registry.get(key)
        if known is None:
            registry[key] = {
                **f, "key": key,
                "first_seen_s": now, "last_seen_s": now, "active": True,
            }
            if on_new is not None:
                on_new(key, f)
        else:
            known.update({
                "message": f["message"], "severity": f["severity"],
                "last_seen_s": now, "active": True,
            })
    return current


def deactivate_stale_findings(registry: dict, current: set) -> None:
    for key, f in registry.items():
        if key not in current:
            f["active"] = False


def service_findings(service: "dict | None") -> list:
    """Live findings of the multi-tenant service plane (ISSUE 14) over a
    JobService ``service_summary()`` dict — evaluated by the service's
    doctor tick beside the per-job diagnose_live passes. The headline
    finding is ``service-saturated``: the admission budget is holding
    queued jobs back while jobs already run — by design (backpressure,
    not a fault), but an operator watching the queue back up needs the
    doctor to say WHY and which knob to turn."""
    if not isinstance(service, dict):
        return []
    findings: list[dict] = []
    queued = service.get("queued") or 0
    if queued and service.get("admission_blocked"):
        inflight = service.get("inflight_bytes") or 0
        budget = service.get("budget_bytes") or 0
        findings.append({
            "severity": "warn", "code": "service-saturated",
            "key": "service-saturated",
            "message": (
                # MiB, matching the service_inflight_budget_mb knob's
                # unit (budget_bytes = mb << 20) — an operator must be
                # able to copy the displayed number back into the flag.
                f"admission blocked: {inflight / (1 << 20):.1f} MB in "
                f"flight of a {budget / (1 << 20):.1f} MB budget with "
                f"{queued} job(s) queued "
                f"({service.get('running', 0)} running) — backpressure is "
                "working; raise service_inflight_budget_mb / "
                "service_max_jobs or add workers to drain faster"
            ),
        })
    elif queued and (service.get("running") or 0) \
            >= (service.get("max_jobs") or 1):
        findings.append({
            "severity": "info", "code": "service-queue",
            "key": "service-queue",
            "message": (
                f"{queued} job(s) queued behind the "
                f"service_max_jobs={service.get('max_jobs')} concurrency "
                "cap"
            ),
        })
    # ---- fleet profiler plane (ISSUE 16) ----
    fl = service.get("fleet_util")
    if isinstance(fl, dict) and (fl.get("active_ws") or 0) >= 5.0:
        # ≥ 5 fleet worker-seconds observed: below that a single poll gap
        # reads as a 100% bubble. Thresholds are deliberately coarse —
        # these are operator prompts, not SLO breaches.
        bubble = fl.get("bubble_frac") or 0.0
        # Quiet on pipelined runs (ISSUE 17): the scheduler already
        # grants reduce per partition and fills barriers with other
        # jobs' map windows — the opportunity the advice names is
        # realized, and residual bubble is queue pressure the
        # service-saturated/service-queue findings already cover.
        if bubble > 0.25 and service.get("sched") != "pipeline":
            findings.append({
                "severity": "warn", "code": "barrier-bubble",
                "key": "barrier-bubble",
                "message": (
                    f"{bubble:.0%} of fleet worker-seconds idle while "
                    "reduce work was barrier-blocked or jobs sat queued "
                    f"({fl.get('bubble_ws', 0):.1f} worker-s) — rerun "
                    "the service and its workers with `--sched pipeline` "
                    "to release reduce per partition and fill barrier "
                    "bubbles with other jobs' map windows; see "
                    "`fleet <work-root>` for the per-job breakdown"
                ),
            })
        utils = [
            w.get("util_frac") for w in (fl.get("workers") or {}).values()
            if isinstance(w, dict) and not w.get("drained")
            and isinstance(w.get("util_frac"), (int, float))
        ]
        if len(utils) >= 2 and max(utils) > 0.2:
            mean = sum(utils) / len(utils)
            if mean > 0 and max(utils) / mean > 2.0:
                findings.append({
                    "severity": "warn", "code": "fleet-imbalance",
                    "key": "fleet-imbalance",
                    "message": (
                        f"worker utilization is imbalanced: max "
                        f"{max(utils):.0%} vs fleet mean {mean:.0%} — "
                        "admission-order granting is starving part of "
                        "the fleet (long map tasks on one worker, or a "
                        "worker polling a barrier-gated job)"
                    ),
                })
    slo = service.get("slo")
    if isinstance(slo, dict):
        lo = _hist((slo.get("low") or {}).get("queue_wait_s"))
        hi = _hist((slo.get("high") or {}).get("queue_wait_s"))
        if lo is not None and hi is not None:
            lo95 = lo.percentile(0.95) or 0.0
            hi95 = hi.percentile(0.95) or 0.0
            if lo95 > 1.0 and lo95 > 4.0 * max(hi95, 0.05):
                findings.append({
                    "severity": "warn", "code": "admission-starvation",
                    "key": "admission-starvation",
                    "message": (
                        f"low-priority queue-wait p95 {lo95:.2f}s vs "
                        f"high-priority {hi95:.2f}s — strict-priority "
                        "admission is starving the low class; consider "
                        "aging or a budget carve-out"
                    ),
                })
    return findings


def format_live(metrics_rpc: dict, stats_rpc: "dict | None" = None) -> str:
    """Plain-text view of the coordinator ``metrics`` RPC — the streaming
    findings (first-seen stamps, live/cleared state) and the fleet's
    freshest samples. ``watch --doctor`` appends this under the progress
    view; ``doctor --live`` prints it on its own."""
    lines: list[str] = []
    findings = metrics_rpc.get("findings") or []
    if findings:
        lines.append(f"  doctor[live]: {len(findings)} finding(s)")
        for f in findings:
            state = "live" if f.get("active", True) else "cleared"
            lines.append(
                f"    [{f['severity'].upper():<5}] {f['code']}"
                f" (first seen {f.get('first_seen_s', 0):.1f}s, {state}): "
                f"{f['message']}"
            )
    else:
        lines.append("  doctor[live]: no findings yet")
    fleet = metrics_rpc.get("fleet") or {}
    for wid, s in sorted(fleet.items(), key=lambda kv: str(kv[0])):
        v = s.get("v") or {}
        parts = [
            f"{k.split('.', 1)[-1]}={v[k]:g}" for k in sorted(v)
            if isinstance(v[k], (int, float)) and not isinstance(v[k], bool)
        ]
        lines.append(
            f"    w{wid} sample ({s.get('age_s', 0):.1f}s old): "
            + (" ".join(parts[:8]) or "empty")
        )
    return "\n".join(lines)


def run_live_cli(args) -> int:
    """``doctor --live HOST:PORT``: poll the coordinator's stats+metrics
    RPCs and stream findings as they appear, until the job completes (or
    --once). Exit 0 on a completed/observed job, 1 when no coordinator
    answers. Backend-free like every analysis tool."""
    import asyncio

    from mapreduce_rust_tpu.coordinator.server import (
        CoordinatorClient,
        RpcTimeout,
    )

    addr = args.live
    host, _, port_s = addr.rpartition(":")
    host = host or "127.0.0.1"
    try:
        port = int(port_s)
    except ValueError:
        print(f"doctor --live: bad address {addr!r} (want HOST:PORT)")
        return 2
    interval = getattr(args, "interval", None) or 1.0
    once = bool(getattr(args, "once", False))
    # ``--job <id>`` (ISSUE 14): against a JobService, stream ONE job's
    # view — its stats come from the job_status RPC and the service's
    # findings are filtered to that job's key prefix.
    job = getattr(args, "job", None)

    async def go() -> int:
        client = CoordinatorClient(host, port,
                                   timeout_s=max(interval * 5, 3.0))
        try:
            await client.connect(retries=5, delay=0.2)
        except (OSError, RpcTimeout) as e:
            print(f"doctor --live: no coordinator at {host}:{port} ({e})")
            return 1
        seen: set = set()
        try:
            while True:
                try:
                    rep = await client.call("job_status", job) if job \
                        else await client.call("stats")
                    live = await client.call("metrics")
                except RpcTimeout as e:
                    print(f"doctor --live: coordinator not answering ({e})")
                    return 1
                except (ConnectionError, RuntimeError) as e:
                    # Gone = job finished; RuntimeError = pre-metrics
                    # coordinator (unknown method) — say which.
                    if isinstance(e, RuntimeError) and "unknown method" in str(e):
                        print("doctor --live: coordinator predates the "
                              "metrics RPC — upgrade it or use post-run "
                              "`doctor <manifest>`")
                        return 2
                    print("doctor --live: coordinator gone — job finished")
                    return 0
                if job and isinstance(rep, dict) and rep.get("ok") is False:
                    print(f"doctor --live: {rep.get('error')}")
                    return 2
                if job:
                    # Per-job filter: the service prefixes per-job finding
                    # keys with "<jid>:" (service-plane findings like
                    # service-saturated stay visible — they affect every
                    # job).
                    live = dict(live)
                    live["findings"] = [
                        f for f in live.get("findings") or []
                        if f.get("job") == job
                        or str(f.get("key", "")).startswith(f"{job}:")
                        or str(f.get("code", "")).startswith("service-")
                    ]
                if getattr(args, "format", "text") == "json":
                    print(json.dumps({"stats": rep, "metrics": live},
                                     sort_keys=True), flush=True)
                else:
                    for f in live.get("findings") or []:
                        key = f.get("key") or f.get("code")
                        if key not in seen:
                            seen.add(key)
                            print(
                                f"[{f.get('first_seen_s', 0):>7.1f}s] "
                                f"[{f['severity'].upper():<5}] "
                                f"{f['code']}: {f['message']}", flush=True,
                            )
                done = rep.get("state") in ("done", "cancelled", "failed") \
                    if job else (rep.get("progress") or {}).get("done")
                if once or done:
                    if getattr(args, "format", "text") == "text":
                        print(format_live(live, rep))
                        if done:
                            print("doctor --live: job complete")
                    return 0
                await asyncio.sleep(interval)
        finally:
            await client.close()

    return asyncio.run(go())


# ---------------------------------------------------------------------------
# Trend: N-round drift detection over .bench/history.jsonl (ISSUE 6)
# ---------------------------------------------------------------------------

#: History-line series the trend gate watches: field → the direction that
#: is BAD ("down": a decline regresses — these are GB/s-class metrics).
TREND_SERIES: dict[str, str] = {
    "value": "down",
    "zipf_gbs": "down",
    # Live-metrics sampler tax (ISSUE 8): bench measures a metrics-on vs
    # metrics-off pair each run; a creeping overhead fraction is exactly
    # the slow-boil regression class trend exists for.
    "metrics_overhead_frac": "up",
    # Dispatch-plane coalescing effectiveness (ISSUE 13): mean update
    # fill drifting DOWN means dispatches go out emptier round over round
    # — the coalesce factor eroding (a vocabulary shift, a threshold
    # regression) long before the wall number moves.
    "merge_fill_frac": "down",
    # Job-service throughput (ISSUE 14): the bench service leg's
    # jobs-per-minute over a fixed mixed-submission stream. Drifting DOWN
    # means the control plane itself (admission, dispatch, per-job
    # bookkeeping) got slower — the regression class a single-job wall
    # number can never see.
    "service_jobs_per_min": "down",
    # Workload plane (ISSUE 15): the bench sort leg's wall and its
    # realized partition-bytes skew ratio. Wall drifting UP is the
    # range-partitioned path slowing; skew drifting UP is the sampled
    # splitters degrading (sampler regression, corpus-generator drift) —
    # each invisible to the hash legs.
    "sort_wall_s": "up",
    "sort_skew": "up",
    # Fleet profiler (ISSUE 16): the bench service leg's cross-job
    # accounting. Bubble fraction drifting UP means more fleet
    # worker-seconds lost to the map barrier / admission queue; util
    # drifting DOWN is the same loss seen from the other side; the
    # pipelining opportunity drifting UP means the barrier is leaving
    # ever more reclaimable headroom on the table (ROADMAP item 1's
    # before/after number).
    "fleet_bubble_frac": "up",
    "fleet_util_frac": "down",
    "pipelining_opportunity_s": "up",
    # Model checker (ISSUE 18): mrmodel exploration throughput over the
    # fixed bench budget. Drifting DOWN means the real control plane (or
    # the invariant replay it runs per schedule) got slower — and since
    # CI explores under a fixed time box, a slower loop silently shrinks
    # the schedule space actually covered.
    "model_schedules_per_s": "down",
    # Roofline attribution (ISSUE 19): the zipf leg's host-map scan
    # achieved GB/s and its fraction of the calibrated memcpy roof.
    # Either drifting DOWN means the scan is moving AWAY from the
    # hardware — a native-scan regression or a machine/calibration shift
    # — exactly the efficiency erosion a wall-seconds series hides when
    # corpus size drifts with it.
    "scan_achieved_gbs": "down",
    "roofline_frac": "down",
    # Sampler tax (ISSUE 19): the --profile-overhead interleaved pair's
    # min-of-N estimate; creeping UP is the profiler outgrowing its ≤2%
    # budget (the metrics_overhead_frac twin).
    "profile_overhead_frac": "up",
    # Provenance plane (ISSUE 20): the --lineage-overhead pair's ledger
    # tax creeping UP is the digest/ledger path outgrowing its ≤2%
    # budget; the blast-radius leg's memo_hit_frac drifting DOWN on the
    # fixed +1% grown corpus means chunking stability eroded — a window
    # boundary shift silently shrinking what incremental re-execution
    # (ROADMAP item 4) could ever skip.
    "lineage_overhead_frac": "up",
    "lineage_memo_hit_frac": "down",
}


def _least_squares_slope(ys: list) -> float:
    """Slope of y over index 0..n-1 (ordinary least squares)."""
    n = len(ys)
    xbar = (n - 1) / 2.0
    ybar = sum(ys) / n
    num = sum((i - xbar) * (y - ybar) for i, y in enumerate(ys))
    den = sum((i - xbar) ** 2 for i in range(n))
    return num / den if den else 0.0


def analyze_trend(lines: list, window: int = 8,
                  drift_threshold: float = 0.10,
                  min_points: int = 4) -> dict:
    """Sustained-drift detection the pairwise ``--baseline`` gate misses:
    a metric that loses 3% every round never trips a 10% pair threshold
    but is down 27% after nine rounds. Over the last ``window`` points of
    each watched series: the least-squares slope (normalized to relative
    drift across the window) AND last-vs-median must both point the bad
    way beyond threshold — slope alone would flag an old, recovered dip;
    last-vs-median alone would flag a single noisy round."""
    series: dict[str, list] = {k: [] for k in TREND_SERIES}
    for ln in lines:
        if not isinstance(ln, dict):
            continue
        for key in TREND_SERIES:
            v = ln.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                series[key].append(float(v))
    out: dict = {
        "schema": DOCTOR_SCHEMA,
        "kind": "doctor_trend",
        "window": window,
        "threshold": drift_threshold,
        "rounds_seen": len(lines),
        "series": {},
        "drifts": [],
    }
    for key, ys in series.items():
        if len(ys) < min_points:
            out["series"][key] = {"points": len(ys), "status": "insufficient"}
            continue
        win = ys[-window:]
        med = sorted(win)[(len(win) - 1) // 2]
        slope = _least_squares_slope(win)
        scale = abs(med) or 1.0
        rel_drift = slope * (len(win) - 1) / scale  # over the whole window
        last_vs_median = (win[-1] - med) / scale
        bad = TREND_SERIES[key]
        sign = -1.0 if bad == "down" else 1.0
        drifting = (
            sign * rel_drift > drift_threshold
            and sign * last_vs_median > drift_threshold / 2
        )
        entry = {
            "points": len(win),
            "median": round(med, 6),
            "last": round(win[-1], 6),
            "slope_per_round": round(slope, 6),
            "rel_drift_over_window": round(rel_drift, 4),
            "last_vs_median": round(last_vs_median, 4),
            "bad_direction": bad,
            "status": "drifting" if drifting else "stable",
        }
        out["series"][key] = entry
        if drifting:
            out["drifts"].append({"metric": key, **entry})
    return out


def format_trend(t: dict) -> str:
    lines = [
        f"doctor trend — {t['rounds_seen']} round(s), window {t['window']}, "
        f"threshold {t['threshold']:.0%}"
    ]
    for key, s in sorted((t.get("series") or {}).items()):
        if s.get("status") == "insufficient":
            lines.append(f"  {key:<12} {s['points']} point(s) — insufficient "
                         "data (need more rounds)")
            continue
        lines.append(
            f"  {key:<12} [{s['status'].upper():<8}] last={s['last']:g} "
            f"median={s['median']:g} drift/window={s['rel_drift_over_window']:+.1%} "
            f"last-vs-median={s['last_vs_median']:+.1%}"
        )
    if t.get("drifts"):
        lines.append(f"  SUSTAINED DRIFT in {len(t['drifts'])} metric(s) — "
                     "the pairwise gate would have missed this")
    else:
        lines.append("  no sustained drift")
    return "\n".join(lines)


def run_trend_cli(args) -> int:
    """``doctor trend [history.jsonl]``: exit 0 = stable/insufficient,
    1 = sustained drift (the CI gate), 2 = unreadable history."""
    path = getattr(args, "history", None) or ".bench/history.jsonl"
    try:
        with open(path) as f:
            raw = f.read()
    except OSError as e:
        print(f"doctor trend: cannot read history {path!r}: {e}")
        return 2
    lines = []
    for ln in raw.splitlines():
        ln = ln.strip()
        if not ln:
            continue
        try:
            lines.append(json.loads(ln))
        except json.JSONDecodeError:
            continue  # a torn append must not invalidate the whole history
    t = analyze_trend(
        lines,
        window=getattr(args, "window", 8) or 8,
        drift_threshold=getattr(args, "drift_threshold", 0.10) or 0.10,
    )
    if getattr(args, "format", "text") == "json":
        print(json.dumps(t, indent=2, sort_keys=True))
    else:
        print(format_trend(t))
    return 1 if t["drifts"] else 0


# ---------------------------------------------------------------------------
# Rendering + CLI
# ---------------------------------------------------------------------------

def format_diagnosis(diag: dict, regressions: "list | None" = None) -> str:
    lines = [f"doctor diagnosis (schema {diag.get('schema')})"]
    bn = diag.get("bottleneck")
    if bn:
        agree = "" if bn.get("agrees_with_stats") else \
            f"  [manifest recorded {bn.get('recorded')!r}]"
        lines.append(f"  bottleneck: {bn['name']}{agree}")
        for a in bn.get("attribution") or []:
            share = f" ({a['share']:.0%})" if a.get("share") is not None else ""
            lines.append(
                f"    {a['component']:<12} {a['seconds']:9.3f}s{share}"
            )
    for name, h in sorted((diag.get("histograms_ms") or {}).items()):
        if not h.get("count"):
            continue
        unit = "ms" if name.endswith("_s") else ""
        lines.append(
            f"  hist {name:<20} n={h['count']:<6} p50={h['p50']:g} "
            f"p95={h['p95']:g} p99={h['p99']:g} max={h['max']:g} {unit}"
        )
    for m, r in sorted((diag.get("rpc_ms") or {}).items()):
        lines.append(
            f"  rpc  {m:<24} n={r.get('count', 0):<6} "
            f"p50={r.get('p50_ms', 0)}ms p99={r.get('p99_ms', 0)}ms "
            f"max={r.get('max_ms', 0)}ms"
        )
    for key, s in sorted((diag.get("skew") or {}).items()):
        lines.append(
            f"  skew {key}: score {s.get('score')} "
            f"(max {s.get('max')} / mean {s.get('mean')}, n={s.get('n')})"
        )
    spec = diag.get("speculation")
    if spec:
        lines.append(
            f"  speculation: {spec['won']} won / {spec['wasted']} wasted of "
            f"{spec['attempts']} attempts (~{spec['time_saved_s']}s saved)"
        )
    st = diag.get("stragglers")
    if st:
        flagged = st.get("flagged") or []
        lines.append(
            f"  stragglers: {len(flagged)} flagged of "
            f"{len(st.get('workers') or {})} workers "
            f"(factor {st.get('factor')})"
            + (f" — {', '.join('w' + str(w) for w in flagged)}" if flagged else "")
        )
    lease = diag.get("lease")
    if lease:
        lines.append(
            f"  lease: timeout {lease['timeout_s']}s vs task p99 "
            f"{lease['task_p99_s']}s ({lease.get('expiries', 0)} expiries)"
        )
    comp = diag.get("compile")
    if comp:
        lines.append(
            f"  compile: {comp.get('count')} compiles {comp.get('total_s')}s "
            f"({comp.get('cache_hits')} hits, {comp.get('cache_misses')} "
            "misses)"
        )
    mem = diag.get("device_memory")
    if mem:
        lines.append(
            f"  device memory high-water: "
            f"{mem['high_water_bytes'] / 1e6:.1f} MB"
        )
    inc = diag.get("incomplete")
    if inc:
        lines.append(
            f"  incomplete: tasks={inc.get('tasks')} flows={inc.get('flows')}"
        )
    for f in diag.get("findings") or []:
        lines.append(f"  [{f['severity'].upper():<5}] {f['code']}: {f['message']}")
    if not (diag.get("findings") or []):
        lines.append("  no findings — run looks healthy")
    if regressions is not None:
        if regressions:
            lines.append(f"  REGRESSIONS vs baseline ({len(regressions)}):")
            for r in regressions:
                chg = "new" if r["change"] is None else f"{r['change']:+.1%}"
                lines.append(
                    f"    {r['metric']}: {r['baseline']} -> {r['current']} "
                    f"[{chg}, threshold {r['threshold']:.0%} {r['direction']}]"
                )
        else:
            lines.append("  baseline: no watched metric regressed")
    return "\n".join(lines)


def run_cli(args) -> int:
    """``doctor`` subcommand body. Exit 0 = diagnosis produced; 1 = a
    --baseline watched metric regressed (the CI gate); 2 = unreadable
    input. The literal target ``trend`` dispatches to the history
    analyzer (run_trend_cli) instead of the manifest diagnosis."""
    from mapreduce_rust_tpu.runtime.telemetry import load_manifest

    if getattr(args, "live", None):
        return run_live_cli(args)
    if args.manifest is None:
        print("doctor: need a manifest path (or --live HOST:PORT, or "
              "'trend')")
        return 2
    if args.manifest == "trend":
        return run_trend_cli(args)
    try:
        manifest = load_manifest(args.manifest)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"doctor: cannot read manifest {args.manifest!r}: {e}")
        return 2

    job_report = None
    if getattr(args, "job_report", None):
        try:
            doc = load_manifest(args.job_report)
            job_report = doc.get("report", doc)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"doctor: cannot read job report {args.job_report!r}: {e}")
            return 2

    trace_events = None
    if getattr(args, "trace", None):
        try:
            trace_events = _load_trace_events(args.trace)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"doctor: cannot read trace {args.trace!r}: {e}")
            return 2

    diag = diagnose(
        manifest, job_report=job_report, trace_events=trace_events,
        straggler_factor=getattr(args, "straggler_factor", 2.0),
    )

    regressions = None
    if getattr(args, "baseline", None):
        try:
            base = load_manifest(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"doctor: cannot read baseline {args.baseline!r}: {e}")
            return 2
        regressions = compare_manifests(
            base, manifest,
            threshold_scale=getattr(args, "threshold_scale", 1.0),
        )
        diag["regressions"] = regressions

    if getattr(args, "format", "text") == "json":
        print(json.dumps(diag, indent=2, sort_keys=True))
    else:
        print(format_diagnosis(diag, regressions))
    return 1 if regressions else 0
