"""The mrlint rule set — one rule per bug class this repo actually shipped.

Each rule's docstring names the incident it encodes (the PR that shipped
the bug and the PR that hand-fixed it); the rule exists so the NEXT
regression of that class is caught by ``python -m mapreduce_rust_tpu lint``
in CI instead of by a human reading a heisenbug out of a crashed run.

Rules are deliberately framework-specific: they know this repo's names
(JobStats, ``_a2a_span``, ``Dictionary``, ``SHARD_MAP_NATIVE``) because
the invariants are this framework's, not Python's. Precision beats recall:
a rule that cries wolf gets baselined into silence, so every rule here is
tuned to fire on the shipped bug pattern and stay quiet on the shipped
fix pattern.
"""

from __future__ import annotations

import ast
from typing import Iterator

from mapreduce_rust_tpu.analysis.lint import (
    Finding,
    ancestors,
    enclosing_class,
    enclosing_function,
    last_segment as _last_segment,
    qualname,
)


class Rule:
    """Base: subclasses set ``name``/``summary`` and implement ``run``."""

    name = "rule"
    summary = ""

    def check(self, tree: ast.Module, src: str, path: str) -> list[Finding]:
        return list(self.run(tree, src, path))

    def run(self, tree: ast.Module, src: str, path: str) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, path: str, node: ast.AST, message: str) -> Finding:
        return Finding(self.name, path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message)


def _mentions(node: ast.AST, ident: str, substring: bool = False) -> bool:
    """Does the subtree reference ``ident`` as a Name or Attribute?"""
    for n in ast.walk(node):
        cand = None
        if isinstance(n, ast.Name):
            cand = n.id
        elif isinstance(n, ast.Attribute):
            cand = n.attr
        if cand is not None and (ident in cand if substring else cand == ident):
            return True
    return False


def _kw(call: ast.Call, name: str) -> "ast.expr | None":
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_true(node: "ast.expr | None") -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _is_false(node: "ast.expr | None") -> bool:
    return isinstance(node, ast.Constant) and node.value is False


# ---------------------------------------------------------------------------


class StatsOwnershipRule(Rule):
    """Functions submitted to a thread pool must not mutate JobStats.

    Incident: PR 2's first cut had host-map scan workers doing
    ``stats.host_map_s += dt`` from pool threads; an orphaned scan
    surviving an exception teardown then raced the unwound stream's stats
    (and the += itself was a lost-update race). The fix made scan workers
    pure and moved every stats write to the single consumer thread — this
    rule keeps it that way.
    """

    name = "stats-ownership"
    summary = "pool-submitted functions must not mutate JobStats/self.stats"

    def run(self, tree, src, path):
        submitted: dict[str, ast.Call] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = qualname(node.func)
            arg = None
            if _last_segment(fn) == "submit" and node.args:
                arg = node.args[0]
            elif _last_segment(fn) == "run_in_executor" and len(node.args) >= 2:
                arg = node.args[1]
            if arg is None:
                continue
            name = qualname(arg)
            if name:
                submitted.setdefault(_last_segment(name), node)
            elif isinstance(arg, ast.Lambda):
                yield from self._scan_body(arg, path, "<lambda>")
        if not submitted:
            return
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in submitted:
                yield from self._scan_body(node, path, node.name)

    def _scan_body(self, fn, path, label):
        for node in ast.walk(fn):
            targets = []
            if isinstance(node, ast.AugAssign):
                targets = [node.target]
            elif isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            for t in targets:
                q = qualname(t)
                # stats.x / self.stats.x / outer.stats.x — any write through
                # a segment named 'stats' is a consumer-thread privilege.
                parts = q.split(".")
                if len(parts) >= 2 and "stats" in parts[:-1]:
                    yield self.finding(
                        path, node,
                        f"{label!r} is submitted to an executor but writes "
                        f"{q!r} — JobStats is owned by the consumer thread; "
                        "return the value and fold it there (an orphaned "
                        "task must not race the unwound stream)",
                    )


class ExecutorTeardownRule(Rule):
    """Every ThreadPoolExecutor must reach shutdown(wait=True,
    cancel_futures=True) through a finally block or a with statement.

    Incident: the host-map engine's pool was torn down with the default
    ``shutdown(wait=False)`` on the exception path, abandoning an in-flight
    scan that kept its memmap window alive past the stream's unwind (fixed
    in PR 2); the ingest pool predates even that, leaking executors past
    stream teardown in PR 1.
    """

    name = "executor-teardown"
    summary = "executors need shutdown(wait=True, cancel_futures=True) in a finally/with"

    _GOOD = "shutdown(wait=True, cancel_futures=True)"

    def run(self, tree, src, path):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _last_segment(qualname(node.func)) not in (
                "ThreadPoolExecutor", "ProcessPoolExecutor"
            ):
                continue
            if any(isinstance(a, ast.withitem) for a in ancestors(node)):
                continue  # context manager owns the lifecycle
            target = self._assign_target(node)
            if target is None:
                yield self.finding(
                    path, node,
                    "executor is neither stored nor used as a context manager "
                    f"— it can never reach {self._GOOD}",
                )
                continue
            q = qualname(target)
            if isinstance(target, ast.Name):
                ok, why = self._name_shutdown_in_finally(node, q)
            else:
                ok, why = self._attr_shutdown_anywhere(node, q)
            if not ok:
                yield self.finding(path, node, why)

    def _assign_target(self, call):
        parent = getattr(call, "mr_parent", None)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], (ast.Name, ast.Attribute)):
            return parent.targets[0]
        if isinstance(parent, ast.AnnAssign) \
                and isinstance(parent.target, (ast.Name, ast.Attribute)):
            return parent.target
        return None

    def _shutdown_calls(self, scope, q):
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and qualname(n.func) == f"{q}.shutdown":
                yield n

    def _good_kwargs(self, call) -> "str | None":
        if _is_false(_kw(call, "wait")):
            return "shutdown(wait=False) abandons running futures"
        if not _is_true(_kw(call, "cancel_futures")):
            return ("shutdown without cancel_futures=True leaves queued work "
                    "to run against torn-down state")
        return None

    def _name_shutdown_in_finally(self, call, q):
        scope = enclosing_function(call)
        if scope is None:
            scope = next(
                (a for a in ancestors(call) if isinstance(a, ast.Module)), call
            )
        in_finally = []
        anywhere = []
        for n in ast.walk(scope):
            if isinstance(n, ast.Try):
                for stmt in n.finalbody:
                    in_finally.extend(self._shutdown_calls(stmt, q))
        anywhere.extend(self._shutdown_calls(scope, q))
        if in_finally:
            bad = [self._good_kwargs(c) for c in in_finally]
            good = [b for b in bad if b is None]
            if good:
                return True, ""
            return False, f"executor {q!r}: {bad[0]} — need {self._GOOD}"
        if anywhere:
            return False, (
                f"executor {q!r} is shut down outside any finally block — an "
                f"exception before the call leaks the pool; move "
                f"{self._GOOD} into a finally (or use a with statement)"
            )
        return False, (
            f"executor {q!r} never reaches shutdown — add a finally with "
            f"{self._GOOD} (or use a with statement)"
        )

    def _attr_shutdown_anywhere(self, call, q):
        # self.pool-style executors have a lifecycle method (close/teardown)
        # elsewhere in the class; require the well-formed shutdown to exist
        # anywhere in the owning class body.
        scope = enclosing_class(call)
        if scope is None:
            scope = next(
                (a for a in ancestors(call) if isinstance(a, ast.Module)), call
            )
        calls = list(self._shutdown_calls(scope, q))
        if not calls:
            return False, (
                f"executor {q!r} never reaches shutdown anywhere in its "
                f"owning class — add a teardown path calling {self._GOOD}"
            )
        if any(self._good_kwargs(c) is None for c in calls):
            return True, ""
        return False, (
            f"executor {q!r}: {self._good_kwargs(calls[0])} — need {self._GOOD}"
        )


class TmpdirCleanupRule(Rule):
    """mkdtemp must be paired with a try/finally rmtree in the same function.

    Incident: the streaming egress once leaked ``egress-*`` part files into
    the output dir when a partition sort failed mid-way (ADVICE r5); the fix
    wrapped the whole egress phase in one try/finally rmtree. Spill-run
    files got the same treatment via ``remove_run_files`` in run_job's
    finally.
    """

    name = "tmpdir-cleanup"
    summary = "mkdtemp needs a try/finally rmtree/remove_run_files in the same function"

    _CLEANERS = ("rmtree", "remove_run_files", "cleanup")

    def run(self, tree, src, path):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _last_segment(qualname(node.func)) != "mkdtemp":
                continue
            scope = enclosing_function(node) or tree
            cleaned = False
            for n in ast.walk(scope):
                if not isinstance(n, ast.Try):
                    continue
                for stmt in n.finalbody:
                    for c in ast.walk(stmt):
                        if isinstance(c, ast.Call) and _last_segment(
                            qualname(c.func)
                        ) in self._CLEANERS:
                            cleaned = True
            if not cleaned:
                yield self.finding(
                    path, node,
                    "mkdtemp without a try/finally rmtree (or "
                    "remove_run_files) in the same function — a failure "
                    "between creation and cleanup leaks the directory into "
                    "a shared output/work dir",
                )


class DonationSafetyRule(Rule):
    """donate_argnums on a shard_map computation must sit behind the
    native-shard_map guard.

    Incident: donating state buffers into the pre-0.6 experimental
    ``shard_map`` corrupts the jaxlib 0.4.x CPU client heap (observed as a
    glibc "corrupted double-linked list" under the spill-heavy mesh merge,
    fixed in PR 1 by gating donation on ``_SHARD_MAP_NATIVE``). Donation is
    a memory optimization, never a correctness requirement — unguarded it
    is a latent heap corruption on every jax<0.6 image.
    """

    name = "donation-safety"
    summary = "donate_argnums near shard_map must be gated on SHARD_MAP_NATIVE"

    def run(self, tree, src, path):
        # decorator Call → the FunctionDef it decorates
        deco_owner: dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    for sub in ast.walk(deco):
                        deco_owner[id(sub)] = node
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _kw(node, "donate_argnums") is None and _kw(node, "donate_argnames") is None:
                continue
            if self._guarded(node):
                continue
            owner = deco_owner.get(id(node))
            near_shard_map = False
            if owner is not None and any(
                _mentions(d, "shard_map") for d in owner.decorator_list
            ):
                near_shard_map = True
            else:
                stmt = self._nearest_statement(node)
                if stmt is not None and _mentions(stmt, "shard_map"):
                    near_shard_map = True
            if near_shard_map:
                yield self.finding(
                    path, node,
                    "donate_argnums applied to a shard_map computation "
                    "without the native-shard_map guard — donating into "
                    "jax.experimental.shard_map corrupts the jaxlib 0.4.x "
                    "heap; gate it on SHARD_MAP_NATIVE (see "
                    "parallel/shuffle.py) or drop the donation",
                )

    def _guarded(self, node) -> bool:
        for anc in ancestors(node):
            test = None
            if isinstance(anc, (ast.If, ast.IfExp)):
                test = anc.test
            if test is not None and _mentions(test, "SHARD_MAP_NATIVE", substring=True):
                return True
        return False

    def _nearest_statement(self, node):
        for anc in ancestors(node):
            if isinstance(anc, ast.stmt):
                return anc
        return None


class A2APurityRule(Rule):
    """No blocking readbacks inside ``_a2a_span`` blocks.

    Incident: PR 2 found the mesh replay paths fetching spill counts
    (``device_get`` → host block) INSIDE the ``mesh.all_to_all`` span, so
    ``stats.all_to_all_s`` — the ICI numerator of the interconnect-vs-
    compute split — was inflated with device-wait time and the multi-chip
    attribution lied. The fix moved every blocking fetch after the span;
    this rule pins it.
    """

    name = "a2a-purity"
    summary = "no device_get/block_until_ready/asarray inside _a2a_span blocks"

    _BLOCKING = (
        "device_get", "block_until_ready", "asarray",
        "local_rows", "local_batch", "to_host",
    )

    def run(self, tree, src, path):
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(
                isinstance(item.context_expr, ast.Call)
                and _last_segment(qualname(item.context_expr.func)).lstrip("_")
                == "a2a_span"
                for item in node.items
            ):
                continue
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and _last_segment(
                        qualname(sub.func)
                    ) in self._BLOCKING:
                        yield self.finding(
                            path, sub,
                            f"{qualname(sub.func)!r} inside an _a2a_span "
                            "block — blocking readbacks inflate "
                            "stats.all_to_all_s (the ICI numerator) with "
                            "device-wait time; fetch after the span and "
                            "account it in device_wait_s",
                        )


class SpanBalanceRule(Rule):
    """Tracer spans are entered only via ``with``.

    A span entered by hand (``span = trace_span(...); span.__enter__()``)
    that unwinds on an exception never closes, leaving the Chrome trace
    with partially-overlapping spans that ``validate_events`` rejects and
    Perfetto renders as garbage. The contextmanager protocol is the only
    supported entry.
    """

    name = "span-balance"
    summary = "trace_span/_a2a_span only as a with-statement context"

    _SPANS = ("trace_span", "a2a_span")

    def run(self, tree, src, path):
        if path.endswith("runtime/trace.py"):
            return  # the definition site manipulates spans by construction
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _last_segment(qualname(node.func)).lstrip("_") not in self._SPANS:
                continue
            parent = getattr(node, "mr_parent", None)
            if isinstance(parent, ast.withitem):
                continue
            yield self.finding(
                path, node,
                f"{qualname(node.func)!r} outside a with statement — a "
                "manually entered span that unwinds on exception leaves the "
                "trace unbalanced (validate_events rejects it); use "
                "'with ...:'",
            )


class SpilledDictApiRule(Rule):
    """No ``in``/``.items()`` on a possibly-spilled Dictionary outside
    runtime/dictionary.py.

    Incident: after the bounded-memory dictionary tier landed, RAM-tier
    point probes (``key in d``, ``d.items()``) silently answered from a
    PARTIAL store once a budget flush had moved words to disk runs — PR 1
    made both raise on spilled instances, and egress consumes
    ``iter_sorted()``. This rule catches new probe sites before they trip
    the runtime guard in a spill-heavy run nobody tests locally.

    Precision: a name is Dictionary-typed if it is assigned from a
    ``Dictionary(...)``-like constructor in the same scope (budget kwargs
    present ⇒ spillable), or follows the repo convention of being named
    exactly ``dictionary`` (provenance unknown ⇒ treated as spillable).
    A budget-free local ``Dictionary()`` is provably RAM-only and exempt.
    """

    name = "spilled-dict-api"
    summary = "no in/.items() on possibly-spilled Dictionary outside runtime/dictionary.py"

    def run(self, tree, src, path):
        if path.endswith("runtime/dictionary.py"):
            return
        scopes = [tree] + [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            yield from self._scan_scope(scope, path)

    def _own_nodes(self, scope):
        """Walk a scope without descending into nested function scopes."""
        body = scope.body if isinstance(
            scope, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef)
        ) else [scope]
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scope boundary — it gets its own pass
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _scan_scope(self, scope, path):
        spillable: dict[str, bool] = {}  # name → may be spilled
        for n in self._own_nodes(scope):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                ctor = qualname(n.value.func)
                if _last_segment(ctor).endswith("Dictionary"):
                    spillable[n.targets[0].id] = bool(
                        n.value.args or n.value.keywords
                    )
        def is_risky(expr) -> "str | None":
            q = qualname(expr)
            if not q:
                return None
            if isinstance(expr, ast.Name):
                if expr.id in spillable:
                    return q if spillable[expr.id] else None
                return q if expr.id == "dictionary" else None
            # self.dictionary / worker.dictionary — unknown provenance
            return q if _last_segment(q) == "dictionary" else None

        for n in self._own_nodes(scope):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "items":
                name = is_risky(n.func.value)
                if name:
                    yield self._probe_finding(path, n, f"{name}.items()")
            if isinstance(n, ast.Compare) and len(n.ops) == 1 \
                    and isinstance(n.ops[0], (ast.In, ast.NotIn)):
                name = is_risky(n.comparators[0])
                if name:
                    yield self._probe_finding(path, n, f"'in {name}'")

    def _probe_finding(self, path, node, probe):
        return self.finding(
            path, node,
            f"{probe} on a possibly-spilled Dictionary answers from the RAM "
            "tier only (flushed words live in disk runs) — consume "
            "iter_sorted() / lookup(), or prove it RAM-only "
            "(runtime/dictionary.py owns the spilled API)",
        )


class JitInLoopRule(Rule):
    """No jax.jit/pjit construction inside per-chunk / per-window loops.

    Incident: the round-3 bench measured warm == cold because fresh jitted
    closures were built per call — every chunk paid the trace. The fix
    cached step fns at module level keyed by value (make_step_fns /
    make_packed_merge_fn); constructing a jit inside a data loop recreates
    exactly that bug, with a ~40 s XLA compile per iteration on TPU.
    """

    name = "jit-in-loop"
    summary = "no jax.jit/pjit construction inside data loops"

    _JITS = ("jit", "pjit")

    def _is_jit_expr(self, node) -> bool:
        if _last_segment(qualname(node)) in self._JITS:
            return True
        if isinstance(node, ast.Call):
            fn = _last_segment(qualname(node.func))
            if fn in self._JITS:
                return True
            if fn == "partial" and node.args \
                    and _last_segment(qualname(node.args[0])) in self._JITS:
                return True
        return False

    def _in_loop(self, node) -> bool:
        return any(
            isinstance(a, (ast.For, ast.AsyncFor, ast.While))
            for a in ancestors(node)
        )

    def run(self, tree, src, path):
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Call) and self._is_jit_expr(node):
                hit = node
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and any(
                self._is_jit_expr(d) for d in node.decorator_list
            ):
                hit = node
            if hit is not None and self._in_loop(hit):
                yield self.finding(
                    path, hit,
                    "jax.jit/pjit constructed inside a loop — every "
                    "iteration re-traces (and on TPU re-compiles, ~40 s); "
                    "build the jitted fn once outside, or use a cached "
                    "factory like make_step_fns",
                )


class PsumReplicatedFlagRule(Rule):
    """No ``psum`` of a value that is already psum-replicated.

    The multi-process drivers depend on replicated decision flags: the
    shuffle step fns psum their overflow counters exactly once
    (``_chip_shuffle_tail``), after which every chip holds the identical
    global total and any process reads ONE local shard
    (``make_mh_shuffle_step_fns`` contract, parallel/shuffle.py). Psumming
    such a value again multiplies it by the axis size — a replay flag that
    should read 1 reads D, and on a flag compared ``== 0`` the bug is
    silent until a skewed input makes every process disagree about a
    replay. Encodes the PR 3 ROADMAP leftover ("psum-replicated-flag
    misuse in multi-process drivers") as a rule instead of a review note.

    Precision: fires only on (a) a ``psum`` call whose argument subtree
    contains another ``psum`` call, and (b) ``psum(x, ...)`` where ``x``
    was assigned from a ``psum`` call in the same function scope. A single
    psum of per-chip values — the shipped pattern — never matches.
    """

    name = "psum-replicated-flag"
    summary = "no psum of an already-psum-replicated value (multiplies by D)"

    def _is_psum(self, node) -> bool:
        return isinstance(node, ast.Call) and \
            _last_segment(qualname(node.func)) == "psum"

    def run(self, tree, src, path):
        scopes = [tree] + [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            yield from self._scan_scope(scope, path)

    def _own_nodes(self, scope):
        body = scope.body if isinstance(
            scope, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef)
        ) else [scope]
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scope gets its own pass
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _scan_scope(self, scope, path):
        # name → line numbers where it was ASSIGNED from a psum call. The
        # match below requires a strictly earlier definition line, so the
        # common rebinding idiom `x = psum(x, AXIS)` — a single psum whose
        # argument is the pre-assignment (per-chip) value — never fires.
        def_lines: dict[str, list[int]] = {}
        for n in self._own_nodes(scope):
            if isinstance(n, ast.Assign) and self._is_psum(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        def_lines.setdefault(t.id, []).append(n.lineno)
            elif isinstance(n, ast.AnnAssign) and n.value is not None \
                    and self._is_psum(n.value) and isinstance(n.target, ast.Name):
                def_lines.setdefault(n.target.id, []).append(n.lineno)
        for n in self._own_nodes(scope):
            if not self._is_psum(n):
                continue
            inner = next(
                (s for a in n.args for s in ast.walk(a) if self._is_psum(s)),
                None,
            )
            if inner is not None:
                yield self.finding(
                    path, n,
                    "psum of a psum result multiplies the total by the axis "
                    "size — the inner psum already replicated it to every "
                    "chip; read one shard instead",
                )
                continue
            for a in n.args:
                for s in ast.walk(a):
                    if isinstance(s, ast.Name) and any(
                        line < n.lineno for line in def_lines.get(s.id, ())
                    ):
                        yield self.finding(
                            path, n,
                            f"{s.id!r} is already a psum-replicated value — "
                            "psumming it again multiplies the flag by the "
                            "axis size (a replay flag compared == 0 then "
                            "lies); psum the per-chip value exactly once "
                            "and read one shard (make_mh_shuffle_step_fns "
                            "contract)",
                        )
                        break
                else:
                    continue
                break


class UnboundedRetryRule(Rule):
    """Retry/poll loops must back off, bound their attempts, or carry a
    stop condition.

    Incident: ISSUE 6 piece 3 — the RPC plane's retry loops slept a fixed
    constant forever: the worker's connect retry hammered a coming-up
    coordinator at a fixed rate (thundering herd on restart), and a
    constant-sleep failure loop can busy-hammer a struggling peer while
    never surfacing the real error. The fix is runtime/backoff.Backoff
    (jittered exponential, cap, budget); this rule keeps constant-sleep
    retry loops from coming back.

    Precision: fires only on ``while True`` loops (a real loop condition
    IS a stop condition) that sleep a non-growing delay — a literal, or a
    name/attribute never reassigned inside the loop; a delay produced by
    any call (``backoff.next_delay()``, ``min(...)``) is assumed to grow
    and stays silent. Two shapes fire: (a) the constant sleep sits on an
    except-handler retry path with no raise/break/return bounding it
    anywhere in the loop; (b) the loop has no exit statement at all.
    Bounded ``for attempt in range(n)`` retries never match (not a While).
    """

    name = "unbounded-retry"
    summary = "no constant-sleep retry/poll loops without backoff, cap, or stop condition"

    def run(self, tree, src, path):
        for node in ast.walk(tree):
            if not isinstance(node, ast.While):
                continue
            if not (isinstance(node.test, ast.Constant)
                    and node.test.value is True):
                continue  # the loop test is a stop condition
            yield from self._check_loop(node, path)

    def _check_loop(self, loop, path):
        body_nodes = [n for stmt in loop.body for n in ast.walk(stmt)]
        sleeps = [
            n for n in body_nodes
            if isinstance(n, ast.Call)
            and _last_segment(qualname(n.func)) == "sleep"
        ]
        if not sleeps:
            return
        assigned: set[str] = set()
        for n in body_nodes:
            targets = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            elif isinstance(n, (ast.For, ast.AsyncFor)):
                targets = [n.target]
            for t in targets:
                q = qualname(t)
                if q:
                    assigned.add(q)

        def is_constant_delay(call: ast.Call) -> bool:
            if not call.args:
                return False
            arg = call.args[0]
            if isinstance(arg, ast.Constant):
                return True
            if isinstance(arg, (ast.Name, ast.Attribute)):
                # Never reassigned in the loop → the delay cannot grow.
                return qualname(arg) not in assigned
            return False  # computed (a call, arithmetic): assume it grows

        const_sleeps = [c for c in sleeps if is_constant_delay(c)]
        if not const_sleeps:
            return
        has_raise = any(isinstance(n, ast.Raise) for n in body_nodes)
        for h in (n for n in body_nodes if isinstance(n, ast.ExceptHandler)):
            h_nodes = list(ast.walk(h))
            h_sleeps = [c for c in const_sleeps if any(c is n for n in h_nodes)]
            if not h_sleeps:
                continue
            if has_raise or any(
                isinstance(n, (ast.Break, ast.Return)) for n in h_nodes
            ):
                continue  # bounded: attempts surface an error or exit
            yield self.finding(
                path, h_sleeps[0],
                "constant-sleep retry in a `while True` loop — failures "
                "retry forever at a fixed rate (thundering herd, and the "
                "real error never surfaces); use runtime/backoff.Backoff "
                "(jittered exponential with cap and budget) or bound the "
                "attempts",
            )
            return
        if not any(
            isinstance(n, (ast.Break, ast.Return, ast.Raise))
            for n in body_nodes
        ):
            yield self.finding(
                path, const_sleeps[0],
                "`while True` poll loop sleeping a constant with no exit "
                "(no break/return/raise) and no backoff — give it a stop "
                "condition, or draw delays from runtime/backoff.Backoff",
            )


class MetricInHotLoopRule(Rule):
    """No metric mutations or wall-clock sampling inside the known
    per-record hot loops.

    The observability doctrine (runtime/metrics.py) allows per-window and
    per-round telemetry but forbids per-record work — the reference's one
    log line *per emitted KV pair* is the founding counter-example, and
    ISSUE 8's live registry makes the mistake easy to re-introduce: a
    registry ``inc`` is a lock acquire + dict update, ``record_hist`` is
    a bisect, and ``time.time()`` is a syscall-class read; any of them
    inside the scan fold or the a2a pack loop multiplies by the record
    rate. The sampler exists precisely so these loops never need their
    own instruments — they tick ``metrics_tick()`` once per window and
    the registry pulls aggregates.

    Precision: fires only inside ``for``/``while`` loops of the named
    hot-loop scopes (the scan-fold and pack functions:
    ``fold_scan_into_dictionary``, ``_pack_update``, ``_fold``,
    ``add_scanned_raw``, ``_insert_hashed``). Three shapes match: (a)
    wall-clock sampling (``time.time``/``perf_counter``/``monotonic``);
    (b) mutations of a registry instrument — a call chained off
    ``counter()``/``gauge()``/``histogram()``, a name assigned from one
    in the same scope, or a mutator on a receiver whose qualname mentions
    ``metric``/``registry``; (c) ``record_hist``/``metrics_tick``/
    ``maybe_sample``/``ship_sample`` calls. The same calls OUTSIDE the
    loops (per-window accounting after the fold) never match.
    """

    name = "metric-in-hot-loop"
    summary = "no metric mutations / time sampling in per-record hot loops"

    HOT_SCOPES = (
        "fold_scan_into_dictionary",  # scan fold: native scan → dictionary
        "_pack_update",               # a2a/merge pack: rows → padded update
        "_fold",                      # HostAccumulator spill fold
        "add_scanned_raw",            # dictionary per-token insert pass
        "_insert_hashed",             # dictionary hashed-word insert loop
    )
    _CLOCKS = ("time", "perf_counter", "monotonic")
    _MUTATORS = ("inc", "observe", "set", "set_total", "set_hist")
    _FACTORIES = ("counter", "gauge", "histogram")
    _TICKS = ("record_hist", "metrics_tick", "maybe_sample", "ship_sample")

    def run(self, tree, src, path):
        for scope in ast.walk(tree):
            if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if scope.name not in self.HOT_SCOPES:
                continue
            yield from self._scan_scope(scope, path)

    def _own_nodes(self, scope):
        stack = list(scope.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scope: not this hot loop's body
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _instrument_names(self, scope) -> set[str]:
        """Names assigned from a registry factory call in this scope —
        ``h = registry.histogram("x")`` makes ``h.observe`` a mutation."""
        out: set[str] = set()
        for n in self._own_nodes(scope):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and _last_segment(qualname(n.value.func)) in self._FACTORIES:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def _is_metric_mutation(self, call: ast.Call, instruments: set) -> bool:
        if not isinstance(call.func, ast.Attribute):
            return False
        if call.func.attr not in self._MUTATORS:
            return False
        recv = call.func.value
        # Chained off a factory: registry.counter("x").inc(...)
        if isinstance(recv, ast.Call) and \
                _last_segment(qualname(recv.func)) in self._FACTORIES:
            return True
        # A name bound from a factory in this scope.
        if isinstance(recv, ast.Name) and recv.id in instruments:
            return True
        # Receiver path names the registry (self.metrics.…, registry.…) —
        # conservative textual hint, scoped to the mutator verbs above.
        q = qualname(recv).lower()
        return "metric" in q or "registry" in q

    def _is_clock(self, call: ast.Call) -> bool:
        q = qualname(call.func)
        if q == "time.time" or q.endswith(".time.time"):
            return True
        # perf_counter/monotonic are unambiguous in any spelling (bare
        # from-import or module-qualified); a bare `time()` is not — it
        # could be anything, so only the module-qualified form fires.
        return _last_segment(q) in ("perf_counter", "monotonic")

    def _scan_scope(self, scope, path):
        instruments = self._instrument_names(scope)
        seen: set[int] = set()
        for loop in self._own_nodes(scope):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for n in ast.walk(loop):
                if not isinstance(n, ast.Call) or id(n) in seen:
                    continue
                seen.add(id(n))
                last = _last_segment(qualname(n.func))
                if self._is_clock(n):
                    yield self.finding(
                        path, n,
                        f"wall-clock sampling ({qualname(n.func)}) inside "
                        f"the {scope.name!r} hot loop runs per record — "
                        "time once per window outside the loop, or let the "
                        "registry sampler (metrics_tick at the window "
                        "sites) carry the series",
                    )
                elif last in self._TICKS:
                    yield self.finding(
                        path, n,
                        f"{last!r} inside the {scope.name!r} hot loop runs "
                        "per record (a histogram add is a bisect, a "
                        "sampler tick is a clock read + compare) — move it "
                        "after the loop; the per-window sites already tick "
                        "the sampler",
                    )
                elif self._is_metric_mutation(n, instruments):
                    yield self.finding(
                        path, n,
                        f"registry instrument mutation inside the "
                        f"{scope.name!r} hot loop — a lock acquire + dict "
                        "update per record is the reference's per-KV log "
                        "line all over again; accumulate locally and "
                        "record once after the loop (the sampler pulls "
                        "aggregates)",
                    )


# ---------------------------------------------------------------------------
# Interprocedural program rules (the ISSUE 7 dataflow layer)
class NakedClockInControlPlaneRule(Rule):
    """No direct ``time.monotonic()`` / ``time.time()`` calls inside the
    control-plane state machines.

    Incident: mrmodel (ISSUE 18) explores the real Coordinator/JobService
    under a virtual clock — the whole point is that no model rewrite can
    drift from the shipped logic. That only holds while every wall-clock
    read in those classes routes through the injectable ``self._now``
    seam: one naked ``time.monotonic()`` and model time and real time
    disagree mid-schedule, so lease expiry explores a state the cluster
    can never reach (or misses one it can). The seam ASSIGNMENT
    (``self._now = now if now is not None else time.monotonic``) is a
    function reference, not a call, and stays legal; ``time.perf_counter``
    latency stamps are measurement, not scheduling, and are out of scope.
    """

    name = "naked-clock-in-control-plane"
    summary = ("control-plane classes read the clock via the _now seam, "
               "never time.monotonic()/time.time() directly")

    #: The classes mrmodel drives under a virtual clock — plus any class
    #: that publishes an RPC ``_METHODS`` table (a control-plane surface
    #: by construction, whatever it is named).
    _CONTROL_CLASSES = frozenset({
        "Coordinator", "JobService", "_Phase", "JobReport",
        "Worker", "ServiceWorker",
    })
    _CLOCKS = frozenset({"monotonic", "time"})

    def _from_imports(self, tree) -> dict[str, str]:
        out: dict[str, str] = {}
        for n in ast.walk(tree):
            if isinstance(n, ast.ImportFrom) and n.module:
                for alias in n.names:
                    out[alias.asname or alias.name] = n.module
        return out

    @staticmethod
    def _defines_methods_table(cls: ast.ClassDef) -> bool:
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_METHODS"
                    for t in stmt.targets):
                return True
        return False

    def _is_naked_clock(self, call: ast.Call, from_imports) -> "str | None":
        q = qualname(call.func)
        if not q:
            return None
        last = _last_segment(q)
        if last not in self._CLOCKS:
            return None
        if q == f"time.{last}" or q.endswith(f".time.{last}"):
            return f"time.{last}"
        if q == last and from_imports.get(last) == "time":
            return f"time.{last}"
        return None

    def run(self, tree, src, path):
        from_imports = self._from_imports(tree)
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if cls.name not in self._CONTROL_CLASSES \
                    and not self._defines_methods_table(cls):
                continue
            for call in ast.walk(cls):
                if not isinstance(call, ast.Call):
                    continue
                clock = self._is_naked_clock(call, from_imports)
                if clock is None:
                    continue
                yield self.finding(
                    path, call,
                    f"{clock}() called directly inside control-plane "
                    f"class {cls.name} — route the read through the "
                    "injectable clock seam (self._now()) so mrmodel's "
                    "virtual-clock exploration drives the same code the "
                    "cluster runs; keep a bare time.monotonic only as "
                    "the seam's default REFERENCE, never a call",
                )


# ---------------------------------------------------------------------------


class ProgramRule(Rule):
    """A rule that runs once over the whole linted file set with the
    dataflow layer (analysis/dataflow.py): CFG + reaching definitions per
    function, and a package call graph so a value or a hazard can be
    followed across frames. Findings land on their file and obey the same
    inline ignores and baseline as per-file findings."""

    def run_program(self, program) -> Iterator[Finding]:
        raise NotImplementedError

    def check(self, tree, src, path):  # pragma: no cover - program-only
        return []


def _call_chain(path_frames) -> str:
    """Render a call path as ``a -> b -> c`` for finding messages."""
    return " -> ".join(fu.qualname for fu, _call in path_frames)


class BlockingInAsyncRule(ProgramRule):
    """No blocking calls reachable inside ``async def`` — directly or
    through sync helper frames.

    Incident: the renewal/backoff loops live on the event loop; a single
    ``time.sleep`` (or a subprocess wait) anywhere in their call closure
    starves EVERY coroutine in the process — renewals stop, leases expire
    under live tasks, and the failure reads as a distributed timing bug
    instead of the local blocking call it is. The chaos sites dodge this
    only because task bodies run in the executor (``run_in_executor``),
    which is exactly the boundary this rule understands: callables merely
    PASSED to an executor sink are not async-context callees.
    """

    name = "blocking-in-async"
    summary = "no time.sleep/subprocess/socket waits reachable from async def"

    #: qualname -> why it blocks. Bare last-segment matches are accepted
    #: only for names that unambiguously come from these modules
    #: (from-import detection below).
    _BLOCKING_ROOTS = {
        "time": {"sleep"},
        "subprocess": {"run", "call", "check_call", "check_output", "Popen"},
        "os": {"system", "wait", "waitpid"},
        "socket": {"create_connection"},
        "urllib.request": {"urlopen"},
    }

    def _from_imports(self, tree) -> dict[str, str]:
        """bare name -> source module, for ``from time import sleep``."""
        out: dict[str, str] = {}
        for n in ast.walk(tree):
            if isinstance(n, ast.ImportFrom) and n.module:
                for alias in n.names:
                    out[alias.asname or alias.name] = n.module
        return out

    def _is_blocking(self, call, from_imports) -> "str | None":
        q = qualname(call.func)
        if not q:
            return None
        last = _last_segment(q)
        for root, names in self._BLOCKING_ROOTS.items():
            if last not in names:
                continue
            if q == f"{root}.{last}" or q.endswith(f".{root}.{last}"):
                return f"{root}.{last}"
            if q == last and from_imports.get(last) == root:
                return f"{root}.{last}"
        return None

    def run_program(self, program):
        from_imports_by_path: dict[str, dict] = {}
        for path, tree in program.files:
            from_imports_by_path[path] = self._from_imports(tree)
        seen: set[tuple[str, int]] = set()
        for root in program.functions:
            if not root.is_async:
                continue
            frames = [(root, [])] + program.reachable(root)
            for fu, chain in frames:
                imports = from_imports_by_path.get(fu.path, {})
                for call, _target in program.callees(fu):
                    blocked = self._is_blocking(call, imports)
                    if blocked is None:
                        continue
                    key = (fu.path, getattr(call, "lineno", 0))
                    if key in seen:
                        continue  # one finding per site, however many
                    seen.add(key)  # async roots reach it
                    via = (
                        f" via {_call_chain(chain)} -> {fu.qualname}"
                        if chain else ""
                    )
                    yield self.finding(
                        fu.path, call,
                        f"{blocked!r} reached inside async def "
                        f"{root.qualname}{via} — a blocking call on the "
                        "event loop starves every coroutine (renewals "
                        "stop, leases expire under live tasks); await "
                        "asyncio.sleep, or move the work to "
                        "run_in_executor",
                    )


class BackendInitInProbeRule(ProgramRule):
    """Telemetry probes must not initialize a jax backend.

    Incident: PR 6's worker device-memory gauge called
    ``jax.local_devices()`` from the task loop; on a process whose
    backend was NOT yet initialized that call *triggers* backend init — a
    ~minutes-long metadata probe against an absent accelerator that
    wedged the worker. The fix gates the gauge on
    ``jax._src.xla_bridge._backends`` (already-initialized check). This
    rule walks every probe-named function (``sample``/``probe``/
    ``gauge``/``platform_info`` — the repo's telemetry naming convention)
    and its sync call closure: any path to ``jax.devices()`` /
    ``jax.local_devices()`` / ``memory_stats()`` must be dominated by a
    ``_backends`` guard, at the device call or at the call site leading
    to it (branch-sensitive: the ``if not _backends: return`` early exit
    counts, including inside try/except).
    """

    name = "backend-init-in-probe"
    summary = "telemetry probes gate device access on xla_bridge._backends"

    _PROBE = ("sample", "probe", "gauge", "platform_info")
    _DEVICE = ("local_devices", "devices", "memory_stats")

    def _is_probe(self, fu) -> bool:
        low = fu.name.lower()
        return any(p in low for p in self._PROBE)

    def _device_calls(self, program, fu):
        for call, _t in program.callees(fu):
            if _last_segment(qualname(call.func)) in self._DEVICE:
                yield call

    def run_program(self, program):
        from mapreduce_rust_tpu.analysis.dataflow import guarded_reach

        seen: set[tuple[str, int]] = set()
        for root in program.functions:
            if not self._is_probe(root):
                continue
            frames = [(root, [])] + program.reachable(root)
            for fu, chain in frames:
                for call in self._device_calls(program, fu):
                    if guarded_reach(fu.cfg, call, "_backends"):
                        continue
                    # A hop guarded at its CALL SITE covers the callee:
                    # the probe checked before descending.
                    if any(
                        guarded_reach(src.cfg, site, "_backends")
                        for src, site in chain
                    ):
                        continue
                    key = (fu.path, getattr(call, "lineno", 0))
                    if key in seen:
                        continue
                    seen.add(key)
                    via = (
                        f" (reached from probe {root.qualname} via "
                        f"{_call_chain(chain)})" if chain else ""
                    )
                    yield self.finding(
                        fu.path, call,
                        f"{qualname(call.func)!r} in telemetry probe "
                        f"{root.qualname}{via} without the "
                        "xla_bridge._backends guard — on an uninitialized "
                        "process this CALL initializes the backend (a "
                        "~minutes metadata probe against an absent "
                        "accelerator wedged a worker, PR 6); check "
                        "`if not xla_bridge._backends: return` first",
                    )


class NondeterministicPartitionRule(ProgramRule):
    """No unordered-set iteration flowing into partition/shard indexing.

    The framework's headline invariant is BIT-IDENTICAL outputs — for any
    worker count, any recovery path, any speculation race. Iterating a
    ``set`` (hash-randomized for str keys) while computing a partition or
    shard index makes the spill ROW ORDER depend on interpreter hash
    state: two attempts of one task then write permuted rows, and the
    "outputs identical" oracle fails only on the rerun nobody can
    reproduce. The shipped pattern sorts first (``for d in sorted(v)``,
    worker/runtime.py); this rule follows values through reaching
    definitions (``pending = seen; for d in pending: ...``) so an alias
    can't hide the set. Dict iteration is insertion-ordered on every
    supported interpreter and deliberately does not fire.
    """

    name = "nondeterministic-partition-input"
    summary = "sort set-typed values before they feed partition/shard indexing"

    _PART_HINT = ("reduce_n", "partition", "shard", "n_part", "nparts",
                  "parts", "buckets")

    def _is_set_expr(self, expr) -> bool:
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return True
        if isinstance(expr, ast.Call) and \
                _last_segment(qualname(expr.func)) in ("set", "frozenset"):
            return True
        return False

    def _partitionish(self, node) -> bool:
        """Does a subtree compute a partition/shard index? ``x % NAME``
        with a partition-hinted NAME, or a subscript into one."""
        for n in ast.walk(node):
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod):
                names = " ".join(
                    q for q in (qualname(n.right), qualname(n.left)) if q
                ).lower()
                if any(h in names for h in self._PART_HINT):
                    return True
            if isinstance(n, ast.Subscript):
                if any(h in qualname(n.value).lower()
                       for h in self._PART_HINT):
                    return True
        return False

    def run_program(self, program):
        from mapreduce_rust_tpu.analysis.dataflow import origins

        for fu in program.functions:
            defs = reach = None
            for n in program._own_walk(fu.node):
                if not isinstance(n, (ast.For, ast.AsyncFor)):
                    continue
                it = n.iter
                set_like = self._is_set_expr(it)
                if not set_like and isinstance(it, ast.Name):
                    if defs is None:
                        defs, reach = fu.rd
                    set_like = any(
                        o is not None and self._is_set_expr(o)
                        for o in origins(fu.cfg, defs, reach, it)
                    )
                if not set_like:
                    continue
                if not (self._partitionish(n) or self._partitionish(it)):
                    continue
                yield self.finding(
                    fu.path, n,
                    "iterating an unordered set into a partition/shard "
                    "index — row order then depends on interpreter hash "
                    "state and two attempts of one task write permuted "
                    "spills, breaking the bit-identical-outputs "
                    "invariant; iterate sorted(...) instead",
                )


class CrossShardFoldRule(ProgramRule):
    """No fold mutations into a DIFFERENT shard's dictionary (rule 12).

    The sharded egress fold (ISSUE 9) holds exactly one invariant the
    sanitizer can only check at runtime: a function that was handed shard
    index ``i`` folds into shard ``i``'s dictionary and no other — a
    ``shards[j]`` mutation with a foreign index splits one key's dedup and
    collision state across two dictionaries, and the corruption is silent
    until an egress diff. This rule checks it statically: inside any
    function with a shard-index parameter (``shard``/``shard_idx``/
    ``shard_index``/``shard_i``/``s`` — the fold plane's naming), a
    dictionary mutator (``add_scanned_raw``/``add_scanned``/``add_words``/
    ``add_text``/``merge``) whose receiver is — or aliases, via reaching
    definitions (the PR 7 dataflow layer) — a subscript into a
    shard container (any name mentioning ``shard``) must index it with an
    expression that MENTIONS the shard parameter. The same applies to a
    ``shards[j]`` handed straight to a ``fold``-named helper (the
    one-call-hop shape ``fold_into(self.shards[j], ...)``). Precision over
    recall: ``shards[s]``, aliases of it, and receivers that arrive as
    plain parameters stay silent.
    """

    name = "cross-shard-fold"
    summary = "a shard-indexed function folds only into its own shard"

    _MUTATORS = ("add_scanned_raw", "add_scanned", "add_words", "add_text",
                 "merge")
    _IDX_PARAMS = ("shard", "shard_idx", "shard_index", "shard_i", "s")

    def _shard_param(self, fu) -> "str | None":
        a = fu.node.args
        for arg in a.posonlyargs + a.args + a.kwonlyargs:
            if arg.arg in self._IDX_PARAMS:
                return arg.arg
        return None

    @staticmethod
    def _shard_subscript(expr) -> "ast.Subscript | None":
        if isinstance(expr, ast.Subscript) \
                and "shard" in qualname(expr.value).lower():
            return expr
        return None

    @staticmethod
    def _mentions_param(expr, param: str) -> bool:
        return any(
            isinstance(n, ast.Name) and n.id == param for n in ast.walk(expr)
        )

    def run_program(self, program):
        from mapreduce_rust_tpu.analysis.dataflow import origins

        for fu in program.functions:
            param = self._shard_param(fu)
            if param is None:
                continue
            defs = reach = None
            for n in program._own_walk(fu.node):
                if not isinstance(n, ast.Call):
                    continue
                if isinstance(n.func, ast.Attribute) \
                        and n.func.attr in self._MUTATORS:
                    recv = n.func.value
                    subs = []
                    direct = self._shard_subscript(recv)
                    if direct is not None:
                        subs.append(direct)
                    elif isinstance(recv, ast.Name):
                        if defs is None:
                            defs, reach = fu.rd
                        for o in origins(fu.cfg, defs, reach, recv):
                            so = (
                                self._shard_subscript(o)
                                if o is not None else None
                            )
                            if so is not None:
                                subs.append(so)
                    for sub in subs:
                        if not self._mentions_param(sub.slice, param):
                            yield self.finding(
                                fu.path, n,
                                f"{fu.qualname} received shard index "
                                f"{param!r} but mutates a shard dictionary "
                                "selected by a different index — one key's "
                                "dedup/collision state would silently "
                                "split across two shard dictionaries; fold "
                                f"only into the shard {param!r} names "
                                "(cross-shard work goes back through the "
                                "router)",
                            )
                            break
                    continue
                # One-call-hop shape: shards[j] handed to a fold helper.
                if "fold" not in _last_segment(qualname(n.func)).lower():
                    continue
                for arg in n.args:
                    sub = self._shard_subscript(arg)
                    if sub is not None \
                            and not self._mentions_param(sub.slice, param):
                        yield self.finding(
                            fu.path, n,
                            f"{fu.qualname} received shard index {param!r} "
                            "but hands a DIFFERENT shard's dictionary to a "
                            "fold helper — the callee will mutate a shard "
                            "this thread does not own (cross-shard-fold)",
                        )
                        break


class BlockingIoInFoldRule(ProgramRule):
    """No file I/O reachable from the fold/consumer hot scopes (rule 13).

    The binary async spill plane (ISSUE 11) exists because
    ``Dictionary._flush_words`` used to sort and WRITE the run file
    inline on the fold/consumer thread — a 15x throughput collapse on the
    spill-engaged Zipf leg that three PRs of telemetry had to find. The
    invariant this rule pins: the fold-side hot scopes (the fold-plane
    thread body, the host-map consumer, the dictionary/accumulator fold
    mutators) hand frozen snapshots to the async writer
    (``AsyncSpillWriter.submit`` — an executor sink, so the handed task
    is invisible to the call graph by design) and never ``open``/
    ``.write``/``.flush``/``np.save`` a file themselves, directly or
    through sync helper frames. Throttled telemetry ticks
    (``maybe_snapshot``/``metrics_tick`` — the flight recorder and the
    metrics sampler own their budgets) are the sanctioned exceptions.
    """

    name = "blocking-io-in-fold"
    summary = "fold/consumer hot scopes do file I/O only via the async writer"

    #: The fold/consumer hot scopes, by the runtime's naming: the fold
    #: plane's per-shard body, the host-map consumer, and every
    #: dictionary/accumulator fold mutator the stream loops call per
    #: window. A rename there must update this list (the fixtures gate it).
    _HOT = (
        "_fold_one", "consume", "fold_scan_into_dictionary",
        "add_scanned_raw", "add_scanned", "add_words", "_insert_hashed",
        "_maybe_flush", "_flush_words", "add_batch", "_flush_run",
    )
    #: Direct file-I/O producers (builtin/module function calls).
    _IO_FUNCS = {
        "open": ("", "io", "os", "gzip", "bz2", "lzma"),
        "save": ("np", "numpy"),
        "savez": ("np", "numpy"),
        "replace": ("os",),
        "rename": ("os",),
        "copyfileobj": ("shutil",),
    }
    #: Methods that write a file handle (receiver must ORIGINATE from an
    #: open() call — reaching defs — or the method stays silent: .write on
    #: buffers/sockets/tracers is not this rule's business).
    _FILE_METHODS = ("write", "flush", "writelines")
    #: Frames whose presence in the chain sanctions the I/O below them:
    #: the flight recorder / metrics sampler ticks are throttled by
    #: contract (their own modules own that budget), and a plane ``submit``
    #: handoff (AsyncSpillWriter / _DispatchPlane) makes everything below
    #: it the plane's business — its sync mode runs the same frames inline
    #: as an explicit opt-in debug/measurement path, not a fold-thread
    #: regression (the rule-14 doctrine, shared).
    _EXEMPT_FRAMES = ("maybe_snapshot", "metrics_tick", "submit")

    def _io_call(self, call) -> "str | None":
        q = qualname(call.func)
        if not q:
            return None
        last = _last_segment(q)
        roots = self._IO_FUNCS.get(last)
        if roots is None:
            return None
        for root in roots:
            if root == "" and q == last:
                return last
            if root and (q == f"{root}.{last}" or q.endswith(f".{root}.{last}")):
                return f"{root}.{last}"
        return None

    @staticmethod
    def _origin_is_open(o) -> bool:
        return (
            isinstance(o, ast.Call)
            and _last_segment(qualname(o.func)) == "open"
        )

    def run_program(self, program):
        from mapreduce_rust_tpu.analysis.dataflow import origins

        seen: set[tuple[str, int]] = set()
        for root in program.functions:
            if root.name not in self._HOT:
                continue
            frames = [(root, [])] + program.reachable(root)
            for fu, chain in frames:
                if fu.name in self._EXEMPT_FRAMES or any(
                    src.name in self._EXEMPT_FRAMES for src, _call in chain
                ):
                    continue
                defs = reach = None
                for call, _target in program.callees(fu):
                    hit = self._io_call(call)
                    if hit is None and isinstance(call.func, ast.Attribute) \
                            and call.func.attr in self._FILE_METHODS:
                        recv = call.func.value
                        if self._origin_is_open(recv):
                            hit = f"file.{call.func.attr}"
                        elif isinstance(recv, ast.Name):
                            if defs is None:
                                defs, reach = fu.rd
                            if any(
                                self._origin_is_open(o)
                                for o in origins(fu.cfg, defs, reach, recv)
                            ):
                                hit = f"file.{call.func.attr}"
                    if hit is None:
                        continue
                    key = (fu.path, getattr(call, "lineno", 0))
                    if key in seen:
                        continue
                    seen.add(key)
                    via = (
                        f" via {_call_chain(chain)} -> {fu.qualname}"
                        if chain else ""
                    )
                    yield self.finding(
                        fu.path, call,
                        f"{hit!r} reached from fold/consumer hot scope "
                        f"{root.qualname}{via} without going through the "
                        "async spill-writer handoff — inline file I/O on "
                        "the fold thread was the spill-engaged Zipf leg's "
                        "15x collapse (ISSUE 11); freeze a snapshot and "
                        "AsyncSpillWriter.submit it instead",
                    )


class DeviceDispatchInConsumerRule(ProgramRule):
    """No device dispatch reachable from the consume/fold hot scopes
    (rule 14).

    The dispatch plane (ISSUE 13) exists because the host-map consumer
    used to scatter, pack, ``jax.device_put`` and invoke the compiled
    packed merge INLINE per window — ~13 s of the 24 s Zipf leg booked as
    host-glue after PR 10 moved everything else off the router. The
    invariant this rule pins (mirroring rule 13's spill contract): the
    router-side hot scopes (the host-map consumer, the fold-plane thread
    body, the dictionary fold mutators) hand windows to the dispatch
    plane (``_DispatchPlane.submit`` — the sanctioned sink frame) and
    never reach ``jax.device_put`` or a merge function produced by
    ``make_packed_merge_fn`` themselves, directly or through sync helper
    frames. Chains that pass the plane's ``submit`` are the plane's own
    sync mode — sanctioned by design (that IS the A/B debug path);
    throttled telemetry ticks stay exempt like rule 13.
    """

    name = "device-dispatch-in-consumer"
    summary = "consume/fold hot scopes dispatch device work only via the plane"

    #: Router-side hot scopes, by the runtime's naming (a rename there
    #: must update this list — the fixtures gate the semantics).
    _HOT = (
        "consume", "_fold_one", "fold_scan_into_dictionary",
        "add_scanned_raw", "add_scanned", "add_words", "_insert_hashed",
        "route_raw", "route_list",
    )
    #: Device-hop producers: the transfer call by qualname, and any call
    #: through a name that ORIGINATES from make_packed_merge_fn (reaching
    #: defs — `merge_packed = make_packed_merge_fn(...); merge_packed(...)`).
    _DEVICE_FUNCS = ("device_put",)
    _MERGE_FACTORY = "make_packed_merge_fn"
    #: Frames whose presence sanctions the dispatch below them: the
    #: dispatch plane's submit handoff (its sync mode runs the same code
    #: inline — that is the measurement plane, not a violation), plus the
    #: throttled telemetry ticks rule 13 also exempts.
    _EXEMPT_FRAMES = ("submit", "maybe_snapshot", "metrics_tick")

    def _device_call(self, call, fu, defs_reach) -> "str | None":
        q = qualname(call.func)
        if q and _last_segment(q) in self._DEVICE_FUNCS:
            return q
        # A call THROUGH a packed-merge closure: receiver name originates
        # from a make_packed_merge_fn(...) call via reaching definitions.
        if isinstance(call.func, ast.Name):
            from mapreduce_rust_tpu.analysis.dataflow import origins

            defs, reach = defs_reach()
            for o in origins(fu.cfg, defs, reach, call.func):
                if (
                    isinstance(o, ast.Call)
                    and _last_segment(qualname(o.func)) == self._MERGE_FACTORY
                ):
                    return f"{self._MERGE_FACTORY}(...) result"
        return None

    def run_program(self, program):
        seen: set[tuple[str, int]] = set()
        for root in program.functions:
            if root.name not in self._HOT:
                continue
            frames = [(root, [])] + program.reachable(root)
            for fu, chain in frames:
                if fu.name in self._EXEMPT_FRAMES or any(
                    src.name in self._EXEMPT_FRAMES for src, _call in chain
                ):
                    continue
                cache: list = []

                def defs_reach(fu=fu, cache=cache):
                    if not cache:
                        cache.append(fu.rd)
                    return cache[0]

                for call, _target in program.callees(fu):
                    hit = self._device_call(call, fu, defs_reach)
                    if hit is None:
                        continue
                    key = (fu.path, getattr(call, "lineno", 0))
                    if key in seen:
                        continue
                    seen.add(key)
                    via = (
                        f" via {_call_chain(chain)} -> {fu.qualname}"
                        if chain else ""
                    )
                    yield self.finding(
                        fu.path, call,
                        f"{hit!r} reached from consume/fold hot scope "
                        f"{root.qualname}{via} without going through the "
                        "dispatch-plane submit handoff — an inline device "
                        "hop on the router thread was the ~13s host-glue "
                        "wall of the Zipf leg (ISSUE 13); hand the window "
                        "to _DispatchPlane.submit instead",
                    )


class UnsampledRangePartitionRule(ProgramRule):
    """Range-partition calls must consume SAMPLER-derived splitters
    (rule 15).

    The workload plane's global-sort contract (ISSUE 15) has two legs:
    partition order is key order, and every re-execution derives the SAME
    splitters. Both die the moment a call site hands
    ``range_partition``/``bucket_scatter(mode="range")`` an ad-hoc
    splitter array: a literal (or a name assigned from one) is divorced
    from the corpus distribution — partitions silently skew — and any
    non-shared derivation can disagree between a task and its recovery
    attempt, routing one key to two partitions (the mrcheck-invisible
    corruption: both attempts "succeed"). Legitimate splitters flow from
    exactly two places: the shared sampler (runtime/splitter.py —
    ``derive_splitters``/``corpus_splitters``/``splitters_for_job``) or
    an app's bound ``.splitters`` attribute, which only
    ``splitter.prepare_app`` writes. This rule follows the splitters
    argument through reaching definitions and flags literal-container
    provenance; values it cannot resolve (parameters, foreign calls)
    stay silent — precision over recall, per the module doctrine.
    """

    name = "unsampled-range-partition"
    summary = "range-partition splitters must come from the shared sampler"

    #: The sampler's producing functions (runtime/splitter.py) — the OK
    #: provenance, alongside a ``.splitters`` attribute read (bound-app).
    _SAMPLER_FUNCS = ("derive_splitters", "corpus_splitters",
                      "splitters_for_job")
    _RANGE_FUNCS = ("range_partition",)

    def _splitter_arg(self, call: ast.Call) -> "ast.expr | None":
        """The splitters expression of a range-partition call site."""
        seg = _last_segment(qualname(call.func))
        if seg in self._RANGE_FUNCS:
            kw = _kw(call, "splitters")
            if kw is not None:
                return kw
            return call.args[1] if len(call.args) > 1 else None
        if seg == "bucket_scatter":
            mode = _kw(call, "mode")
            if not (isinstance(mode, ast.Constant) and mode.value == "range"):
                return None  # hash mode: no splitters to audit
            return _kw(call, "splitters") or (
                call.args[4] if len(call.args) > 4 else None
            )
        return None

    def _provenance(self, expr) -> "str | None":
        """"ok" (sampler/bound-app mention), "literal" (container built
        in place), or None (unresolvable here)."""
        verdict = None
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) and \
                    _last_segment(qualname(n.func)) in self._SAMPLER_FUNCS:
                return "ok"
            if isinstance(n, ast.Attribute) and n.attr == "splitters":
                return "ok"  # the bound-app seam: prepare_app-written
            if isinstance(n, (ast.List, ast.Tuple, ast.Set, ast.ListComp)):
                verdict = "literal"
        return verdict

    def run_program(self, program):
        from mapreduce_rust_tpu.analysis.dataflow import origins

        for fu in program.functions:
            defs = reach = None
            for call, _target in program.callees(fu):
                arg = self._splitter_arg(call)
                if arg is None:
                    continue
                prov = self._provenance(arg)
                if prov is None and isinstance(arg, ast.Name):
                    if defs is None:
                        defs, reach = fu.rd
                    for o in origins(fu.cfg, defs, reach, arg):
                        p = self._provenance(o) if o is not None else None
                        if p == "ok":
                            prov = "ok"
                            break
                        if p == "literal":
                            prov = "literal"
                if prov != "literal":
                    continue
                yield self.finding(
                    fu.path, call,
                    "range partition fed ad-hoc literal splitters — "
                    "partitions then ignore the corpus distribution and "
                    "a re-executed task may derive DIFFERENT routing "
                    "than its first attempt; derive them with the shared "
                    "sampler (runtime/splitter.derive_splitters / "
                    "splitters_for_job, or the app's prepare_app-bound "
                    ".splitters)",
                )


class UnreapedJobLabelsRule(ProgramRule):
    """Per-job labeled metric series must have a reachable reap
    (rule 16).

    The multi-tenant service publishes ``job=<id>``-labeled gauges
    (phase progress, tenant attribution) — one labeled child per live
    job. Labels are an unbounded cardinality dimension: without a
    matching ``remove_labels(job=...)`` on the job's teardown path,
    every job that ever ran stays a live series forever, the Prometheus
    scrape body grows without bound, and the registry lock is held
    longer on every tick (the slow leak ISSUE 16's fleet plane would
    itself be built on). The contract: any CLASS whose methods write a
    mutator (``set``/``inc``/``observe``/``set_total``/``set_hist``)
    with a ``job=`` kwarg must also, somewhere in its method set or
    their sync call closure, call ``remove_labels``. Module-level
    functions stay silent — a free function has no teardown seam to
    anchor the reap to, and the repo's labeled writers are all
    class-owned ticks.
    """

    name = "unreaped-job-labels"
    summary = "job=-labeled metric writes need a reachable remove_labels reap"

    _MUTATORS = ("set", "inc", "observe", "set_total", "set_hist")

    def _job_label_sites(self, fu):
        """Mutator calls carrying a ``job=`` kwarg, by direct AST walk —
        qualname() cannot render call-containing receiver chains like
        ``self.registry.gauge(...).set(...)``, so the verb + kwarg shape
        is the detector."""
        for n in ast.walk(fu.node):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in self._MUTATORS
                and any(kw.arg == "job" for kw in n.keywords)
            ):
                yield n

    @staticmethod
    def _has_reap(fu) -> bool:
        return any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "remove_labels"
            for n in ast.walk(fu.node)
        )

    def run_program(self, program):
        by_class: dict[tuple, list] = {}
        for fu in program.functions:
            if "." not in fu.qualname:
                continue  # free function: no teardown seam to demand
            cls = fu.qualname.rsplit(".", 1)[0]
            by_class.setdefault((fu.path, cls), []).append(fu)
        for (path, cls), methods in sorted(by_class.items()):
            sites = [
                (fu, call) for fu in methods
                for call in self._job_label_sites(fu)
            ]
            if not sites:
                continue
            sanctioned = any(self._has_reap(fu) for fu in methods)
            if not sanctioned:
                for fu in methods:
                    if any(
                        self._has_reap(reached)
                        for reached, _chain in program.reachable(fu)
                    ):
                        sanctioned = True
                        break
            if sanctioned:
                continue
            fu, call = sites[0]
            yield self.finding(
                path, call,
                f"{cls} registers job=-labeled series "
                f"({len(sites)} write site(s)) but no method reaches "
                "remove_labels — every job that ever ran stays a live "
                "labeled child and the scrape body grows without bound; "
                "reap with registry.<instrument>.remove_labels(job=...) "
                "on the job's teardown path",
            )


class FifoPollInSchedulerRule(ProgramRule):
    """Scheduler grant loops must consult the scoring seam (rule 17).

    ISSUE 17 replaced the service's admission-order job polling with a
    scored candidate order (``_sched_order``: priority class, phase
    criticality, worker recent-job affinity). The shipped-bug shape is
    the old ``JobService.get_task``: a ``for job in <running …>:`` loop
    inside a scheduler-named scope that calls the per-phase grant RPCs
    directly — admission order silently decides fleet placement again,
    reintroducing the barrier bubbles the pipeline scheduler exists to
    fill, and the regression is invisible (every output stays correct,
    only ``fleet_bubble_frac`` drifts up). Sanctioned shape: the scope
    consults the seam — mentions ``_sched_order``/``sched_pipeline`` or
    a score — anywhere in its body; FIFO-as-oracle then lives INSIDE the
    seam, not beside it.
    """

    name = "fifo-poll-in-scheduler"
    summary = ("scheduler grant loops must consult the scoring seam, "
               "not admission order")

    _GRANTS = ("get_map_task", "get_reduce_task")
    _SEAMS = ("_sched_order", "sched_order", "sched_pipeline")

    @staticmethod
    def _scheduler_scope(fu) -> bool:
        q = fu.qualname.lower()
        return "sched" in q or q.rsplit(".", 1)[-1] == "get_task"

    def run_program(self, program):
        for fu in program.functions:
            if not self._scheduler_scope(fu):
                continue
            if any(_mentions(fu.node, s) for s in self._SEAMS) \
                    or _mentions(fu.node, "score", substring=True):
                continue
            for n in ast.walk(fu.node):
                if not isinstance(n, (ast.For, ast.AsyncFor)):
                    continue
                if not _mentions(n.iter, "running", substring=True):
                    continue
                if not any(
                    isinstance(c, ast.Call)
                    and isinstance(c.func, ast.Attribute)
                    and c.func.attr in self._GRANTS
                    for c in ast.walk(n)
                ):
                    continue
                yield self.finding(
                    fu.path, n,
                    f"{fu.qualname} grants tasks in admission order — a "
                    "`for … in running` poll loop that never consults "
                    "the scoring seam; iterate _sched_order(wid) "
                    "(priority, phase criticality, worker affinity) so "
                    "one job's map windows can fill another's barrier "
                    "bubbles, with FIFO kept as a mode inside the seam",
                )
                break  # one finding per scope names the class of bug


class RpcArgCompatRule(ProgramRule):
    """Every parameter of an RPC handler beyond its first operand must be
    trailing-with-default.

    Incident class: the coordinator/service wire protocol is positional
    JSON-RPC frames from workers of MIXED vintages — a rolling fleet
    restart always has old workers calling new servers. The shipped
    handlers grew ``wid=-1``, ``sample=None``, ``job=None`` one at a time
    precisely so an old caller's shorter frame still binds; ONE required
    parameter added mid-signature and every pre-upgrade worker's
    ``renew_map_lease(tid, wid)`` dies server-side as a TypeError that
    telemetry records as a stale renewal storm. The RPC surface is
    whatever the class's own ``_METHODS`` table exports — the rule reads
    that table, so a new handler is covered the moment it is wired.
    """

    name = "rpc-arg-compat"
    summary = ("RPC handler params beyond the first must be "
               "trailing-with-default (mixed-vintage wire compat)")

    @staticmethod
    def _methods_literal(cls: ast.ClassDef) -> "set[str] | None":
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "_METHODS"
                    for t in stmt.targets):
                names = {
                    n.value for n in ast.walk(stmt.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                }
                return names or None
        return None

    def run_program(self, program):
        for path, tree in program.files:
            for cls in ast.walk(tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                methods = self._methods_literal(cls)
                if not methods:
                    continue
                for fn in cls.body:
                    if not isinstance(fn, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                        continue
                    if fn.name not in methods:
                        continue
                    yield from self._check_signature(path, cls, fn)

    def _check_signature(self, path, cls, fn):
        a = fn.args
        pos = list(a.posonlyargs) + list(a.args)
        if pos and pos[0].arg in ("self", "cls"):
            pos = pos[1:]
        required = len(pos) - len(a.defaults)
        for i, arg in enumerate(pos):
            if 1 <= i < required:
                yield self.finding(
                    path, arg,
                    f"RPC handler {cls.name}.{fn.name} parameter "
                    f"{arg.arg!r} is required — a positional wire frame "
                    "from a pre-upgrade worker omits it and the call "
                    "dies as a server-side TypeError; new RPC params "
                    "must be trailing-with-default",
                )
        for arg, default in zip(a.kwonlyargs, a.kw_defaults):
            if default is None:
                yield self.finding(
                    path, arg,
                    f"RPC handler {cls.name}.{fn.name} keyword-only "
                    f"parameter {arg.arg!r} has no default — positional "
                    "wire frames can never supply it, so every caller "
                    "of any vintage fails; give it a default",
                )


class UnnamedPlaneThreadRule(Rule):
    """Plane threads must be named at creation (``name=`` /
    ``thread_name_prefix=``).

    Incident: ISSUE 19's sampling profiler attributes collapsed stacks
    by thread name, and the sanitizer's ownership messages print thread
    names — but the ingest producer and the ingest scan pool rendered as
    ``Thread-N``/``ThreadPoolExecutor-0_1``, so their samples landed in
    the unattributable ``other`` plane and ownership reports named
    nobody. Satellite 1 put every plane thread on the stable ``mr/``
    scheme; this rule keeps the next thread on it. Scoped to the
    installed package: test harness threads don't feed profiles.
    """

    name = "unnamed-plane-thread"
    summary = "threading.Thread/ThreadPoolExecutor in the package needs " \
              "name=/thread_name_prefix="

    def run(self, tree, src, path):
        parts = path.replace("\\", "/").split("/")
        if "mapreduce_rust_tpu" not in parts:
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _last_segment(qualname(node.func))
            if fn == "Thread" and _kw(node, "name") is None:
                yield self.finding(
                    path, node,
                    "threading.Thread without name= — the profiler "
                    "attributes samples by thread name and the sanitizer "
                    "names owners; use the mr/ plane scheme "
                    "(mr/scan-0, mr/fold-2, mr/spill-acc, mr/dispatch)",
                )
            elif (fn == "ThreadPoolExecutor"
                    and _kw(node, "thread_name_prefix") is None):
                yield self.finding(
                    path, node,
                    "ThreadPoolExecutor without thread_name_prefix= — "
                    "its workers render as ThreadPoolExecutor-N_M and "
                    "profile into the unattributable 'other' plane; "
                    "use the mr/ plane scheme",
                )


class AdHocCorpusDigestRule(Rule):
    """Corpus/chunk bytes get hashed through the lineage seam, not
    ad-hoc hashlib calls.

    Incident: ISSUE 20's provenance plane keys everything — forward and
    backward queries, the blast-radius diff, the service result-cache
    cross-check — on ONE pair of digest definitions
    (``runtime.lineage.chunk_digest`` over raw chunk bytes,
    ``corpus_fingerprint`` over name:size:mtime metadata). A second
    ad-hoc digest of the same bytes elsewhere drifts independently
    (different algorithm, different truncation, pre- vs post-
    normalization bytes) and the planes silently stop agreeing: a cache
    hit keyed one way can't be cross-checked against a ledger keyed the
    other. Scoped to the installed package; the lineage module itself
    and the service's ``scan_corpus`` seam (which IS the metadata
    fingerprint) are the two legitimate homes.
    """

    name = "ad-hoc-corpus-digest"
    summary = "hashlib over corpus/chunk bytes outside the " \
              "runtime.lineage digest seam"

    CTORS = {"blake2b", "sha256", "sha1", "md5", "sha512", "sha3_256"}
    HOT = ("chunk", "window", "payload", "corpus")
    EXEMPT_FUNCS = {"scan_corpus", "scan_corpus_spec"}

    def _hot_arg(self, node) -> "str | None":
        """First plain Name in the subtree whose id smells like corpus
        bytes. Names only — attribute mentions like cfg.chunk_bytes are
        shape knobs feeding config fingerprints, not the bytes
        themselves."""
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                low = n.id.lower()
                if any(w in low for w in self.HOT):
                    return n.id
        return None

    def run(self, tree, src, path):
        parts = path.replace("\\", "/").split("/")
        if "mapreduce_rust_tpu" not in parts:
            return
        if "/".join(parts[-2:]) == "runtime/lineage.py":
            return
        exempt: set[int] = set()
        hashed: set[str] = set()
        for node in ast.walk(tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name in self.EXEMPT_FUNCS):
                exempt.update(id(n) for n in ast.walk(node))
            elif (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _last_segment(
                        qualname(node.value.func)) in self.CTORS):
                hashed.update(t.id for t in node.targets
                              if isinstance(t, ast.Name))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or id(node) in exempt:
                continue
            fn = _last_segment(qualname(node.func))
            is_ctor = fn in self.CTORS
            is_update = (
                fn == "update" and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in hashed
            )
            if not (is_ctor or is_update) or not node.args:
                continue
            hot = self._hot_arg(node.args[0])
            if hot is None:
                continue
            yield self.finding(
                path, node,
                f"ad-hoc {fn}(...{hot}...) digest of corpus/chunk bytes "
                "— every plane keys on the lineage seam; use "
                "runtime.lineage.chunk_digest for content or "
                "corpus_fingerprint for file metadata so digests stay "
                "comparable across the ledger, the result cache, and "
                "the coordinator journal",
            )


ALL_RULES: list[Rule] = [
    StatsOwnershipRule(),
    ExecutorTeardownRule(),
    TmpdirCleanupRule(),
    DonationSafetyRule(),
    A2APurityRule(),
    SpanBalanceRule(),
    SpilledDictApiRule(),
    JitInLoopRule(),
    PsumReplicatedFlagRule(),
    UnboundedRetryRule(),
    MetricInHotLoopRule(),
    NakedClockInControlPlaneRule(),
    UnnamedPlaneThreadRule(),
    AdHocCorpusDigestRule(),
]

#: Interprocedural rules: run once per lint over the whole file set, on
#: the shared dataflow layer. Kept separate so ``lint_file`` (single-file
#: consumers, fixture tests) stays cheap and self-contained.
PROGRAM_RULES: list[ProgramRule] = [
    BlockingInAsyncRule(),
    BackendInitInProbeRule(),
    NondeterministicPartitionRule(),
    CrossShardFoldRule(),
    BlockingIoInFoldRule(),
    DeviceDispatchInConsumerRule(),
    UnsampledRangePartitionRule(),
    UnreapedJobLabelsRule(),
    FifoPollInSchedulerRule(),
    RpcArgCompatRule(),
]
