"""Deterministic fault injection for the control plane (ISSUE 6 piece 2).

Every recovery path this framework ships — lease expiry, re-execution,
idempotent finishes, speculative attempts, revocation, the late-report
path — used to be tested by timing luck: a test killed a worker and hoped
the kill landed inside the window it meant to exercise. This module makes
faults first-class: a **spec string** names seeded, reproducible faults at
named worker sites, carried by ``Config.chaos`` / ``MR_CHAOS=<spec>`` /
``run|worker --chaos``.

Spec grammar (elements separated by ``;``)::

    spec  := elem (';' elem)*
    elem  := 'seed=' INT | fault
    fault := SITE ':' ARG (':' ARG)*

Sites and their positional args (PHASE is ``map``/``reduce``/``*``; TID is
an int or ``*``; SECONDS a float)::

    pause:PHASE:TID:SECONDS      sleep before sending the finish report —
                                 the slow-but-ALIVE straggler (renewals
                                 keep flowing; only speculation or
                                 patience recovers this one)
    kill:PHASE:TID               SIGKILL this process mid-task (lease
                                 expiry + re-execution recovers)
    drop_finish:PHASE:TID        suppress the finish-report RPC (the task
                                 completed; the coordinator never hears —
                                 lease expiry re-executes, the journal
                                 dedups)
    delay_finish:PHASE:TID:SECONDS  delay the finish-report RPC (late-
                                 report race against the lease detector)
    wedge_renewal:PHASE:TID      stop heartbeats for the attempt while the
                                 task keeps computing (wedged renewal
                                 thread: lease expires under a live task)
    slow_scan:wWID:SECONDS       worker WID computes SECONDS slower per
                                 task (the heterogeneous-fleet straggler
                                 the doctor flags and speculation beats)
    slow_disk:SECONDS            every spill-run write sleeps SECONDS
                                 first (runtime/spill.py — one checkpoint
                                 covers dictionary AND accumulator tiers;
                                 ``p=`` samples runs by seeded hash of the
                                 run index). The slow-disk straggler the
                                 ASYNC spill writer hides behind compute
                                 while the sync plane stalls per run —
                                 bench.py --chaos measures exactly that
                                 pair (ISSUE 11)
    slow_dispatch:SECONDS        every device-merge dispatch sleeps
                                 SECONDS first (runtime/driver.py — ONE
                                 checkpoint in the dispatch plane, firing
                                 per packed merge; ``p=`` samples by
                                 seeded hash of the dispatch index). The
                                 slow-device-hop straggler the ASYNC
                                 dispatch plane hides on its own thread
                                 while --sync-dispatch eats every delay
                                 on the router's wall — bench.py --chaos
                                 measures exactly that pair (ISSUE 13)

Trailing ``KEY=VAL`` args refine any fault: ``attempt=N`` (default 1 —
a fault that re-fired on the recovery attempt would loop forever; ``*``
matches every attempt) and ``p=P`` (with ``tid=*``: fire on the fraction P
of tasks, chosen by a **seeded hash** of (seed, site, phase, tid, attempt)
so the same seed always picks the same victims).

Pure stdlib, no jax — importable from any control-plane process.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

SITES = (
    "pause", "kill", "drop_finish", "delay_finish", "wedge_renewal",
    "slow_scan", "slow_disk", "slow_dispatch",
)
_NEEDS_SECONDS = ("pause", "delay_finish", "slow_scan", "slow_disk",
                  "slow_dispatch")

#: Canonical scenario specs shared by ``bench.py --chaos`` and the chaos
#: test suite — one copy, so the benched and the tested faults are the
#: same faults. Keyed by scenario name; every spec is seeded. The
#: wedge_renewal scenario pairs the wedge with a pause: a task that
#: finishes inside its lease would make the dead heartbeat unobservable —
#: the pause keeps the task alive past expiry, so the recovery under test
#: (lease expiry beneath a LIVE task + its late report) actually runs.
SCENARIOS: dict[str, str] = {
    "pause": "seed=1;pause:map:0:1.2",
    "kill": "seed=2;kill:map:1",
    "drop_finish": "seed=3;drop_finish:reduce:0",
    "wedge_renewal": "seed=4;wedge_renewal:map:0;pause:map:0:3.0",
    "slow_scan": "seed=5;slow_scan:w0:2.5",
    # Fires only where a spill tier engages (the cluster legs run
    # unbudgeted, so there it is a fault-free control); the bench's
    # dedicated --chaos slow-disk pair runs it against a BUDGETED job,
    # async vs sync, to measure what the background writer hides.
    "slow_disk": "seed=6;slow_disk:0.05",
    # Fires on every packed device merge the host engine dispatches
    # (cluster workers run map_engine='host'); the bench's dedicated
    # --chaos slow-dispatch pair runs it async-vs-sync against a real
    # window stream to measure what the dispatch thread hides (ISSUE 13).
    "slow_dispatch": "seed=7;slow_dispatch:0.02",
}


@dataclasses.dataclass
class Fault:
    site: str
    phase: str | None = None   # "map" | "reduce" | None (= "*")
    tid: int | None = None     # None = "*"
    wid: int | None = None     # slow_scan target
    seconds: float = 0.0
    attempt: int | None = 1    # None = every attempt
    p: float | None = None     # seeded sampling fraction (tid=* only)

    def matches(self, seed: int, site: str, phase=None, tid=None,
                attempt=None, wid=None) -> bool:
        if site != self.site:
            return False
        if self.phase is not None and phase != self.phase:
            return False
        if self.tid is not None and tid != self.tid:
            return False
        if self.wid is not None and wid != self.wid:
            return False
        if self.attempt is not None and attempt is not None \
                and attempt != self.attempt:
            return False
        if self.p is not None:
            # Seeded hash, not random(): the same (seed, site, phase, tid,
            # attempt) always decides the same way — reruns reproduce.
            h = hashlib.sha256(
                f"{seed}:{site}:{phase}:{tid}:{attempt}".encode()
            ).digest()
            if int.from_bytes(h[:8], "big") / 2**64 >= self.p:
                return False
        return True


class ChaosPlan:
    """A parsed spec: ``pick()`` is the single injection checkpoint the
    worker calls at each site; it returns the matching :class:`Fault` (or
    None) and records every trigger so the run manifest can list exactly
    which faults fired."""

    def __init__(self, seed: int, faults: list[Fault], spec: str) -> None:
        self.seed = seed
        self.faults = faults
        self.spec = spec
        self.events: list[dict] = []

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        seed = 0
        faults: list[Fault] = []
        for raw in spec.split(";"):
            elem = raw.strip()
            if not elem:
                continue
            if elem.startswith("seed="):
                try:
                    seed = int(elem[5:])
                except ValueError:
                    raise ValueError(f"chaos: bad seed in {elem!r}") from None
                continue
            parts = elem.split(":")
            site = parts[0]
            if site not in SITES:
                raise ValueError(
                    f"chaos: unknown site {site!r} (sites: {', '.join(SITES)})"
                )
            pos: list[str] = []
            kw: dict[str, str] = {}
            for a in parts[1:]:
                if "=" in a:
                    k, v = a.split("=", 1)
                    kw[k] = v
                else:
                    if kw:
                        raise ValueError(
                            f"chaos: positional arg after key=val in {elem!r}"
                        )
                    pos.append(a)
            faults.append(cls._build(site, pos, kw, elem))
        if not faults:
            raise ValueError(f"chaos: no faults in spec {spec!r}")
        return cls(seed, faults, spec)

    @staticmethod
    def _build(site: str, pos: list[str], kw: dict, elem: str) -> Fault:
        def bad(msg: str) -> ValueError:
            return ValueError(f"chaos: {msg} in {elem!r}")

        f = Fault(site=site)
        try:
            if site == "slow_scan":
                if len(pos) != 2 or not pos[0].startswith("w"):
                    raise bad("slow_scan needs wWID:SECONDS")
                f.wid = int(pos[0][1:])
                f.seconds = float(pos[1])
                f.attempt = None  # a slow worker is slow on EVERY attempt
            elif site in ("slow_disk", "slow_dispatch"):
                if len(pos) != 1:
                    raise bad(f"{site} needs SECONDS")
                f.seconds = float(pos[0])
                f.attempt = None  # a slow disk/device hop is slow on
                # EVERY run write / merge dispatch
            else:
                want = 3 if site in _NEEDS_SECONDS else 2
                if len(pos) != want:
                    raise bad(f"{site} needs {want} positional args")
                if pos[0] not in ("map", "reduce", "*"):
                    raise bad(f"bad phase {pos[0]!r}")
                f.phase = None if pos[0] == "*" else pos[0]
                f.tid = None if pos[1] == "*" else int(pos[1])
                if site in _NEEDS_SECONDS:
                    f.seconds = float(pos[2])
        except ValueError as e:
            if str(e).startswith("chaos:"):
                raise
            raise bad(f"bad number ({e})") from None
        for k, v in kw.items():
            try:
                if k == "attempt":
                    f.attempt = None if v == "*" else int(v)
                elif k == "p":
                    f.p = float(v)
                    if not 0.0 < f.p <= 1.0:
                        raise bad("p must be in (0, 1]")
                else:
                    raise bad(f"unknown key {k!r}")
            except ValueError as e:
                if str(e).startswith("chaos:"):
                    raise
                raise bad(f"bad number for {k}= ({e})") from None
        if f.seconds < 0:
            raise bad("seconds must be >= 0")
        return f

    @classmethod
    def from_config(cls, cfg) -> "ChaosPlan | None":
        """The worker's entry point: MR_CHAOS (process-tree enablement,
        like MR_SANITIZE) beats Config.chaos; None when neither is set."""
        spec = os.environ.get("MR_CHAOS") or getattr(cfg, "chaos", None)
        return cls.parse(spec) if spec else None

    def pick(self, site: str, phase=None, tid=None, attempt=None,
             wid=None) -> "Fault | None":
        for f in self.faults:
            if f.matches(self.seed, site, phase=phase, tid=tid,
                         attempt=attempt, wid=wid):
                self.events.append({
                    "site": site, "phase": phase, "tid": tid,
                    "attempt": attempt, "wid": wid,
                    "seconds": f.seconds or None,
                })
                return f
        return None

    def fired(self) -> list[dict]:
        """Every fault that actually triggered, in order — the manifest's
        honest record of what this run was subjected to."""
        return list(self.events)


def build_spec(seed: int, faults: "list[str]") -> str:
    """Render fault elements into one canonical seeded spec — the export
    side of the grammar (mrmodel's counterexample → chaos repro). The
    result is round-tripped through :meth:`ChaosPlan.parse` before it is
    returned: a malformed export is a bug in the exporter, and it fails
    HERE, not in the worker that later replays the repro."""
    elems: list[str] = []
    for f in faults:
        f = f.strip()
        if f and f not in elems:
            elems.append(f)
    if not elems:
        raise ValueError("chaos: build_spec needs at least one fault")
    spec = ";".join([f"seed={int(seed)}"] + elems)
    ChaosPlan.parse(spec)
    return spec
